"""Flight recorder — a crash-safe ring of the most recent span/event
records, dumped to ``DK_OBS_DIR`` when something goes wrong.

The JSONL event log answers "what happened over the whole run"; the
flight recorder answers the incident question — "what were the last N
things this process did" — and guarantees that answer SURVIVES the
incident: a bounded in-memory ring (the ``timeseries.TimeSeries``
bounded-ring idiom, applied to whole records) holds the tail of the
event stream, and :func:`dump` writes it atomically as one JSON file
the moment a trigger fires:

- **watchdog alert transitions** (``watchdog.Watchdog.check`` dumps on
  every rule that starts firing, and stamps the dump path into the
  alert payload — so a ``DK_ALERT_CMD`` webhook line names the
  artifact, not just the symptom);
- **preemption** (the dispatch loop's boundary notice and the
  ``preemption.on_request`` watcher both dump before the drain);
- **unhandled crash** — :func:`attach` chains ``sys.excepthook`` and
  ``threading.excepthook``, so an exception nobody caught (on ANY
  thread) leaves a ``flightrec-*.json`` beside the event files
  (``SystemExit``/``KeyboardInterrupt`` are deliberate exits, not
  crashes — skipped);
- **on demand** via the ``/tracez`` endpoint both HTTP servers serve
  (:func:`tracez_doc`), or a direct :func:`dump` call.

The ring is attached by ``events._resolve`` exactly when ``DK_OBS_DIR``
selects an event log, so the zero-cost contract holds: recorder off =
no ring, no hooks, no per-emit work.  Ring capacity is
``DK_TRACE_RING`` records (default 2048); each record is the same dict
the event writer serialized, trace ids included — which is what makes a
set of dumps from different hosts stitchable by ``trace_id``
(:func:`read_dumps` + ``trace_export.chrome_trace``).
"""

from __future__ import annotations

import collections
import json
import os
import sys
import threading
import time

from dist_keras_tpu.observability import events, metrics
from dist_keras_tpu.utils import knobs

_DUMP_PREFIX = "flightrec"


class FlightRecorder:
    """Bounded ring of event records + atomic dump writer."""

    def __init__(self, capacity=None):
        if capacity is None:
            capacity = int(knobs.get("DK_TRACE_RING"))
        self.capacity = max(16, int(capacity))
        self._ring = collections.deque(maxlen=self.capacity)
        # one lock for append AND copy: deque.append alone is
        # thread-safe, but list(deque) raises "deque mutated during
        # iteration" against a concurrent appender — and a dump that
        # dies of that is lost exactly when the process is busiest.
        # An uncontended acquire is ~100ns against the µs-scale json
        # serialization each ringed record already paid.
        self._lock = threading.Lock()
        self._dump_seq = 0

    def record(self, rec):
        """Ring one record (the event writer's dict, post-serialize)."""
        with self._lock:
            self._ring.append(rec)

    def records(self):
        """Chronological copy of the retained records."""
        with self._lock:
            return list(self._ring)

    def __len__(self):
        return len(self._ring)

    def stats(self):
        return {"capacity": self.capacity, "n": len(self._ring),
                "dumps": self._dump_seq}

    def dump(self, reason, directory, rank, **fields):
        """Write the ring to ``<directory>/flightrec-rank_{r}-p{pid}-
        NNN-<reason>.json`` (tmp + rename, so a reader never sees a
        torn dump); -> the path.  The pid in the name keeps a
        supervised RELAUNCH into the same obs dir from overwriting the
        previous incarnation's post-mortem (same rank, fresh seq
        counter).  Raises on failure — :func:`dump` (module level) is
        the never-throws wrapper."""
        with self._lock:
            seq = self._dump_seq
            self._dump_seq += 1
        safe = "".join(c if c.isalnum() or c in "._-" else "_"
                       for c in str(reason)) or "dump"
        path = os.path.join(
            directory,
            f"{_DUMP_PREFIX}-rank_{rank}-p{os.getpid()}-{seq:03d}-"
            f"{safe}.json")
        doc = {"reason": str(reason), "t": time.time(), "rank": rank,
               "pid": os.getpid(), "fields": dict(fields),
               "n": len(self._ring), "records": self.records()}
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(doc, f, default=str)
        os.replace(tmp, path)
        return path


_lock = threading.Lock()
_recorder = None
_hooks_installed = False


def recorder():
    """The process-wide recorder (created on first use)."""
    global _recorder
    with _lock:
        if _recorder is None:
            _recorder = FlightRecorder()
        return _recorder


def attach():
    """Arm the recorder: register the events sink (every emitted record
    is ringed) and chain the crash hooks.  Called by ``events._resolve``
    when ``DK_OBS_DIR`` selects a writer; idempotent.  The sink is the
    module-level :func:`record` — it resolves ``recorder()`` per call,
    so a test's :func:`reset` swaps in a fresh ring without the sink
    feeding a discarded one."""
    events._sink = record
    _install_crash_hooks()


def record(rec):
    recorder().record(rec)


def dump(reason, **fields):
    """Dump the ring to the active ``DK_OBS_DIR``; -> the dump path, or
    None (log disabled, or the write failed — a recorder dump is a
    best-effort artifact and must NEVER add a failure to the incident
    it records).  Emits one ``flight_dump`` event naming the path and
    counts ``flight.dumps``."""
    d = events.obs_dir()
    if d is None:
        return None
    try:
        path = recorder().dump(reason, d, events.rank() or 0, **fields)
    # dklint: ignore[broad-except] a failed dump must not add a failure to the incident it records
    except Exception as e:
        print(f"[dk.observability] WARNING: flight dump ({reason}) "
              f"failed: {e!r}", file=sys.stderr, flush=True)
        return None
    metrics.counter("flight.dumps").inc()
    events.emit("flight_dump", reason=str(reason), path=path,
                n=len(recorder()), **fields)
    return path


def _install_crash_hooks():
    """Chain ``sys.excepthook`` + ``threading.excepthook`` so an
    UNHANDLED exception on any thread dumps the ring before the
    process (or thread) dies.  The previous hooks always run after —
    this is a recorder, not an error handler."""
    global _hooks_installed
    with _lock:
        if _hooks_installed:
            return
        _hooks_installed = True
    prev_sys = sys.excepthook
    prev_threading = threading.excepthook

    def _crash_dump(exc_type, exc, where):
        if issubclass(exc_type, (SystemExit, KeyboardInterrupt)):
            return  # deliberate exits (incl. Preempted) are not crashes
        dump("crash", error=exc_type.__name__,
             detail=str(exc)[:200], where=where)

    def _sys_hook(exc_type, exc, tb):
        _crash_dump(exc_type, exc, "main")
        prev_sys(exc_type, exc, tb)

    def _threading_hook(args):
        _crash_dump(args.exc_type, args.exc_value,
                    getattr(args.thread, "name", "?"))
        prev_threading(args)

    sys.excepthook = _sys_hook
    threading.excepthook = _threading_hook


def tracez_doc():
    """The ``/tracez`` payload: recorder stats + the retained records
    (JSON-ready — every record already round-tripped the writer's
    serializer)."""
    rec = recorder()
    return {"rank": events.rank(), "enabled": events.enabled(),
            **rec.stats(), "records": rec.records()}


def load_dump(path):
    """Read one dump file -> its document (the :func:`dump` schema)."""
    with open(path) as f:
        return json.load(f)


def dump_files(directory):
    """-> sorted paths of every ``flightrec-*.json`` under
    ``directory`` (including ``host_{i}/`` subdirs — the
    ``Job.collect_obs`` layout, same convention as
    ``report.event_files``)."""
    directory = os.path.abspath(os.path.expanduser(str(directory)))
    out = []
    roots = [directory]
    try:
        for name in os.listdir(directory):
            p = os.path.join(directory, name)
            if name.startswith("host_") and os.path.isdir(p):
                roots.append(p)
    except OSError:
        return []
    for root in roots:
        try:
            names = os.listdir(root)
        except OSError:
            continue
        out.extend(os.path.join(root, n) for n in names
                   if n.startswith(_DUMP_PREFIX + "-")
                   and n.endswith(".json"))
    return sorted(out)


def read_dumps(directory):
    """Merge every host's recorder dumps into ONE deduplicated timeline
    ordered by ``(t, rank, seq)`` — the stitching input for
    ``trace_export``.  Two dumps from one process overlap (the ring
    retains history across dumps); records are deduplicated by
    ``(pid, rank, seq)`` — seq is unique per event writer, and the
    dump's recorded pid distinguishes two INCARNATIONS of the same
    rank (a supervised relaunch restarts seq at 0; without the pid its
    records would vanish as false duplicates).  A torn/unreadable dump
    is skipped, not fatal — the merger must work best exactly when the
    run died worst."""
    seen = set()
    records = []
    for path in dump_files(directory):
        try:
            doc = load_dump(path)
        except (OSError, ValueError):
            continue
        pid = doc.get("pid")
        for rec in doc.get("records", ()):
            key = (pid, rec.get("rank", doc.get("rank", 0)),
                   rec.get("seq"))
            if key in seen:
                continue
            seen.add(key)
            records.append(rec)
    records.sort(key=lambda e: (e.get("t", 0.0), e.get("rank", 0),
                                e.get("seq", 0)))
    return records


def reset():
    """Drop the ring (tests).  The chained excepthooks stay installed
    and the installed flag stays set — re-chaining on every reset would
    stack hook frames; the hooks read the live recorder through
    :func:`dump`, so a fresh ring is all a test needs."""
    global _recorder
    with _lock:
        _recorder = None
