"""Flight recorder — a crash-safe ring of the most recent span/event
records, dumped to ``DK_OBS_DIR`` when something goes wrong.

The JSONL event log answers "what happened over the whole run"; the
flight recorder answers the incident question — "what were the last N
things this process did" — and guarantees that answer SURVIVES the
incident: a bounded in-memory ring (the ``timeseries.TimeSeries``
bounded-ring idiom, applied to whole records) holds the tail of the
event stream, and :func:`dump` writes it atomically as one JSON file
the moment a trigger fires:

- **watchdog alert transitions** (``watchdog.Watchdog.check`` dumps on
  every rule that starts firing, and stamps the dump path into the
  alert payload — so a ``DK_ALERT_CMD`` webhook line names the
  artifact, not just the symptom);
- **preemption** (the dispatch loop's boundary notice and the
  ``preemption.on_request`` watcher both dump before the drain);
- **unhandled crash** — :func:`attach` chains ``sys.excepthook`` and
  ``threading.excepthook``, so an exception nobody caught (on ANY
  thread) leaves a ``flightrec-*.json`` beside the event files
  (``SystemExit``/``KeyboardInterrupt`` are deliberate exits, not
  crashes — skipped);
- **on demand** via the ``/tracez`` endpoint both HTTP servers serve
  (:func:`tracez_doc`), or a direct :func:`dump` call.

The ring is attached by ``events._resolve`` exactly when ``DK_OBS_DIR``
selects an event log, so the zero-cost contract holds: recorder off =
no ring, no hooks, no per-emit work.  Ring capacity is
``DK_TRACE_RING`` records (default 2048); each record is the same dict
the event writer serialized, trace ids included — which is what makes a
set of dumps from different hosts stitchable by ``trace_id``
(:func:`read_dumps` + ``trace_export.chrome_trace``).
"""

from __future__ import annotations

import collections
import json
import os
import sys
import threading
import time

from dist_keras_tpu.observability import events, metrics
from dist_keras_tpu.utils import knobs

_DUMP_PREFIX = "flightrec"


class FlightRecorder:
    """Bounded ring of event records + atomic dump writer."""

    def __init__(self, capacity=None):
        if capacity is None:
            capacity = int(knobs.get("DK_TRACE_RING"))
        self.capacity = max(16, int(capacity))
        self._ring = collections.deque(maxlen=self.capacity)
        # one lock for append AND copy: deque.append alone is
        # thread-safe, but list(deque) raises "deque mutated during
        # iteration" against a concurrent appender — and a dump that
        # dies of that is lost exactly when the process is busiest.
        # An uncontended acquire is ~100ns against the µs-scale json
        # serialization each ringed record already paid.
        self._lock = threading.Lock()
        self._dump_seq = 0

    def record(self, rec):
        """Ring one record (the event writer's dict, post-serialize)."""
        with self._lock:
            self._ring.append(rec)

    def records(self):
        """Chronological copy of the retained records."""
        with self._lock:
            return list(self._ring)

    def __len__(self):
        return len(self._ring)

    def stats(self):
        return {"capacity": self.capacity, "n": len(self._ring),
                "dumps": self._dump_seq}

    def dump(self, reason, directory, rank, **fields):
        """Write the ring to ``<directory>/flightrec-rank_{r}-p{pid}-
        NNN-<reason>.json`` (tmp + rename, so a reader never sees a
        torn dump); -> the path.  The pid in the name keeps a
        supervised RELAUNCH into the same obs dir from overwriting the
        previous incarnation's post-mortem (same rank, fresh seq
        counter).  Raises on failure — :func:`dump` (module level) is
        the never-throws wrapper."""
        with self._lock:
            seq = self._dump_seq
            self._dump_seq += 1
        safe = "".join(c if c.isalnum() or c in "._-" else "_"
                       for c in str(reason)) or "dump"
        path = os.path.join(
            directory,
            f"{_DUMP_PREFIX}-rank_{rank}-p{os.getpid()}-{seq:03d}-"
            f"{safe}.json")
        doc = {"reason": str(reason), "t": time.time(), "rank": rank,
               "pid": os.getpid(), "fields": dict(fields),
               "n": len(self._ring), "records": self.records()}
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(doc, f, default=str)
        os.replace(tmp, path)
        return path


# --- tail-based trace retention (round 22) -------------------------
#
# Event kinds that belong to a per-request trace: these are the
# records retention may buffer (everything else — lifecycle events,
# perf samples, alerts — always writes through immediately).
_RETAIN_KINDS = frozenset({
    "span_begin", "span_end", "serve_enqueue", "serve_batch_flush",
    "serve_batch_error", "serve_predict_error",
})
# Span paths whose ``span_end`` marks a request's END — the tail-based
# decision point for that trace's local buffer.  Matched on the dotted
# path suffix so a root nested under an outer span still decides.
_ROOT_SPANS = ("serve.request", "serve.client", "route.forward")


def _is_root_end(rec):
    if rec.get("kind") != "span_end":
        return False
    path = str(rec.get("span", ""))
    return any(path == r or path.endswith("." + r) for r in _ROOT_SPANS)


class TraceRetention:
    """Keep full span records only for requests worth keeping.

    Buffers trace-stamped records per ``trace_id`` (custody taken from
    the event writer via ``events._set_retainer``) and decides at
    request END — the root span's ``span_end`` — whether to flush the
    buffer to the log or drop it:

    - **slow**: root duration >= ``slow_s`` (``DK_TRACE_RETAIN_SLOW_S``,
      defaulting to the SLO latency bar ``DK_SLO_LATENCY_S``) — every
      objective-breaching request keeps its complete trace;
    - **errored**: any buffered record carries an ``error`` field or an
      error kind;
    - **head-sampled**: a pure hash of the trace id falls under
      ``DK_TRACE_SAMPLE`` — a deterministic healthy-traffic baseline
      (replays keep the same traces; no RNG).

    Everything else is dropped (counted, not logged), so steady
    healthy traffic stops growing the event log linearly.  The
    in-flight buffer is bounded by ``DK_TRACE_RETAIN_BUDGET`` traces;
    past the budget the OLDEST buffer is flushed — fail OPEN: memory
    pressure can only make retention keep more, never lose an
    incident's trace.  :func:`dump` flushes all in-flight buffers
    first, so an alert/crash artifact always includes the traces that
    were still in progress.
    """

    def __init__(self, slow_s=None, sample=None, budget=None):
        if slow_s is None:
            slow_s = knobs.get("DK_TRACE_RETAIN_SLOW_S")
            if slow_s is None:
                slow_s = knobs.get("DK_SLO_LATENCY_S")
        self.slow_s = float(slow_s)
        self.sample = float(knobs.get("DK_TRACE_SAMPLE")
                            if sample is None else sample)
        self.budget = max(1, int(knobs.get("DK_TRACE_RETAIN_BUDGET")
                                 if budget is None else budget))
        self._buf = collections.OrderedDict()  # trace_id -> [records]
        self._writer = None
        self._lock = threading.Lock()

    def offer(self, rec, writer):
        """The ``events`` seam: -> True when custody of ``rec`` is
        taken (buffered or decided here), False to write through."""
        if rec.get("kind") not in _RETAIN_KINDS:
            return False
        tid = rec.get("trace_id")
        if not tid:
            return False
        self._writer = writer
        evicted = decided = None
        with self._lock:
            buf = self._buf.get(tid)
            if buf is None:
                if len(self._buf) >= self.budget:
                    _, evicted = self._buf.popitem(last=False)
                buf = self._buf[tid] = []
            buf.append(rec)
            if _is_root_end(rec):
                decided = self._buf.pop(tid)
            inflight = len(self._buf)
        if evicted is not None:
            # budget overflow: fail open — flush, never drop unseen
            metrics.counter("trace.retained").inc()
            self._flush(evicted, writer)
        if decided is not None:
            if self._keep(decided, rec):
                metrics.counter("trace.retained").inc()
                self._flush(decided, writer)
            else:
                metrics.counter("trace.dropped").inc()
                metrics.counter("trace.dropped_records").inc(
                    len(decided))
        metrics.gauge("trace.inflight").set(inflight)
        return True

    def _keep(self, records, root_rec):
        try:
            dur = float(root_rec.get("duration_s") or 0.0)
        except (TypeError, ValueError):
            dur = 0.0
        if dur >= self.slow_s:
            return True
        for r in records:
            if "error" in r or "error" in str(r.get("kind", "")):
                return True
        if self.sample > 0.0:
            try:
                h = int(str(root_rec.get("trace_id", ""))[:8], 16)
            except ValueError:
                h = 0
            if h / 0xFFFFFFFF < self.sample:
                return True
        return False

    def _flush(self, records, writer):
        sink = events._sink
        for r in records:
            writer.write(r)
            if sink is not None:
                sink(r)

    def flush_all(self):
        """Flush every in-flight buffer (drain / incident dump /
        process teardown): undecided traces are retained — fail open.
        Never throws; -> the number of records flushed."""
        with self._lock:
            bufs = list(self._buf.values())
            self._buf.clear()
        w, n = self._writer, 0
        for records in bufs:
            if w is None:
                break
            try:
                self._flush(records, w)
                metrics.counter("trace.retained").inc()
                n += len(records)
            # dklint: ignore[broad-except] a failed flush must not add a failure to the drain/incident path
            except Exception as e:
                print(f"[dk.observability] WARNING: trace retention "
                      f"flush failed: {e!r}", file=sys.stderr,
                      flush=True)
                break
        metrics.gauge("trace.inflight").set(0)
        return n

    def stats(self):
        with self._lock:
            inflight = len(self._buf)
        return {"inflight": inflight, "slow_s": self.slow_s,
                "sample": self.sample, "budget": self.budget}


_lock = threading.Lock()
_recorder = None
_retention = None
_hooks_installed = False


def recorder():
    """The process-wide recorder (created on first use)."""
    global _recorder
    with _lock:
        if _recorder is None:
            _recorder = FlightRecorder()
        return _recorder


def attach():
    """Arm the recorder: register the events sink (every emitted record
    is ringed) and chain the crash hooks.  Called by ``events._resolve``
    when ``DK_OBS_DIR`` selects a writer; idempotent.  The sink is the
    module-level :func:`record` — it resolves ``recorder()`` per call,
    so a test's :func:`reset` swaps in a fresh ring without the sink
    feeding a discarded one.  When ``DK_TRACE_RETAIN`` is armed this
    also installs the tail-based :class:`TraceRetention` policy into
    the event seam."""
    global _retention
    events._sink = record
    if knobs.get("DK_TRACE_RETAIN"):
        with _lock:
            if _retention is None:
                _retention = TraceRetention()
        events._set_retainer(_retention.offer)
    _install_crash_hooks()


def retention():
    """The active :class:`TraceRetention` policy, or None when
    ``DK_TRACE_RETAIN`` is off."""
    return _retention


def retain_flush():
    """Flush every in-flight retention buffer to the event log (drain
    paths, incident dumps); no-op when retention is off.  -> records
    flushed."""
    r = _retention
    return r.flush_all() if r is not None else 0


def record(rec):
    recorder().record(rec)


def dump(reason, **fields):
    """Dump the ring to the active ``DK_OBS_DIR``; -> the dump path, or
    None (log disabled, or the write failed — a recorder dump is a
    best-effort artifact and must NEVER add a failure to the incident
    it records).  Emits one ``flight_dump`` event naming the path and
    counts ``flight.dumps``."""
    d = events.obs_dir()
    if d is None:
        return None
    # flush in-flight retention buffers FIRST: the incident's own
    # trace is usually still undecided at alert time, and a dump that
    # lost it would defeat the whole "every incident keeps its trace"
    # contract
    retain_flush()
    try:
        path = recorder().dump(reason, d, events.rank() or 0, **fields)
    # dklint: ignore[broad-except] a failed dump must not add a failure to the incident it records
    except Exception as e:
        print(f"[dk.observability] WARNING: flight dump ({reason}) "
              f"failed: {e!r}", file=sys.stderr, flush=True)
        return None
    metrics.counter("flight.dumps").inc()
    events.emit("flight_dump", reason=str(reason), path=path,
                n=len(recorder()), **fields)
    return path


def _install_crash_hooks():
    """Chain ``sys.excepthook`` + ``threading.excepthook`` so an
    UNHANDLED exception on any thread dumps the ring before the
    process (or thread) dies.  The previous hooks always run after —
    this is a recorder, not an error handler."""
    global _hooks_installed
    with _lock:
        if _hooks_installed:
            return
        _hooks_installed = True
    prev_sys = sys.excepthook
    prev_threading = threading.excepthook

    def _crash_dump(exc_type, exc, where):
        if issubclass(exc_type, (SystemExit, KeyboardInterrupt)):
            return  # deliberate exits (incl. Preempted) are not crashes
        dump("crash", error=exc_type.__name__,
             detail=str(exc)[:200], where=where)

    def _sys_hook(exc_type, exc, tb):
        _crash_dump(exc_type, exc, "main")
        prev_sys(exc_type, exc, tb)

    def _threading_hook(args):
        _crash_dump(args.exc_type, args.exc_value,
                    getattr(args.thread, "name", "?"))
        prev_threading(args)

    sys.excepthook = _sys_hook
    threading.excepthook = _threading_hook


def tracez_doc():
    """The ``/tracez`` payload: recorder stats + the retained records
    (JSON-ready — every record already round-tripped the writer's
    serializer)."""
    rec = recorder()
    r = _retention
    return {"rank": events.rank(), "enabled": events.enabled(),
            **rec.stats(),
            "retention": r.stats() if r is not None else None,
            "records": rec.records()}


def load_dump(path):
    """Read one dump file -> its document (the :func:`dump` schema)."""
    with open(path) as f:
        return json.load(f)


def dump_files(directory):
    """-> sorted paths of every ``flightrec-*.json`` under
    ``directory`` (including ``host_{i}/`` subdirs — the
    ``Job.collect_obs`` layout, same convention as
    ``report.event_files``)."""
    directory = os.path.abspath(os.path.expanduser(str(directory)))
    out = []
    roots = [directory]
    try:
        for name in os.listdir(directory):
            p = os.path.join(directory, name)
            if name.startswith("host_") and os.path.isdir(p):
                roots.append(p)
    except OSError:
        return []
    for root in roots:
        try:
            names = os.listdir(root)
        except OSError:
            continue
        out.extend(os.path.join(root, n) for n in names
                   if n.startswith(_DUMP_PREFIX + "-")
                   and n.endswith(".json"))
    return sorted(out)


def read_dumps(directory):
    """Merge every host's recorder dumps into ONE deduplicated timeline
    ordered by ``(t, rank, seq)`` — the stitching input for
    ``trace_export``.  Two dumps from one process overlap (the ring
    retains history across dumps); records are deduplicated by
    ``(pid, rank, seq)`` — seq is unique per event writer, and the
    dump's recorded pid distinguishes two INCARNATIONS of the same
    rank (a supervised relaunch restarts seq at 0; without the pid its
    records would vanish as false duplicates).  A torn/unreadable dump
    is skipped, not fatal — the merger must work best exactly when the
    run died worst."""
    seen = set()
    records = []
    for path in dump_files(directory):
        try:
            doc = load_dump(path)
        except (OSError, ValueError):
            continue
        pid = doc.get("pid")
        for rec in doc.get("records", ()):
            key = (pid, rec.get("rank", doc.get("rank", 0)),
                   rec.get("seq"))
            if key in seen:
                continue
            seen.add(key)
            records.append(rec)
    records.sort(key=lambda e: (e.get("t", 0.0), e.get("rank", 0),
                                e.get("seq", 0)))
    return records


def reset():
    """Drop the ring (tests).  The chained excepthooks stay installed
    and the installed flag stays set — re-chaining on every reset would
    stack hook frames; the hooks read the live recorder through
    :func:`dump`, so a fresh ring is all a test needs."""
    global _recorder, _retention
    with _lock:
        _recorder = None
        _retention = None
