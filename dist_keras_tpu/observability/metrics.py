"""Process-wide metrics registry — named counters, gauges, histograms.

``utils/profiling.StepTimer`` sketched this in miniature (a list of
per-call durations with summary stats); this module grows it into the
registry every subsystem shares: trainers, ``comm.backend``,
``checkpoint``, ``resilience.retry`` and ``data.streaming`` register
named instruments here, and the whole registry snapshots to JSON at
epoch boundaries into the event stream (``events.py``), so a post-hoc
report can say "this run retried rsync 7 times and spent 12 s in
checkpoint saves" without anyone having threaded those numbers through
return values.

Design points:

- **Get-or-create by name** (:func:`counter` / :func:`gauge` /
  :func:`histogram`): call sites never coordinate registration order,
  and the same name from two modules is the same instrument.
- **Cheap always-on**: incrementing a counter is a lock + int add —
  safe on warm host-side paths (per-chunk, per-retry; NOT the compiled
  per-step device loop, which cannot host Python hooks).  File I/O only
  happens at explicit :func:`emit_snapshot` points, and only when
  ``DK_OBS_DIR`` is set.
- **Zero-length windows are guarded**: an empty histogram summarizes to
  ``count: 0`` with ``None`` stats instead of a numpy warning or a
  raise — the same convention ``StepTimer.summary`` now follows.
"""

from __future__ import annotations

import threading
import time

import numpy as np

from dist_keras_tpu.observability import events

# Exemplar capture (round 22): when the SLO plane is armed
# (``DK_SLO``), every histogram observation made under an open span
# records that span's ``(trace_id, span_id)`` in a small per-histogram
# ring, so a scrape's bad percentile links straight to a retained
# trace.  ``spans.py`` registers the provider at import (it already
# imports this module, so the hook avoids a metrics->spans cycle the
# same way ``events._set_context_provider`` does); the knob is read
# once and cached, keeping the disarmed observe path at two global
# loads.
_exemplar_provider = None   # () -> (trace_id, span_id) | None
_exemplars_on = None        # cached DK_SLO (tri-state: None = unknown)


def _set_exemplar_provider(fn):
    global _exemplar_provider
    _exemplar_provider = fn


def _exemplars_enabled():
    global _exemplars_on
    if _exemplars_on is None:
        from dist_keras_tpu.utils import knobs

        _exemplars_on = bool(knobs.get("DK_SLO"))
    return _exemplars_on


class Counter:
    """Monotonic named count (retry attempts, nonfinite steps, ...)."""

    def __init__(self, name):
        self.name = name
        self._value = 0
        self._lock = threading.Lock()

    def inc(self, n=1):
        with self._lock:
            self._value += n

    @property
    def value(self):
        return self._value


class Gauge:
    """Last-write-wins named value (resident bytes, world size, ...)."""

    def __init__(self, name):
        self.name = name
        self._value = None
        self._lock = threading.Lock()

    def set(self, value):
        with self._lock:
            self._value = value

    @property
    def value(self):
        return self._value


class Histogram:
    """Sample distribution with percentile summaries (durations).

    ``count`` / ``mean`` / ``total`` / ``max`` are EXACT over the whole
    lifetime (until :meth:`reset`); percentiles are computed over a
    bounded window of the most recent :data:`Histogram.WINDOW` samples,
    so a week-long run's memory stays flat and the epoch-boundary
    snapshot cost stays O(window) instead of growing quadratically with
    run length.  A recent window is also the operationally useful
    percentile — "what do saves cost *now*", not diluted by hour-one.
    """

    WINDOW = 4096
    EXEMPLARS = 8

    def __init__(self, name=None):
        import collections

        self.name = name
        self._window = collections.deque(maxlen=self.WINDOW)
        self._exemplars = collections.deque(maxlen=self.EXEMPLARS)
        self._count = 0
        self._total = 0.0
        self._max = None
        self._over = {}  # threshold -> cumulative count(value > thr)
        self._lock = threading.Lock()

    def observe(self, value, exemplar=None):
        """Record one sample.  ``exemplar``: optional ``(trace_id,
        span_id)`` linking this observation to a trace; when omitted
        and the SLO plane is armed, the current span's ids are
        captured automatically (provider registered by ``spans.py``).
        """
        value = float(value)
        if exemplar is None and _exemplar_provider is not None \
                and _exemplars_enabled():
            exemplar = _exemplar_provider()
        with self._lock:
            self._window.append(value)
            self._count += 1
            self._total += value
            if self._max is None or value > self._max:
                self._max = value
            for thr in self._over:
                if value > thr:
                    self._over[thr] += 1
            if exemplar is not None:
                self._exemplars.append(
                    (str(exemplar[0]), str(exemplar[1]), value,
                     time.time()))

    def track_over(self, threshold):
        """Start counting observations ABOVE ``threshold`` exactly
        (cumulative, like ``count``) — the latency-SLO seam: one float
        compare per observe once registered, zero when not."""
        thr = float(threshold)
        with self._lock:
            self._over.setdefault(thr, 0)

    def over(self, threshold):
        """Cumulative count of observations above a tracked threshold
        (0 for a threshold never registered)."""
        with self._lock:
            return self._over.get(float(threshold), 0)

    def exemplars(self):
        """-> recent exemplars, newest last:
        ``[{trace_id, span_id, value, t}, ...]``."""
        with self._lock:
            items = list(self._exemplars)
        return [{"trace_id": tid, "span_id": sid, "value": v, "t": t}
                for tid, sid, v, t in items]

    def reset(self):
        with self._lock:
            self._window.clear()
            self._exemplars.clear()
            self._count = 0
            self._total = 0.0
            self._max = None
            self._over = {thr: 0 for thr in self._over}

    @property
    def samples(self):
        """The retained (most recent) samples — the percentile window."""
        with self._lock:
            return list(self._window)

    def totals(self):
        """-> {count, total, max} — the exact lifetime aggregates,
        WITHOUT the percentile pass (no window copy, no numpy).  The
        sampler's per-tick path: at a sub-second ``DK_OBS_SAMPLE_S``
        cadence the full :meth:`summary` per histogram per tick is
        what would break the <5% overhead contract."""
        with self._lock:
            return {"count": self._count, "total": self._total,
                    "max": self._max}

    def summary(self):
        """-> {count, mean, p50, p95, p99, max, total}; a zero-length
        window returns ``count: 0`` with ``None`` stats (``total: 0.0``)
        instead of raising from the percentile math."""
        with self._lock:
            count, total, mx = self._count, self._total, self._max
            window = list(self._window)
        if count == 0:
            return {"count": 0, "mean": None, "p50": None, "p95": None,
                    "p99": None, "max": None, "total": 0.0}
        # one percentile pass for all three points (summary() runs at
        # every epoch-boundary snapshot — it is warm-path-adjacent)
        p50, p95, p99 = np.percentile(
            np.asarray(window, dtype=np.float64), (50, 95, 99))
        return {
            "count": count,
            "mean": total / count,
            "p50": float(p50),
            "p95": float(p95),
            "p99": float(p99),
            "max": mx,
            "total": total,
        }


# The metric vocabulary — every instrument name any seam registers,
# with its kind.  Entries containing ``*`` are fnmatch patterns for
# dynamic families (the call site carries a ``# dklint: metrics=<pat>``
# annotation naming its pattern).  Adding a counter/gauge/histogram?
# Register it here AND add a row to the README metrics table, or the
# ``metric-unregistered`` / ``metric-undocumented`` lint rules fail
# the tree; exact names must also stay collision-free after Prometheus
# sanitization (``metric-collision``).
KNOWN_METRICS = {
    # training
    "train.nonfinite_steps": "counter",
    # checkpointing (checkpoint.py): what the training loop actually
    # waited vs what the (possibly background) writer spent
    "ckpt.save_stall_s": "histogram",
    "ckpt.write_s": "histogram",
    # differential saves + remote tier (checkpoint.py,
    # resilience/store.py)
    "ckpt.chunks_skipped": "counter",
    "ckpt.bytes_pushed": "counter",
    "ckpt.remote_pruned": "counter",
    # streaming data plane
    "stream.batches": "counter",
    "stream.rows": "counter",
    # retry surfaces (resilience/retry.py — per-surface families)
    "*.retries": "counter",
    "*.exhausted": "counter",
    # supervisor
    "supervisor.restarts": "counter",
    "supervisor.giveups": "counter",
    # elastic resharding restore (resilience/elastic.py)
    "reshard.restores": "counter",
    "reshard.bytes": "counter",
    # serving
    "serve.enqueued": "counter",
    "serve.completed": "counter",
    "serve.rejected": "counter",
    "serve.errors": "counter",
    "serve.reloads": "counter",
    "serve.reload.skipped_corrupt": "counter",
    "serve.reload.errors": "counter",
    "serve.pending": "gauge",
    "serve.predict_s": "histogram",
    # serving router tier (serving/router.py, serving/reload.py,
    # serving/autoscale.py)
    "route.requests": "counter",
    "route.errors": "counter",
    "route.evictions": "counter",
    "route.readmissions": "counter",
    "route.cutovers": "counter",
    "route.backends_live": "gauge",
    "route.forward_s": "histogram",
    "autoscale.resizes": "counter",
    "autoscale.replicas": "gauge",
    # parameter-server training mode (ps/server.py)
    "ps.pulls": "counter",
    "ps.commits": "counter",
    "ps.joins": "counter",
    "ps.lapses": "counter",
    "ps.stale_scaled": "counter",
    "ps.rejected_stale": "counter",
    "ps.workers": "gauge",
    "ps.clock": "gauge",
    "ps.staleness": "histogram",
    # PS commit-delta compression (ps/worker.py): payload array bytes
    # before/after the DK_PS_COMPRESS codec — equal when it is off
    "ps.commit_bytes_raw": "counter",
    "ps.commit_bytes_wire": "counter",
    # perf attribution (observability/perf.py)
    "perf.retraces": "counter",
    "perf.traces": "counter",
    "perf.dispatches": "counter",
    "perf.h2d_bytes": "counter",
    "perf.d2h_bytes": "counter",
    "perf.compile_s": "histogram",
    "perf.h2d_s": "histogram",
    "perf.d2h_s": "histogram",
    "perf.phase.*": "histogram",
    # spans (observability/spans.py)
    "span.*": "histogram",
    # watchdog
    "watchdog.alerts": "counter",
    "watchdog.firing.*": "gauge",
    # flight recorder (observability/flight.py)
    "flight.dumps": "counter",
    # SLO plane (observability/slo.py): per-objective burn gauges —
    # slo.<objective>.burn_fast / .burn_slow / .firing
    "slo.*": "gauge",
    # tail-based trace retention (observability/flight.py)
    "trace.retained": "counter",
    "trace.dropped": "counter",
    "trace.dropped_records": "counter",
    "trace.inflight": "gauge",
    # cluster simulator (sim/)
    "sim.host_steps": "counter",
    "sim.faults": "counter",
    # continuous-batching decode engine (serving/decode.py)
    "decode.admitted": "counter",
    "decode.completed": "counter",
    "decode.rejected": "counter",
    "decode.errors": "counter",
    "decode.cancelled": "counter",
    "decode.tokens": "counter",
    "decode.ttft_s": "histogram",
    "decode.step_s": "histogram",
    "decode.active": "gauge",
    "decode.kv_used_pages": "gauge",
    # decode survivability plane (serving/decode.py): quarantine +
    # sequence recovery, deadline admission/expiry, brownout shedding
    # (shed is deliberately NOT folded into decode.rejected — the
    # generate_tokens SLO reads rejected, and a shed that burned the
    # SLO would amplify itself), and the periodic allocator self-check
    "decode.quarantines": "counter",
    "decode.recovered": "counter",
    "decode.shed": "counter",
    "decode.deadline_infeasible": "counter",
    "decode.deadline_expired": "counter",
    "decode.kv_leaked": "counter",
    # router hedging (serving/router.py): hedged /generate forwards,
    # first-wins outcomes, and budget denials
    "route.hedges": "counter",
    "route.hedge_wins": "counter",
    "route.hedge_denied": "counter",
    "route.stream_errors": "counter",
}

_lock = threading.Lock()
_registry = {}  # name -> instrument


def _get(name, cls):
    with _lock:
        inst = _registry.get(name)
        if inst is None:
            inst = _registry[name] = cls(name)
        elif not isinstance(inst, cls):
            raise TypeError(
                f"metric {name!r} is already registered as "
                f"{type(inst).__name__}, not {cls.__name__}")
        return inst


def counter(name):
    return _get(str(name), Counter)


def gauge(name):
    return _get(str(name), Gauge)


def histogram(name):
    return _get(str(name), Histogram)


def snapshot(percentiles=True):
    """-> JSON-ready dict of every registered instrument's current
    value: ``{"counters": {...}, "gauges": {...}, "histograms":
    {name: summary}}``.  ``percentiles=False`` swaps each histogram's
    full summary for its cheap :meth:`Histogram.totals` (count/total/
    max only) — the sampler-tick variant, O(instruments) with no numpy
    pass, so a sub-second sampling cadence stays inside the <5%
    overhead contract."""
    with _lock:
        items = list(_registry.items())
    out = {"counters": {}, "gauges": {}, "histograms": {}}
    for name, inst in items:
        if isinstance(inst, Counter):
            out["counters"][name] = inst.value
        elif isinstance(inst, Gauge):
            out["gauges"][name] = inst.value
        else:
            h = inst.summary() if percentiles else inst.totals()
            if percentiles:
                ex = inst.exemplars()
                if ex:
                    h["exemplars"] = ex
            out["histograms"][name] = h
    return out


def emit_snapshot(**extra):
    """Write the registry snapshot into the event stream (one
    ``"metrics"`` event) — the epoch-boundary hook trainers call.
    No-op when ``DK_OBS_DIR`` is unset, and the snapshot itself is only
    computed when the emit will land."""
    if not events.enabled():
        return
    events.emit("metrics", **snapshot(), **extra)


def to_prometheus(**kw):
    """Prometheus text exposition (format 0.0.4) of the registry — the
    one scrape format the serving ``/metricsz?format=prometheus``
    endpoint and the standalone per-host exporter both serve.  Kwargs
    pass through to :func:`observability.prometheus.render` (lazy
    import keeps this module http-free)."""
    from dist_keras_tpu.observability import prometheus

    return prometheus.render(**kw)


def reset():
    """Drop every registered instrument (tests)."""
    global _exemplars_on
    with _lock:
        _registry.clear()
    _exemplars_on = None
