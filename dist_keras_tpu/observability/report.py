"""Multi-host run report — merge per-host event logs into one timeline.

The launcher (or anyone pointed at a ``DK_OBS_DIR`` after the fact —
``python -m dist_keras_tpu.observability <dir>``) merges the per-host
``events-rank_{i}.jsonl`` files into a single timeline ordered by
``(time, rank, seq)`` and summarizes it: per-phase durations (from
spans), coordination-op durations, retry counts, checkpoint commits,
nonfinite-step totals, preemption attribution (WHICH rank got the
signal, what step the cluster agreed to save), and the last-N events per
host — which is exactly the artifact needed to attribute a hang like the
r05 "backend unresponsive" bench failure or a ``BarrierTimeout`` to the
host that stalled: the dead host's file simply *stops*, and the merged
tail shows what every other host was waiting on.

Strictly read-only and import-light (stdlib only): safe to run from a
monitor loop against a live run's directory.
"""

from __future__ import annotations

import json
import os
import re
import time

_FILE_RE = re.compile(r"^events-rank_(\d+)\.jsonl(?:\.(\d+))?$")


def event_files(directory):
    """-> [(rank, path)] of the per-host event files, including rotated
    segments (``events-rank_{i}.jsonl.N`` — produced by the
    ``DK_OBS_ROTATE_MB`` size cap) and files one level down in
    ``host_{i}/`` subdirectories (the layout ``Job.collect_obs``
    rsyncs back, so a collect destination is directly monitorable).
    Ordered per rank OLDEST segment first (highest ``.N``, then the
    active file) so a sequential reader sees each host's history in
    emission order.  The merged timeline re-sorts by (t, rank, seq)
    anyway; this order is for humans cat-ing the list."""
    directory = os.path.abspath(os.path.expanduser(str(directory)))
    out = []

    def _scan(d):
        try:
            names = os.listdir(d)
        except OSError:
            return []
        return [(n, os.path.join(d, n)) for n in names]

    entries = _scan(directory)
    for name, path in list(entries):
        if re.match(r"^host_\d+$", name) and os.path.isdir(path):
            entries.extend(_scan(path))
    for name, path in entries:
        m = _FILE_RE.match(name)
        if m:
            seg = int(m.group(2)) if m.group(2) else 0
            out.append(((int(m.group(1)), -seg), path))
    return [(key[0], path) for key, path in sorted(out)]


def read_events(directory):
    """Merged timeline: every host's events ordered by (t, rank, seq).

    A torn final line (host killed mid-write — the atomic line writer
    makes this rare but a dying fs can still truncate) is skipped, not
    fatal: the report must work best exactly when the run died worst.
    """
    events = []
    for rank, path in event_files(directory):
        try:
            with open(path) as f:
                lines = f.readlines()
        except OSError:
            continue
        for line in lines:
            line = line.strip()
            if not line:
                continue
            try:
                ev = json.loads(line)
            except ValueError:
                continue  # torn tail line
            ev.setdefault("rank", rank)
            events.append(ev)
    events.sort(key=lambda e: (e.get("t", 0.0), e.get("rank", 0),
                               e.get("seq", 0)))
    return events


def _acc(table, key, dt):
    row = table.setdefault(key, {"count": 0, "total_s": 0.0,
                                 "max_s": 0.0})
    row["count"] += 1
    if dt is not None:
        row["total_s"] += float(dt)
        row["max_s"] = max(row["max_s"], float(dt))


def summarize(events):
    """-> structured summary of a merged timeline (JSON-ready)."""
    ranks = {}
    phases = {}       # span path -> {count, total_s, max_s}
    coord = {}        # coordination/barrier op -> {count, total_s, max_s}
    retries = {}      # retry-surface name -> {attempts, exhausted}
    faults = {}       # fault point -> fires
    saves = {}        # rank -> last ckpt_save step
    promoted = []
    restored = []
    epochs = {}       # rank -> epoch_end count
    signalled = {}    # rank -> signum (preemption attribution)
    dead = []         # peer-dead transitions [(rank reporting, peer)]
    resizes = []      # elastic world resizes, in timeline order
    reshards = []     # resharding restores [(rank, step, N -> M)]
    # parameter-server attribution (ps/): per-worker commit counts,
    # the server-side staleness histogram, membership transitions
    ps_commits = {}   # wid -> commits applied
    ps_staleness = {}  # staleness value -> count (the histogram)
    ps_joins = []     # [{wid, rank, rejoined}] in timeline order
    ps_lapses = []    # [{wid, rank, reason}] in timeline order
    ps_rejected = 0   # over-cap commits refused (typed StaleCommit)
    # decode survivability attribution (serving/decode.py): which
    # replica died, how many sequences it carried, where they landed
    dq = []           # quarantines [{replica, orphans, cause}]
    dr = {}           # recoveries: dst replica -> count
    dshed = {}        # brownout sheds: reason -> count
    ddl = {"infeasible": 0, "expired": 0}
    dleaks = 0        # self-check reclaimed pages
    nonfinite = 0
    for ev in events:
        rank = int(ev.get("rank", 0))
        kind = ev.get("kind", "?")
        row = ranks.setdefault(rank, {"events": 0, "first_t": None,
                                      "last_t": None, "last_kind": None})
        row["events"] += 1
        t = ev.get("t")
        if t is not None:
            if row["first_t"] is None:
                row["first_t"] = t
            row["last_t"] = t
        row["last_kind"] = kind
        if kind == "span_end":
            _acc(phases, ev.get("span", "?"), ev.get("duration_s"))
        elif kind in ("coord", "coord_error"):
            _acc(coord, ev.get("op", "?"), ev.get("duration_s"))
        elif kind == "barrier":
            _acc(coord, f"comm.barrier({ev.get('tag', '?')})",
                 ev.get("duration_s"))
        elif kind == "retry":
            r = retries.setdefault(ev.get("name", "?"),
                                   {"attempts": 0, "exhausted": 0})
            r["attempts"] += 1
        elif kind == "retry_exhausted":
            r = retries.setdefault(ev.get("name", "?"),
                                   {"attempts": 0, "exhausted": 0})
            r["exhausted"] += 1
        elif kind == "fault":
            point = ev.get("point", "?")
            faults[point] = faults.get(point, 0) + 1
        elif kind == "ckpt_save":
            if ev.get("step") is not None:
                saves[rank] = int(ev["step"])
        elif kind == "ckpt_promote":
            if ev.get("step") is not None:
                promoted.append(int(ev["step"]))
        elif kind == "ckpt_restore":
            if ev.get("step") is not None:
                restored.append(int(ev["step"]))
        elif kind == "epoch_end":
            epochs[rank] = epochs.get(rank, 0) + 1
            nonfinite += int(ev.get("nonfinite_steps", 0) or 0)
        elif kind in ("preempt_signal", "preempt"):
            # attribution, not participation: every host emits a
            # "preempt" at the boundary where it honors the cluster
            # vote, but a host that merely ADOPTED the verdict
            # (adopted=True) did not receive the OS signal — only the
            # genuinely-signalled rank(s) belong here
            if not ev.get("adopted"):
                signalled.setdefault(rank, ev.get("signum"))
        elif kind == "peer_dead":
            dead.append((rank, ev.get("peer")))
        elif kind == "elastic_resize":
            resizes.append({
                "session": ev.get("session"),
                "old_world": ev.get("old_world"),
                "new_world": ev.get("new_world"),
                "dropped_ranks": ev.get("dropped_ranks"),
                "dropped_hosts": ev.get("dropped_hosts")})
        elif kind == "ps_commit":
            wid = ev.get("wid", "?")
            ps_commits[wid] = ps_commits.get(wid, 0) + 1
            s = ev.get("staleness")
            if s is not None:
                ps_staleness[int(s)] = ps_staleness.get(int(s), 0) + 1
        elif kind == "ps_worker_join":
            ps_joins.append({"wid": ev.get("wid"),
                             "rank": ev.get("worker_rank"),
                             "rejoined": bool(ev.get("rejoined"))})
        elif kind == "ps_worker_lapse":
            ps_lapses.append({"wid": ev.get("wid"),
                              "rank": ev.get("worker_rank"),
                              "reason": ev.get("reason")})
        elif kind == "ps_stale_scaled":
            if ev.get("rejected"):
                ps_rejected += 1
        elif kind == "decode_quarantine":
            dq.append({"replica": ev.get("replica"),
                       "orphans": ev.get("orphans"),
                       "cause": ev.get("cause")})
        elif kind == "decode_recover":
            dst = ev.get("dst", "?")
            dr[dst] = dr.get(dst, 0) + 1
        elif kind == "decode_shed":
            why = ev.get("reason", "?")
            dshed[why] = dshed.get(why, 0) + 1
        elif kind == "decode_deadline":
            if ev.get("phase") == "admission":
                ddl["infeasible"] += 1
            else:
                ddl["expired"] += 1
        elif kind == "decode_kv_leak":
            dleaks += int(ev.get("pages", 0) or 0)
        elif kind == "reshard_restore":
            reshards.append({
                "rank": rank, "step": ev.get("step"),
                "saved_world": ev.get("saved_world"),
                "world": ev.get("world"),
                "n_sharded": ev.get("n_sharded"),
                "bytes_in": ev.get("bytes_in")})
    # the "agreed save step": under coordinated preemption every rank
    # saves the same step — report it when the saves agree
    agreed = None
    if saves and len(set(saves.values())) == 1:
        agreed = next(iter(saves.values()))
    return {
        "n_events": len(events),
        "ranks": ranks,
        "phases": phases,
        "coord": coord,
        "retries": retries,
        "faults": faults,
        "checkpoints": {"last_save_by_rank": saves,
                        "agreed_step": agreed,
                        "promoted": sorted(set(promoted)),
                        "restored": sorted(set(restored))},
        "epochs_by_rank": epochs,
        "nonfinite_steps": nonfinite,
        "preempt_signalled": signalled,
        "peer_dead": dead,
        "elastic_resizes": resizes,
        "reshard_restores": reshards,
        "ps": {"commits_by_worker": ps_commits,
               "staleness_hist": ps_staleness,
               "joins": ps_joins, "lapses": ps_lapses,
               "rejected_stale": ps_rejected},
        "decode": {"quarantines": dq,
                   "recoveries_by_replica": dr,
                   "sheds_by_reason": dshed,
                   "deadline": ddl,
                   "kv_pages_reclaimed": dleaks},
    }


def perf_summary(events):
    """-> perf-attribution view of a merged timeline: per-rank retrace/
    dispatch/transfer totals + per-phase host-wall breakdown (from each
    rank's LAST registry snapshot, falling back to its last
    ``perf_sample``), plus every ``watchdog_alert``/``watchdog_clear``
    in timeline order.  The CLI's ``--perf`` section."""
    last_metrics = {}   # rank -> last "metrics" registry snapshot
    last_sample = {}    # rank -> last "perf_sample" payload
    alerts, clears = [], []
    for ev in events:
        rank = int(ev.get("rank", 0))
        kind = ev.get("kind")
        if kind == "metrics":
            last_metrics[rank] = ev
        elif kind == "perf_sample":
            last_sample[rank] = ev
        elif kind == "watchdog_alert":
            alerts.append(ev)
        elif kind == "watchdog_clear":
            clears.append(ev)
    per_rank = {}
    for rank in sorted(set(last_metrics) | set(last_sample)):
        snap = last_metrics.get(rank)
        samp = last_sample.get(rank)
        # take whichever record is NEWER: a process that trains and
        # then serves keeps emitting perf_sample long after its last
        # epoch-boundary snapshot — preferring the snapshot
        # unconditionally would freeze --perf at train-end totals
        if snap is not None and samp is not None \
                and samp.get("t", 0.0) > snap.get("t", 0.0):
            snap = None
        if snap is not None:
            counters = snap.get("counters", {}) or {}
            hists = snap.get("histograms", {}) or {}
            phases = {}
            for name, h in hists.items():
                if not name.startswith("perf.phase."):
                    continue
                count = h.get("count", 0) or 0
                total = h.get("total", 0.0) or 0.0
                phases[name[len("perf.phase."):]] = {
                    "count": count, "total_s": round(total, 4),
                    "mean_s": (round(total / count, 6) if count
                               else None)}
            per_rank[rank] = {
                "retraces": counters.get("perf.retraces", 0),
                "dispatches": counters.get("perf.dispatches", 0),
                "h2d_bytes": counters.get("perf.h2d_bytes", 0),
                "d2h_bytes": counters.get("perf.d2h_bytes", 0),
                "phases": phases,
            }
        else:  # no epoch boundary, or the sampler ran past the last one
            s = last_sample[rank]
            per_rank[rank] = {
                "retraces": s.get("retraces", 0),
                "dispatches": s.get("dispatches", 0),
                "h2d_bytes": s.get("h2d_bytes", 0),
                "d2h_bytes": s.get("d2h_bytes", 0),
                "phases": s.get("phases", {}) or {},
            }
    return {"per_rank": per_rank, "watchdog_alerts": alerts,
            "watchdog_clears": clears}


def render_perf(directory, events=None):
    """Human-readable perf/watchdog section for ``--perf``."""
    if events is None:
        events = read_events(directory)
    p = perf_summary(events)
    lines = ["# perf attribution"]
    if not p["per_rank"] and not p["watchdog_alerts"]:
        lines.append("no perf telemetry recorded (retrace/dispatch "
                     "counters ride registry snapshots — was the run "
                     "instrumented with DK_OBS_DIR, and did it reach "
                     "an epoch boundary or a perf_sample tick?)")
        return "\n".join(lines)
    for rank in sorted(p["per_rank"]):
        row = p["per_rank"][rank]
        lines.append(
            f"rank {rank}: retraces={row['retraces']} "
            f"dispatches={row['dispatches']} "
            f"h2d={row['h2d_bytes']}B d2h={row['d2h_bytes']}B")
        for name in ("data", "step", "comm", "ckpt"):
            ph = row["phases"].get(name)
            if not ph:
                continue
            mean = ph.get("mean_s")
            lines.append(
                f"  phase {name}: n={ph.get('count', 0)} "
                f"total={ph.get('total_s', 0.0):.3f}s"
                + (f" mean={mean * 1e3:.2f}ms" if mean else ""))
    t0 = events[0].get("t", 0.0) if events else 0.0
    if p["watchdog_alerts"]:
        lines.append("watchdog alerts:")
        for a in p["watchdog_alerts"]:
            ts = a.get("t", 0.0)
            extras = _fmt_fields(
                a, skip=("t", "seq", "rank", "kind", "rule"))
            lines.append(f"  +{ts - t0:9.3f}s rank {a.get('rank', 0)} "
                         f"{a.get('rule', '?')}: {extras}")
        for c in p["watchdog_clears"]:
            ts = c.get("t", 0.0)
            lines.append(f"  +{ts - t0:9.3f}s rank {c.get('rank', 0)} "
                         f"{c.get('rule', '?')}: cleared")
    else:
        lines.append("watchdog alerts: none")
    return "\n".join(lines)


def _fmt_fields(ev, skip=("t", "seq", "rank", "kind")):
    parts = []
    for k, v in ev.items():
        if k in skip:
            continue
        if isinstance(v, float):
            v = round(v, 4)
        parts.append(f"{k}={v}")
    return " ".join(parts)


def slo_summary(events):
    """SLO-plane digest from the merged timeline — for ``--slo``.

    Objective status and burn rates come from the ``slo_transition``
    and ``watchdog_alert`` (rule ``slo_burn_rate``) payloads, which
    carry the registry's evaluation at alert time: ``perf_sample``
    records don't ship the serving counters, so the offline report
    reads the burns the live evaluator published rather than
    recomputing them.
    """
    per_rank = {}

    def _row(rank):
        return per_rank.setdefault(
            int(rank), {"firing": [], "objectives": {}})

    n_transitions = 0
    for ev in events:
        kind = ev.get("kind")
        if kind == "slo_transition":
            n_transitions += 1
            row = _row(ev.get("rank", 0))
            row["firing"] = sorted(ev.get("firing", ()) or ())
        elif (kind == "watchdog_alert"
                and ev.get("rule") == "slo_burn_rate"):
            row = _row(ev.get("rank", 0))
            row["objectives"][str(ev.get("objective", "?"))] = {
                "t": ev.get("t", 0.0),
                "page": ev.get("page"),
                "target": ev.get("target"),
                "burn": {"5m": ev.get("burn_5m"),
                         "1h": ev.get("burn_1h"),
                         "6h": ev.get("burn_6h")},
            }
        elif (kind == "watchdog_clear"
                and ev.get("rule") == "slo_burn_rate"):
            for o in _row(ev.get("rank", 0))["objectives"].values():
                o["cleared"] = True
    return {"per_rank": per_rank, "transitions": n_transitions}


def _fmt_burn(v):
    return "?" if v is None else f"{v:g}"


def render_slo(directory, events=None, worst=5):
    """Human-readable SLO section for ``--slo``: per-rank objective
    status with the burn rates at alert time, then the worst-``worst``
    retained requests with their cross-host critical-path
    attribution (queue wait vs forward hop vs replica compute vs
    reload stall)."""
    from dist_keras_tpu.observability import trace_export

    if events is None:
        events = read_events(directory)
    s = slo_summary(events)
    lines = ["# SLO report"]
    t0 = events[0].get("t", 0.0) if events else 0.0
    if not s["per_rank"] and not s["transitions"]:
        lines.append("no SLO telemetry recorded (burn-rate evaluation "
                     "rides the sampler tick — was the run armed with "
                     "DK_SLO=1 and a DK_OBS_SAMPLE_S cadence?)")
    for rank in sorted(s["per_rank"]):
        row = s["per_rank"][rank]
        firing = ", ".join(row["firing"]) if row["firing"] else "none"
        lines.append(f"rank {rank}: firing objectives: {firing}")
        for name in sorted(row["objectives"]):
            o = row["objectives"][name]
            b = o["burn"]
            status = ("cleared" if o.get("cleared")
                      else f"{o.get('page', '?')} page")
            lines.append(
                f"  {name}: target={o.get('target')} burn "
                f"5m={_fmt_burn(b['5m'])} 1h={_fmt_burn(b['1h'])} "
                f"6h={_fmt_burn(b['6h'])} "
                f"[{status}, alerted +{o['t'] - t0:.3f}s]")
    paths = trace_export.request_paths(events, worst=worst)
    if paths:
        lines.append(f"worst {len(paths)} retained request(s) by "
                     "end-to-end latency:")
        for p in paths:
            crit = p["critical"]
            lines.append(
                f"  trace {p['trace_id']}: {p['total_s'] * 1e3:.1f}ms "
                f"root {p['root']} (rank {p['rank']}) — critical hop "
                f"{crit['span']} ({crit['category']}) on rank "
                f"{crit['rank']}, self {crit['self_s'] * 1e3:.1f}ms")
            for hop in p["path"]:
                lines.append(
                    f"    {hop['span']:<20} rank {hop['rank']} "
                    f"{hop['category']:<16} "
                    f"total={hop['duration_s'] * 1e3:8.1f}ms "
                    f"self={hop['self_s'] * 1e3:8.1f}ms")
            cats = ", ".join(
                f"{k}={v * 1e3:.1f}ms" for k, v in
                sorted(p["by_category"].items(),
                       key=lambda kv: -kv[1]))
            lines.append(f"    attribution: {cats}")
    else:
        lines.append("retained requests: none (tail-based retention "
                     "keeps span records only for slow/errored/head-"
                     "sampled requests — was DK_TRACE_RETAIN=1 armed?)")
    return "\n".join(lines)


def render(directory, last_n=10):
    """Human-readable report: summary + the last-N events per host."""
    events = read_events(directory)
    lines = [f"# dist_keras_tpu run report — {directory}"]
    if not events:
        lines.append("no events found (is DK_OBS_DIR right? did the "
                     "run export it?)")
        return "\n".join(lines)
    s = summarize(events)
    t0 = events[0].get("t", 0.0)
    lines.append(f"{s['n_events']} events from "
                 f"{len(s['ranks'])} host(s), spanning "
                 f"{events[-1].get('t', t0) - t0:.1f}s")
    for rank in sorted(s["ranks"]):
        row = s["ranks"][rank]
        stale = ""
        if row["last_t"] is not None:
            age = events[-1].get("t", row["last_t"]) - row["last_t"]
            if age > 1.0:
                stale = (f"  << went quiet {age:.1f}s before the end "
                         f"(last: {row['last_kind']})")
        lines.append(f"  rank {rank}: {row['events']} events, "
                     f"last kind {row['last_kind']}{stale}")
    if s["preempt_signalled"]:
        for rank, signum in sorted(s["preempt_signalled"].items()):
            lines.append(f"preemption: rank {rank} got signal {signum}")
        if s["checkpoints"]["agreed_step"] is not None:
            lines.append("agreed save step: "
                         f"{s['checkpoints']['agreed_step']}")
    if s["checkpoints"]["last_save_by_rank"]:
        lines.append(f"checkpoints: last save by rank "
                     f"{s['checkpoints']['last_save_by_rank']}, "
                     f"promoted {s['checkpoints']['promoted']}, "
                     f"restored {s['checkpoints']['restored']}")
    if s["phases"]:
        lines.append("phases (spans):")
        for name in sorted(s["phases"]):
            p = s["phases"][name]
            lines.append(f"  {name}: n={p['count']} "
                         f"total={p['total_s']:.3f}s "
                         f"max={p['max_s']:.3f}s")
    if s["coord"]:
        lines.append("coordination ops:")
        for name in sorted(s["coord"]):
            p = s["coord"][name]
            lines.append(f"  {name}: n={p['count']} "
                         f"total={p['total_s']:.3f}s "
                         f"max={p['max_s']:.3f}s")
    if s["retries"]:
        lines.append("retries: " + ", ".join(
            f"{k} x{v['attempts']}"
            + (f" (EXHAUSTED x{v['exhausted']})" if v["exhausted"]
               else "")
            for k, v in sorted(s["retries"].items())))
    if s["faults"]:
        lines.append("faults fired: " + ", ".join(
            f"{k} x{v}" for k, v in sorted(s["faults"].items())))
    if s["nonfinite_steps"]:
        lines.append(f"nonfinite steps: {s['nonfinite_steps']}")
    if s["peer_dead"]:
        lines.append("dead-peer reports: " + ", ".join(
            f"rank {r} saw peer {p} die" for r, p in s["peer_dead"]))
    for rz in s["elastic_resizes"]:
        lines.append(
            f"elastic resize: world {rz['old_world']} -> "
            f"{rz['new_world']} at session {rz['session']} (dropped "
            f"ranks {rz['dropped_ranks']}: {rz['dropped_hosts']})")
    for rs in s["reshard_restores"]:
        lines.append(
            f"reshard restore: rank {rs['rank']} loaded step "
            f"{rs['step']} written by world {rs['saved_world']} as "
            f"world {rs['world']} ({rs['n_sharded']} sharded leaves, "
            f"{rs['bytes_in']} bytes gathered)")
    ps = s["ps"]
    if ps["commits_by_worker"] or ps["joins"] or ps["lapses"]:
        commits = ", ".join(
            f"{wid} x{n}" for wid, n in
            sorted(ps["commits_by_worker"].items()))
        lines.append(f"parameter server: commits by worker: "
                     f"{commits or 'none'}")
        if ps["staleness_hist"]:
            hist = " ".join(
                f"{s_}:{n}" for s_, n in
                sorted(ps["staleness_hist"].items()))
            lines.append(f"  staleness histogram (value:count): {hist}")
        for j in ps["joins"]:
            lines.append(
                f"  worker join: {j['wid']}"
                + (f" (rank {j['rank']})" if j["rank"] is not None
                   else "")
                + (" [rejoin]" if j["rejoined"] else ""))
        for lp in ps["lapses"]:
            lines.append(
                f"  worker lapse: {lp['wid']}"
                + (f" (rank {lp['rank']})" if lp["rank"] is not None
                   else "")
                + f" — {lp['reason']}")
        if ps["rejected_stale"]:
            lines.append(f"  over-cap commits refused (typed): "
                         f"{ps['rejected_stale']}")
    dc = s["decode"]
    if (dc["quarantines"] or dc["recoveries_by_replica"]
            or dc["sheds_by_reason"] or any(dc["deadline"].values())
            or dc["kv_pages_reclaimed"]):
        lines.append("decode survivability:")
        for q in dc["quarantines"]:
            landed = sum(dc["recoveries_by_replica"].values())
            lines.append(
                f"  replica {q['replica']} quarantined "
                f"({q['cause']}): {q['orphans']} in-flight "
                f"sequence(s), {landed} recovered onto "
                + (", ".join(
                    f"replica {d} x{n}" for d, n in
                    sorted(dc["recoveries_by_replica"].items(),
                           key=lambda kv: str(kv[0])))
                   or "nobody"))
        if dc["sheds_by_reason"]:
            lines.append("  brownout sheds: " + ", ".join(
                f"{k} x{v}" for k, v in
                sorted(dc["sheds_by_reason"].items())))
        if any(dc["deadline"].values()):
            lines.append(
                f"  deadlines: {dc['deadline']['infeasible']} "
                f"rejected at the door, "
                f"{dc['deadline']['expired']} expired mid-decode")
        if dc["kv_pages_reclaimed"]:
            lines.append(f"  KV LEAK: self-check reclaimed "
                         f"{dc['kv_pages_reclaimed']} page(s)")
    # the tail per host — what each host was doing when the run ended
    by_rank = {}
    for ev in events:
        by_rank.setdefault(int(ev.get("rank", 0)), []).append(ev)
    for rank in sorted(by_rank):
        lines.append(f"last {last_n} events, rank {rank}:")
        for ev in by_rank[rank][-last_n:]:
            ts = ev.get("t")
            stamp = (f"+{ts - t0:9.3f}s" if ts is not None
                     else " " * 11)
            lines.append(f"  {stamp} {ev.get('kind', '?'):<14} "
                         f"{_fmt_fields(ev)}")
    return "\n".join(lines)


def write_report(directory, out_path=None, last_n=10):
    """Render and write ``report.txt`` beside the event files (or to
    ``out_path``); returns the path.  The leader calls this at the end
    of a run so the artifact exists without any post-hoc CLI step."""
    text = render(directory, last_n=last_n)
    if out_path is None:
        out_path = os.path.join(
            os.path.abspath(os.path.expanduser(str(directory))),
            "report.txt")
    tmp = f"{out_path}.tmp.{os.getpid()}.{int(time.time() * 1e6)}"
    with open(tmp, "w") as f:
        f.write(text + "\n")
    os.replace(tmp, out_path)
    return out_path
