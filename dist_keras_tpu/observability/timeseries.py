"""Live time-series metrics — bounded rings sampled by a background thread.

PR 3's registry answers "what were the totals when the run ended"; this
module grows it into the live, queryable signal the anomaly watchdog and
the serving router need: every registered counter/gauge/histogram is
periodically sampled into a bounded per-metric :class:`TimeSeries` ring,
so "is step time regressing *right now*" and "is this host's queue
growing" are O(window) reads against flat memory instead of a log scan.

Design points:

- **Flat memory, lock-cheap.**  A :class:`TimeSeries` is two
  preallocated float64 arrays (timestamps, values) written round-robin;
  ``append`` is a short lock + two array stores, ``values()`` copies the
  window in chronological order.  A week-long run holds exactly
  ``window`` points per metric, forever.
- **One sampler thread per process** (:class:`MetricsSampler`), cadence
  ``DK_OBS_SAMPLE_S`` seconds.  Each tick snapshots the metrics
  registry: counters and numeric gauges record their value; histograms
  record the *cumulative* ``<name>.count`` and ``<name>.total`` pair, so
  a consumer (the watchdog's regression rule) derives interval means
  from deltas without per-sample percentile math.  The tick then runs
  the attached :class:`~dist_keras_tpu.observability.watchdog.Watchdog`
  and — when the event log is enabled — emits one compact
  ``perf_sample`` event carrying the perf-attribution snapshot
  (:func:`~dist_keras_tpu.observability.perf.snapshot`), so the merged
  report can plot retraces/dispatches/phase walls over time.
- **Zero-cost when off.**  :func:`maybe_start_sampler` (called from
  ``Trainer.record_training_start`` and the serving front end) is one
  env read when ``DK_OBS_SAMPLE_S`` is unset — no thread, no series, no
  registry walk.  Sampling is independent of ``DK_OBS_DIR``: an
  operator can run the watchdog + Prometheus exporter live without
  writing event files.

Env knobs: ``DK_OBS_SAMPLE_S`` (sampler cadence, seconds; unset =
sampler never auto-starts), ``DK_OBS_TS_WINDOW`` (ring size per metric,
default 512), ``DK_WATCHDOG=0`` (auto-started sampler skips the default
watchdog), ``DK_METRICS_PORT`` (:func:`maybe_start_sampler` also brings
up the standalone Prometheus exporter — independently of the sampling
cadence, so a scrape-port-only config still serves — see
``prometheus.py``).
"""

from __future__ import annotations

import threading
import time

import numpy as np

from dist_keras_tpu.observability import events, metrics
from dist_keras_tpu.utils import knobs

# the registry's default is the single source of truth
DEFAULT_WINDOW = knobs.KNOBS["DK_OBS_TS_WINDOW"].default


def _default_window():
    # registry-parsed (default 512, malformed -> default); the floor
    # keeps a degenerate ring usable
    return max(2, int(knobs.get("DK_OBS_TS_WINDOW")))


class TimeSeries:
    """Bounded ``(t, value)`` ring for one metric.

    ``append`` overwrites the oldest point past ``window``; readers get
    chronological copies.  All methods are safe against a concurrent
    appender (the sampler thread) — the lock covers only index
    arithmetic and array stores, never user code.
    """

    def __init__(self, name, window=None):
        self.name = str(name)
        self.window = int(window) if window else _default_window()
        if self.window < 2:
            raise ValueError(f"window={window} must be >= 2")
        self._t = np.zeros(self.window, dtype=np.float64)
        self._v = np.zeros(self.window, dtype=np.float64)
        self._n = 0  # total points ever appended
        self._lock = threading.Lock()

    def append(self, value, t=None):
        t = time.time() if t is None else float(t)
        with self._lock:
            i = self._n % self.window
            self._t[i] = t
            self._v[i] = float(value)
            self._n += 1

    def __len__(self):
        return min(self._n, self.window)

    @property
    def total_appended(self):
        """Lifetime point count (retained points = ``len(self)``)."""
        return self._n

    @property
    def latest(self):
        """The most recent ``(t, value)``, or None when empty."""
        with self._lock:
            if self._n == 0:
                return None
            i = (self._n - 1) % self.window
            return (self._t[i], self._v[i])

    def values(self):
        """-> ``(t, v)`` float64 arrays, oldest first (copies — safe to
        hold while the sampler keeps appending)."""
        with self._lock:
            n = min(self._n, self.window)
            if n == 0:
                return (np.empty(0), np.empty(0))
            if self._n <= self.window:
                return (self._t[:n].copy(), self._v[:n].copy())
            i = self._n % self.window
            order = np.r_[i:self.window, 0:i]
            return (self._t[order].copy(), self._v[order].copy())

    def since(self, t0):
        """-> the retained ``(t, v)`` points with ``t >= t0``."""
        t, v = self.values()
        keep = t >= float(t0)
        return (t[keep], v[keep])

    def span_s(self):
        """Seconds covered by the retained window (0.0 when < 2 pts)."""
        t, _ = self.values()
        return float(t[-1] - t[0]) if len(t) >= 2 else 0.0


_lock = threading.Lock()
_series = {}  # name -> TimeSeries


def series(name, window=None):
    """Get-or-create the named series (same call-site contract as the
    metrics registry: no registration-order coordination)."""
    name = str(name)
    with _lock:
        s = _series.get(name)
        if s is None:
            s = _series[name] = TimeSeries(name, window=window)
        return s


def get(name):
    """The named series, or None — a probe that never creates (rules
    must not materialize empty series for metrics nobody records)."""
    with _lock:
        return _series.get(str(name))


def names():
    with _lock:
        return sorted(_series)


def record_snapshot(snap, t=None):
    """Fold one metrics-registry snapshot into the series registry —
    the sampler tick's core, public so tests drive it deterministically.

    Counters -> ``<name>``; numeric gauges -> ``<name>``; histograms ->
    cumulative ``<name>.count`` + ``<name>.total`` (interval means are
    deltas, derived by consumers — storing cumulative keeps each tick
    O(metrics) with no per-metric state here)."""
    t = time.time() if t is None else float(t)
    for name, v in snap.get("counters", {}).items():
        series(name).append(v, t=t)
    for name, v in snap.get("gauges", {}).items():
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            series(name).append(v, t=t)
    for name, h in snap.get("histograms", {}).items():
        series(f"{name}.count").append(h.get("count", 0), t=t)
        series(f"{name}.total").append(h.get("total", 0.0), t=t)


def default_sample_s():
    """The ``DK_OBS_SAMPLE_S`` cadence, or None when unset/malformed
    (malformed = sampler stays off, loudly on stderr would be noise —
    the README documents the knob as float seconds)."""
    raw = (knobs.raw("DK_OBS_SAMPLE_S") or "").strip()
    if not raw:
        return None
    try:
        v = float(raw)
    except ValueError:
        return None
    return v if v > 0 else None


class MetricsSampler:
    """Background thread sampling the registry every ``interval_s``.

    ``start``/``stop`` are idempotent; ``tick()`` is the single sampling
    pass, public so tests (and the watchdog gate) can drive it without
    wall-clock waits.  A tick never throws — a failing rule or emit
    degrades like every other observability path.
    """

    def __init__(self, interval_s=None, watchdog=None):
        if interval_s is None:
            interval_s = default_sample_s()
        if interval_s is None:
            interval_s = 5.0
        self.interval_s = float(interval_s)
        if self.interval_s <= 0:
            raise ValueError(f"interval_s={interval_s} must be > 0")
        self.watchdog = watchdog
        self.ticks = 0
        self._stop = threading.Event()
        self._thread = None
        self._lock = threading.Lock()

    def tick(self, now=None):
        """One sampling pass: registry -> series, watchdog check, and a
        ``perf_sample`` event when the log is enabled."""
        now = time.time() if now is None else float(now)
        snap = None
        try:
            # percentiles=False: the tick must stay O(instruments)
            # with no numpy percentile pass — series only need the
            # cumulative count/total anyway (rules derive interval
            # means from deltas)
            snap = metrics.snapshot(percentiles=False)
            record_snapshot(snap, t=now)
        # dklint: ignore[broad-except] a registry snapshot failure must not kill the sampler tick
        except Exception:  # pragma: no cover - registry must not kill
            pass
        # SLO evaluation runs AFTER the rings absorb this tick's
        # snapshot (objectives read the rings) and BEFORE the watchdog
        # check (SLOBurnRate reads the evaluation, idempotent per
        # timestamp).  maybe_evaluate is a no-op unless DK_SLO is
        # armed, and never throws.
        from dist_keras_tpu.observability import slo

        slo.maybe_evaluate(now)
        if self.watchdog is not None:
            try:
                self.watchdog.check(now=now)
            # dklint: ignore[broad-except] watchdog.check never throws; belt-and-braces for the tick
            except Exception:  # pragma: no cover - never throws anyway
                pass
        if events.enabled():
            try:
                from dist_keras_tpu.observability import perf

                events.emit("perf_sample", **perf.snapshot(snap=snap))
            # dklint: ignore[broad-except] a failed perf_sample is a dropped sample, not a dead sampler
            except Exception:  # pragma: no cover - dropped sample
                pass
        # under the lock: tick() runs on the sampler thread AND from
        # main (tests, stop(final_tick=True)) — a torn += would lose
        # counts the idempotence tests assert on
        with self._lock:
            self.ticks += 1

    def _loop(self):
        while not self._stop.wait(self.interval_s):
            self.tick()

    @property
    def running(self):
        t = self._thread
        return t is not None and t.is_alive()

    def start(self):
        """Start the sampler thread (idempotent); -> self."""
        with self._lock:
            if self.running:
                return self
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, daemon=True, name="dk-obs-sampler")
            self._thread.start()
        return self

    def stop(self, timeout=5.0, final_tick=False):
        """Stop the thread (idempotent).  ``final_tick=True`` runs one
        last deterministic pass so the series carry the run's end."""
        with self._lock:
            t, self._thread = self._thread, None
            self._stop.set()
        if t is not None and t.is_alive() \
                and t is not threading.current_thread():
            t.join(timeout=timeout)
        if final_tick:
            self.tick()


_global = {"sampler": None}


def get_sampler():
    """The process-wide sampler (None until :func:`maybe_start_sampler`
    armed one)."""
    return _global["sampler"]


def maybe_start_sampler():
    """Start the process-wide sampler iff ``DK_OBS_SAMPLE_S`` is set —
    the auto-wiring hook trainers and the serving front end call.  Two
    env reads when everything is unset.  The first start attaches the
    default watchdog (unless ``DK_WATCHDOG=0``).  The
    ``DK_METRICS_PORT`` Prometheus exporter is attempted FIRST and
    unconditionally: an operator who sets only the scrape port (the
    README's "one scrape config covers the pod" wiring) gets a live
    exporter without also having to opt into sampling.  Returns the
    running sampler or None."""
    try:
        from dist_keras_tpu.observability import prometheus

        prometheus.maybe_start_exporter()
    # dklint: ignore[broad-except] exporter bring-up is best-effort; telemetry must not kill
    except Exception:  # pragma: no cover - exporter must not kill
        pass
    interval = default_sample_s()
    if interval is None:
        return None
    with _lock:
        sampler = _global["sampler"]
        if sampler is None:
            wd = None
            if knobs.get("DK_WATCHDOG"):
                from dist_keras_tpu.observability import watchdog

                wd = watchdog.Watchdog()
            sampler = _global["sampler"] = MetricsSampler(
                interval_s=interval, watchdog=wd)
    return sampler.start()


def stop_sampler(final_tick=False):
    """Stop and forget the process-wide sampler (tests / clean exits)."""
    with _lock:
        sampler, _global["sampler"] = _global["sampler"], None
    if sampler is not None:
        sampler.stop(final_tick=final_tick)


def reset():
    """Drop every series and the global sampler (tests)."""
    stop_sampler()
    with _lock:
        _series.clear()
