"""Prometheus text exposition for the metrics registry.

One format for every scraper: the serving front end answers
``GET /metricsz?format=prometheus`` with this rendering, and the
standalone per-host :class:`Exporter` (armed by ``DK_METRICS_PORT``, or
started explicitly) serves the same text on ``/metrics`` — so the
future multi-host router, an ops Prometheus, and a curl all read one
vocabulary.  Text format 0.0.4 (the stable exposition format), stdlib
only, strictly read-only against the registry.

Mapping:

- counter ``a.b``            -> ``dk_a_b_total`` (TYPE counter)
- numeric gauge ``a.b``      -> ``dk_a_b`` (TYPE gauge; non-numeric
  gauges are skipped — exposition is numbers-only)
- histogram ``a.b``          -> ``dk_a_b`` (TYPE summary) with
  ``quantile="0.5|0.95|0.99"`` sample lines plus ``dk_a_b_sum`` /
  ``dk_a_b_count`` (exact lifetime totals; quantiles over the bounded
  recent window, matching ``Histogram.summary``)

Every sample carries a ``rank`` label (``DK_COORD_RANK`` >
``JAX_PROCESS_ID`` > 0 — the event log's identity resolution), so a
fleet scrape federates per-host series without relabeling.
"""

from __future__ import annotations

import json
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from dist_keras_tpu.observability import events, metrics
from dist_keras_tpu.utils import knobs

_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")

PREFIX = "dk_"
QUANTILES = (("0.5", "p50"), ("0.95", "p95"), ("0.99", "p99"))


def metric_name(name, prefix=PREFIX):
    """Registry name -> Prometheus metric name (dots and every other
    illegal character become underscores; a leading digit is guarded)."""
    n = _NAME_RE.sub("_", str(name))
    if n and n[0].isdigit():
        n = "_" + n
    return prefix + n


def _labels(extra=None, rank=None):
    lab = {}
    if rank is None:
        rank = events._default_rank()
    lab["rank"] = str(rank)
    if extra:
        lab.update({str(k): str(v) for k, v in extra.items()})
    return lab


def _escape(v):
    return str(v).replace("\\", "\\\\").replace('"', '\\"')


def _fmt_labels(lab):
    if not lab:
        return ""
    body = ",".join(f'{k}="{_escape(v)}"'
                    for k, v in sorted(lab.items()))
    return "{" + body + "}"


def _num(v):
    return f"{v:.10g}" if isinstance(v, float) else str(v)


def render(snapshot=None, labels=None, extra_gauges=None, rank=None,
           prefix=PREFIX):
    """-> the exposition text (trailing newline included).

    ``snapshot`` defaults to the live registry; ``extra_gauges`` is a
    flat ``{name: number}`` dict rendered as additional gauges (the
    serving endpoint passes the engine's numeric stats through it)."""
    snap = metrics.snapshot() if snapshot is None else snapshot
    base = _labels(labels, rank=rank)
    lbl = _fmt_labels(base)
    lines = []
    for name in sorted(snap.get("counters", {})):
        v = snap["counters"][name]
        mn = metric_name(name, prefix) + "_total"
        lines.append(f"# TYPE {mn} counter")
        lines.append(f"{mn}{lbl} {_num(v)}")
    for name in sorted(snap.get("gauges", {})):
        v = snap["gauges"][name]
        if not isinstance(v, (int, float)) or isinstance(v, bool):
            continue
        mn = metric_name(name, prefix)
        lines.append(f"# TYPE {mn} gauge")
        lines.append(f"{mn}{lbl} {_num(v)}")
    for name in sorted(snap.get("histograms", {})):
        h = snap["histograms"][name]
        mn = metric_name(name, prefix)
        lines.append(f"# TYPE {mn} summary")
        for q, key in QUANTILES:
            val = h.get(key)
            if val is None:
                continue
            qlbl = _fmt_labels({**base, "quantile": q})
            lines.append(f"{mn}{qlbl} {_num(float(val))}")
        lines.append(f"{mn}_sum{lbl} {_num(float(h.get('total', 0.0)))}")
        lines.append(f"{mn}_count{lbl} {_num(int(h.get('count', 0)))}")
        # OpenMetrics-style exemplar comments (round 22): a scrape's
        # bad percentile links straight to a retained trace.  Comment
        # syntax keeps the 0.0.4 text parsers happy — they skip '#'
        # lines they don't know — while the trace/span ids stay
        # machine-recoverable from the scrape body.
        for ex in h.get("exemplars", ()):
            xl = _fmt_labels({"trace_id": ex.get("trace_id", ""),
                              "span_id": ex.get("span_id", "")})
            lines.append(
                f"# {xl} {_num(float(ex.get('value', 0.0)))}")
    for name in sorted(extra_gauges or {}):
        v = (extra_gauges or {})[name]
        if not isinstance(v, (int, float)) or isinstance(v, bool):
            continue
        mn = metric_name(name, prefix)
        lines.append(f"# TYPE {mn} gauge")
        lines.append(f"{mn}{lbl} {_num(v)}")
    return "\n".join(lines) + "\n"


CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


class _Handler(BaseHTTPRequestHandler):
    server_version = "dk-metrics/0.1"
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):  # the event log is the log
        pass

    def do_GET(self):
        path = self.path.split("?")[0]
        if path in ("/metrics", "/metricsz"):
            body = render().encode("utf-8")
            self.send_response(200)
            self.send_header("Content-Type", CONTENT_TYPE)
        elif path == "/healthz":
            body = json.dumps({"status": "ok"}).encode("utf-8")
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
        elif path == "/statusz":
            # the SAME build/config/open-span document the serving
            # front end serves — one shared renderer, one shape
            from dist_keras_tpu.observability import statusz

            body = statusz.render().encode("utf-8")
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
        elif path == "/tracez":
            # flight-recorder ring on demand (default=str: records
            # hold pre-serialization field values)
            from dist_keras_tpu.observability import flight

            body = json.dumps(flight.tracez_doc(),
                              default=str).encode("utf-8")
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
        else:
            body = json.dumps({"error": "not_found",
                               "path": self.path}).encode("utf-8")
            self.send_response(404)
            self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)


class Exporter(ThreadingHTTPServer):
    """Standalone per-host scrape endpoint: ``GET /metrics`` (alias
    ``/metricsz``) serves the live registry exposition; ``/healthz``
    answers 200; ``/statusz`` serves the shared build/config/open-span
    snapshot and ``/tracez`` the flight-recorder ring (same documents
    as the serving front end).  ``port=0`` binds an ephemeral port
    (tests)."""

    daemon_threads = True

    def __init__(self, port=0, host="0.0.0.0"):
        self._thread = None
        super().__init__((host, int(port)), _Handler)

    @property
    def address(self):
        return self.server_address[:2]

    def start(self):
        """Serve on a background thread; -> (host, bound_port)."""
        # dklint: thread-root=obs.exporter
        # (serve_forever is inherited from ThreadingHTTPServer, which
        # then spawns one handler thread per request — the registry's
        # ~_Handler.* row is where the off-main code actually runs)
        self._thread = threading.Thread(
            target=self.serve_forever, daemon=True,
            name="dk-metrics-exporter")
        self._thread.start()
        events.emit("metrics_exporter_listen", host=self.address[0],
                    port=self.address[1])
        return self.address

    def close(self):
        if self._thread is not None:
            self.shutdown()
            self._thread = None
        self.server_close()


_lock = threading.Lock()
_exporter = None


def get_exporter():
    return _exporter


def maybe_start_exporter():
    """Start the process-wide exporter iff ``DK_METRICS_PORT`` is set
    to a valid port (idempotent; one env read when unset).  Launch
    wiring: ``Job(metrics_port=...)`` exports the knob per host, so
    every host in a pod scrapes on the same port.  -> the exporter or
    None; a bind failure warns once and stays None (telemetry must not
    kill the run)."""
    import sys

    global _exporter
    raw = (knobs.raw("DK_METRICS_PORT") or "").strip()
    if not raw:
        return None
    with _lock:
        if _exporter is not None:
            return _exporter
        try:
            port = int(raw)
            if port < 1:
                return None
            exp = Exporter(port=port)
            exp.start()
        # dklint: ignore[broad-except] exporter bind failure warns once; telemetry must not kill the run
        except Exception as e:
            print(f"[dk.observability] WARNING: metrics exporter on "
                  f"port {raw!r} failed: {e!r}", file=sys.stderr,
                  flush=True)
            return None
        _exporter = exp
    return _exporter


def stop_exporter():
    """Close and forget the process-wide exporter (tests)."""
    global _exporter
    with _lock:
        exp, _exporter = _exporter, None
    if exp is not None:
        exp.close()
