"""``/statusz`` — one build/config/liveness snapshot for every server.

The serving front end (``serving/server.py``) and the standalone
Prometheus exporter (``observability/prometheus.py``) both answer
``GET /statusz`` with exactly this document, so an operator (or the
future router tier) reads ONE shape regardless of which port answered:

- ``build`` — python/platform/pid, plus the jax version when the
  process has loaded it (checked via ``sys.modules`` — this module must
  stay importable from the report CLI without dragging jax in);
- ``knobs`` — the effective value of every registered ``DK_*`` knob
  (parsed, defaults applied) plus whether the env actually set it: the
  "what configuration is this process REALLY running" answer that env
  dumps and launch scripts only approximate;
- ``spans`` — the open-span path per live thread
  (``spans.open_spans()``): a wedged process shows WHERE it is wedged;
- ``flight`` — recorder ring stats (capacity / retained / dumps);
- ``slz`` — the SLO plane: armed-or-not, each objective's last burn
  rates per window and firing flags (``slo.status_doc()``);
- ``uptime_s`` since this module first rendered (process-start proxy).
"""

from __future__ import annotations

import json
import os
import sys
import time

from dist_keras_tpu.observability import events, flight, slo, spans
from dist_keras_tpu.utils import knobs

_t0 = time.time()


def status_doc(extra=None):
    """-> the JSON-ready status document (``extra`` merges in a
    server-specific section, e.g. the serving engine's stats)."""
    import platform

    knob_rows = {}
    for name, knob in knobs.KNOBS.items():
        try:
            value = knobs.get(name)
        except ValueError:  # on_error="raise" knobs with malformed env
            value = "<malformed>"
        knob_rows[name] = {"value": value,
                           "set": knobs.raw(name) is not None}
    doc = {
        "build": {
            "python": sys.version.split()[0],
            "platform": platform.platform(),
            "pid": os.getpid(),
            "jax": getattr(sys.modules.get("jax"), "__version__", None),
        },
        "rank": events.rank(),
        "obs_dir": events.obs_dir(),
        "uptime_s": round(time.time() - _t0, 1),
        "knobs": knob_rows,
        "spans": spans.open_spans(),
        "flight": flight.recorder().stats(),
        "slz": slo.status_doc(),
    }
    if extra:
        doc.update(extra)
    return doc


def render(extra=None):
    """The shared ``/statusz`` body — both HTTP servers serve these
    exact bytes (plus their own ``extra`` section)."""
    return json.dumps(status_doc(extra=extra), indent=1, default=str,
                      sort_keys=False)
