"""Anomaly watchdog — declarative rules over the live time series.

The sampler (``timeseries.MetricsSampler``) turns the metrics registry
into per-metric ``(t, value)`` rings; this module evaluates rules over
them every tick and turns transitions into typed alerts:

- rule starts firing  -> one ``watchdog_alert`` event (naming the rule,
  metric, measured value vs baseline — and, because every event carries
  ``rank``, WHICH host regressed), ``watchdog.alerts`` counter ++, the
  ``watchdog.firing.<rule>`` gauge -> 1, the pluggable ``alert_sink``
  callback, and the ``resilience.supervisor`` alert seam (registered
  sinks + ``DK_ALERT_CMD``) — one delivery per transition, never one
  per tick;
- rule stops firing for ``clear_checks`` CONSECUTIVE ticks -> one
  ``watchdog_clear`` event and the gauge -> 0.  The consecutive-clear
  hysteresis is the anti-flapping contract: a value oscillating around
  the threshold produces one alert and (eventually) one clear, not an
  alert storm.

Rules (each a small class with ``evaluate(now) -> (firing, fields)``;
compose your own or take :func:`default_rules`):

- :class:`StepTimeRegression` — the recent interval-mean of a phase
  histogram (e.g. ``perf.phase.step``) exceeds ``factor`` x the MEDIAN
  of earlier interval means.  Median baseline, deliberately: the first
  interval contains the XLA compile (seconds against millisecond
  steps), and a mean baseline would let that one outlier mask a real
  2x regression forever.
- :class:`ThroughputStall` — a counter that was advancing has not
  advanced for ``window_s`` (e.g. ``perf.dispatches``: the run is
  alive but no work is retiring — the r05 "backend unresponsive"
  signature).
- :class:`QueueDepthGrowth` — a gauge (e.g. ``serve.pending``) rising
  monotonically across the last ``samples`` ticks above ``min_depth``:
  offered load is outrunning service rate *before* the queue bound
  starts rejecting.
- :class:`HeartbeatQuiet` — heartbeat-evidence dead peers
  (``coordination.dead_peers_at``, ``require_file=True`` so a host
  that never started is not convicted); fires naming the quiet ranks.

Rule evaluation never throws into the sampler: a broken rule degrades
to "not firing" plus one stderr warning per process.
"""

from __future__ import annotations

import sys
import threading
import time

import numpy as np

from dist_keras_tpu.observability import events, metrics, timeseries
from dist_keras_tpu.utils import knobs


class Rule:
    """One declarative anomaly rule.  Subclasses set ``name`` and
    implement :meth:`evaluate`; ``fields`` become the alert payload."""

    name = "rule"

    def evaluate(self, now):
        """-> ``(firing: bool, fields: dict)`` for this instant."""
        raise NotImplementedError

    def reset(self):
        """Forget accumulated state (stateful rules override; default
        no-op).  Called via :meth:`Watchdog.quiesce` when a workload
        phase ends ON PURPOSE — counters that stop advancing because
        the work completed must not be judged as a stall."""


def _aligned(count_series, total_series):
    """-> ``(t, count, total)`` arrays restricted to ticks present in
    BOTH rings.  The sampler appends ``.count`` then ``.total`` with one
    shared timestamp per tick under separate ring locks, so a reader
    landing between the two appends sees the newest count with no
    matching total; pairing by tail length would then shift every
    interval by one tick and can manufacture a regression that never
    happened.  Intersecting on the shared timestamps makes any torn
    read degrade to "newest tick not visible yet" instead."""
    tc, c = count_series.values()
    tt, tot = total_series.values()
    t, ic, it = np.intersect1d(tc, tt, return_indices=True)
    return t, c[ic], tot[it]


def _means_of(t, c, tot):
    """-> (t, mean) arrays of per-sample-interval histogram means from
    aligned cumulative arrays (only intervals where the count advanced
    produce a point)."""
    if len(t) < 2:
        return np.empty(0), np.empty(0)
    dc, dtot = np.diff(c), np.diff(tot)
    keep = dc > 0
    with np.errstate(divide="ignore", invalid="ignore"):
        means = np.where(keep, dtot / np.maximum(dc, 1), 0.0)
    return t[1:][keep], means[keep]


def _interval_means(count_series, total_series):
    """-> (t, mean) per-interval means of a cumulative ``.count`` /
    ``.total`` ring pair (torn-read-safe via :func:`_aligned`)."""
    return _means_of(*_aligned(count_series, total_series))


class StepTimeRegression(Rule):
    """Recent mean of ``<metric>`` (a registry histogram sampled as
    ``.count``/``.total`` series) > ``factor`` x the median of earlier
    interval means, AND slower by at least ``min_abs_s`` absolute.
    The absolute floor is the anti-noise half of the contract: 2x of a
    1 ms step is scheduler jitter, 2x of a 1 s step is an incident —
    a ratio alone cannot tell them apart on fast steps."""

    def __init__(self, metric="perf.phase.step", factor=2.0,
                 recent_s=10.0, min_count=2, min_baseline=3,
                 min_abs_s=0.01):
        self.metric = str(metric)
        self.name = f"step_time_regression.{self.metric}" \
            if self.metric != "perf.phase.step" else "step_time_regression"
        self.factor = float(factor)
        self.recent_s = float(recent_s)
        self.min_count = int(min_count)
        self.min_baseline = int(min_baseline)
        self.min_abs_s = float(min_abs_s)
        self._since_t = 0.0

    def reset(self, now=None):
        """Phase boundary (quiesce): the rings outlive a workload, so
        the rule must forget them itself — judging workload B's compile
        era against workload A's millisecond baseline would page the
        operator for a normal warm-up.  Points at/before the boundary
        are ignored; the rule stays quiet until ``min_baseline`` NEW
        interval means accumulate, exactly like process start."""
        self._since_t = time.time() if now is None else float(now)

    def evaluate(self, now):
        sc = timeseries.get(f"{self.metric}.count")
        st = timeseries.get(f"{self.metric}.total")
        if sc is None or st is None:
            return False, {}
        ta, c, tot = _aligned(sc, st)
        if self._since_t:
            keep = ta > self._since_t
            ta, c, tot = ta[keep], c[keep], tot[keep]
        t, means = _means_of(ta, c, tot)
        if not len(means):
            return False, {}
        cut = float(now) - self.recent_s
        recent, baseline = means[t > cut], means[t <= cut]
        if len(baseline) < self.min_baseline or not len(recent):
            return False, {}
        # recent WEIGHTED mean from the cumulative deltas across the
        # cut, on the same aligned post-boundary view
        i = int(np.searchsorted(ta, cut, side="right")) - 1
        if i < 0 or c[-1] - c[i] < self.min_count:
            return False, {}
        recent_mean = (tot[-1] - tot[i]) / (c[-1] - c[i])
        base = float(np.median(baseline))
        firing = (base > 0 and recent_mean > self.factor * base
                  and recent_mean - base > self.min_abs_s)
        phase = self.metric.rsplit(".", 1)[-1]
        return firing, {"metric": self.metric, "phase": phase,
                        "recent_mean_s": round(float(recent_mean), 6),
                        "baseline_median_s": round(base, 6),
                        "factor": self.factor,
                        "min_abs_s": self.min_abs_s}


class ThroughputStall(Rule):
    """A previously-advancing counter has not advanced in ``window_s``.

    Stateful across ticks by design: judging the stall from the ring's
    retained span alone would (a) blind the rule whenever the ring
    covers less than ``window_s`` (512 points at a 0.1 s cadence retain
    51 s — a 60 s stall could never fire) and (b) falsely CLEAR a
    still-ongoing stall once the flat period scrolls the last advance
    out of the ring.  Tracking the last-advance instant in the rule —
    evaluated every sampler tick, like all rules — has neither failure
    mode.  A counter that never advanced stays quiet (idle != stalled).

    ``pending_metric``: optional gauge naming the outstanding work
    (e.g. ``serve.pending``).  While that gauge exists and reads <= 0
    the stall clock is HELD — a serving host with no offered load is
    idle, not wedged, and must not page the operator after every quiet
    hour.  A process where the gauge was never recorded (pure
    training: no serving engine) is unaffected.
    """

    def __init__(self, metric="perf.dispatches", window_s=60.0,
                 pending_metric=None):
        self.metric = str(metric)
        self.name = f"throughput_stall.{self.metric}"
        self.window_s = float(window_s)
        self.pending_metric = str(pending_metric) if pending_metric \
            else None
        self.reset()

    def reset(self):
        """Disarm: post-reset quiet is idle, not a stall — the
        quiesce() hook for deliberate completions (train end, drain)."""
        self._last = None            # last observed value
        self._last_advance_t = None  # when it last grew
        self._advanced = False       # grew at least once since armed

    def evaluate(self, now):
        s = timeseries.get(self.metric)
        if s is None:
            return False, {}
        latest = s.latest
        if latest is None:
            return False, {}
        t, v = latest
        if self._last is None:
            self._last = v           # arm on first sight — not growth
            return False, {}
        if v > self._last:
            self._advanced = True
            self._last_advance_t = t
        self._last = v
        if not self._advanced:
            return False, {}
        if self.pending_metric is not None:
            p = timeseries.get(self.pending_metric)
            pl = p.latest if p is not None else None
            if pl is not None and pl[1] <= 0:
                # nothing outstanding: quiet is idle — hold the stall
                # clock so only time spent with work pending counts
                self._last_advance_t = now
                return False, {}
        stalled_s = float(now) - float(self._last_advance_t)
        return stalled_s >= self.window_s, {
            "metric": self.metric,
            "stalled_s": round(stalled_s, 3),
            "last_value": float(v)}


class QueueDepthGrowth(Rule):
    """A gauge rising monotonically over the last ``samples`` ticks,
    ending at/above ``min_depth``."""

    def __init__(self, metric="serve.pending", samples=5, min_depth=16):
        self.metric = str(metric)
        self.name = f"queue_depth_growth.{self.metric}"
        self.samples = int(samples)
        self.min_depth = float(min_depth)

    def evaluate(self, now):
        s = timeseries.get(self.metric)
        if s is None:
            return False, {}
        _, v = s.values()
        if len(v) < self.samples:
            return False, {}
        w = v[-self.samples:]
        firing = bool(np.all(np.diff(w) >= 0) and w[-1] > w[0]
                      and w[-1] >= self.min_depth)
        return firing, {"metric": self.metric, "depth": float(w[-1]),
                        "grew_from": float(w[0]),
                        "samples": self.samples}


class HeartbeatQuiet(Rule):
    """Heartbeat-evidence dead peers under ``DK_COORD_DIR`` — the
    watchdog-plane mirror of the coordination layer's typed
    ``PeerLost``, but continuous (an alert while the run still limps)
    instead of terminal."""

    name = "heartbeat_quiet"

    def evaluate(self, now):
        d = knobs.raw("DK_COORD_DIR")
        if not d:
            return False, {}
        try:
            world = int(knobs.raw("DK_COORD_WORLD") or 0)
        except ValueError:
            return False, {}
        if world < 2:
            return False, {}
        from dist_keras_tpu.resilience import coordination

        dead = coordination.dead_peers_at(d, world, require_file=True)
        return bool(dead), {"ranks": sorted(dead), "world": world}


def default_rules():
    """The standard production set — step-time regression, dispatch
    stall, serving completion stall, serving queue growth, quiet
    hosts.  Both stall rules gate on ``serve.pending`` so an idle
    serving host reads as idle, never as a stall; in a pure training
    process that gauge is never recorded and the gate is inert (the
    narrow cost: a co-resident idle serving engine holds the dispatch
    stall clock during training — a missed page there beats paging
    every host on every quiet night).  With ``DK_SLO`` armed the set
    also carries ``slo.SLOBurnRate`` (lazy import: slo depends on this
    module for the ``Rule`` base, so the reach-back stays inside the
    function body)."""
    rules = [
        StepTimeRegression(),
        ThroughputStall("perf.dispatches", pending_metric="serve.pending"),
        ThroughputStall("serve.completed", pending_metric="serve.pending"),
        QueueDepthGrowth("serve.pending"),
        HeartbeatQuiet(),
    ]
    try:
        from dist_keras_tpu.observability import slo

        rules.extend(slo.burn_rules())
    # dklint: ignore[broad-except] a broken SLO plane degrades to the classic rule set
    except Exception:  # pragma: no cover - slo plane optional
        pass
    return rules


class Watchdog:
    """Evaluate rules; emit typed alerts on transitions only.

    ``alert_sink``: optional callable receiving each alert dict — the
    pluggable seam the ISSUE names; alerts ALSO route through
    ``resilience.supervisor.alert`` (registered sinks + the
    ``DK_ALERT_CMD`` webhook-command), so one operator hook covers
    supervisor giveups and watchdog alerts alike.
    """

    def __init__(self, rules=None, alert_sink=None, clear_checks=2):
        self.rules = list(rules) if rules is not None else default_rules()
        self.alert_sink = alert_sink
        self.clear_checks = max(1, int(clear_checks))
        self.alerts = []   # every alert ever fired (introspection)
        self._state = {}   # rule -> {"firing": bool, "clears": int}
        self._warned = set()
        self._lock = threading.Lock()

    def firing(self):
        """Names of the rules currently in the firing state."""
        with self._lock:
            return sorted(r.name for r, st in self._state.items()
                          if st["firing"])

    def quiesce(self):
        """A workload phase ended DELIBERATELY (train end, serving
        drain): reset every rule's accumulated state so the quiet that
        follows completion is idle, not anomaly.  Without this, a
        completed run's dispatch counter stops advancing forever and
        ``ThroughputStall`` would page the operator for every run that
        succeeded.  Already-firing alerts clear through the normal
        hysteresis as the reset rules report not-firing."""
        for rule in self.rules:
            try:
                rule.reset()
            # dklint: ignore[broad-except] a broken rule reset degrades to a one-time warning
            except Exception as e:
                self._warn_once(rule, e)

    def _warn_once(self, rule, e):
        if rule.name in self._warned:
            return
        self._warned.add(rule.name)
        print(f"[dk.watchdog] WARNING: rule {rule.name!r} raised "
              f"{e!r} — treated as not-firing", file=sys.stderr,
              flush=True)

    def _deliver(self, alert):
        # the ONE alert seam: supervisor sinks + DK_ALERT_CMD, then the
        # watchdog-local callback; all best-effort — alerting must
        # never be the thing that kills the run it watches
        try:
            from dist_keras_tpu.resilience import supervisor

            supervisor.alert("watchdog_alert", **alert)
        # dklint: ignore[broad-except] the alert seam never raises into the sampler thread
        except Exception:  # pragma: no cover - alert seam never raises
            pass
        if self.alert_sink is not None:
            try:
                self.alert_sink(alert)
            # dklint: ignore[broad-except] a broken alert_sink warns; alerting must not kill the run
            except Exception as e:
                print(f"[dk.watchdog] WARNING: alert_sink raised {e!r}",
                      file=sys.stderr, flush=True)

    def check(self, now=None):
        """Evaluate every rule once; -> the alerts fired THIS check
        (transitions only)."""
        now = time.time() if now is None else float(now)
        fired = []
        for rule in self.rules:
            try:
                firing, fields = rule.evaluate(now)
            # dklint: ignore[broad-except] a broken rule degrades to not-firing + one warning
            except Exception as e:
                self._warn_once(rule, e)
                firing, fields = False, {}
            with self._lock:
                st = self._state.setdefault(
                    rule, {"firing": False, "clears": 0})
                if firing:
                    st["clears"] = 0
                    transition = not st["firing"]
                    st["firing"] = True
                else:
                    transition = False
                    if st["firing"]:
                        st["clears"] += 1
                        if st["clears"] >= self.clear_checks:
                            st["firing"] = False
                            st["clears"] = 0
                            events.emit("watchdog_clear", rule=rule.name)
                            # dklint: metrics=watchdog.firing.*
                            metrics.gauge(
                                f"watchdog.firing.{rule.name}").set(0)
            if transition:
                alert = {"rule": rule.name, "t": now, **fields}
                # dump the flight recorder FIRST and stamp the path
                # into the alert payload: the DK_ALERT_CMD webhook line
                # then names the artifact to open, not just the
                # symptom — an alert is actionable without shell
                # archaeology.  Transition-only cadence bounds the I/O.
                try:
                    from dist_keras_tpu.observability import flight

                    dump_path = flight.dump("watchdog_alert",
                                            rule=rule.name)
                # dklint: ignore[broad-except] a failed dump must not block the alert delivery
                except Exception:  # pragma: no cover - dump optional
                    dump_path = None
                if dump_path is not None:
                    alert["dump_path"] = dump_path
                self.alerts.append(alert)
                fired.append(alert)
                events.emit("watchdog_alert", **alert)
                metrics.counter("watchdog.alerts").inc()
                # dklint: metrics=watchdog.firing.*
                metrics.gauge(f"watchdog.firing.{rule.name}").set(1)
                self._deliver(alert)
        return fired
