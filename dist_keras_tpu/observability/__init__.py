"""Run telemetry: structured events, metrics registry, span tracing,
multi-host run reports.

The reference's only instrumentation is trainer wall-clock timing
(``record_training_start/stop``); this subsystem is the §5 "tracing" row
grown to production shape, recording what a run was *doing* — so a hang,
a ``BarrierTimeout`` or an unresponsive backend leaves a timeline naming
the host and phase that stalled instead of silence:

- :mod:`~dist_keras_tpu.observability.events` — append-only per-host
  JSONL under ``DK_OBS_DIR`` (atomic line writer; zero-cost no-op when
  the env is unset; never throws into training code).  Every seam emits
  typed events: epoch ends, chunk boundaries, checkpoint
  save/promote/restore, retry attempts, fault-point fires, preemption
  signals, coordination votes/barriers with durations, dead-peer
  transitions, NaN-sentinel hits.
- :mod:`~dist_keras_tpu.observability.metrics` — process-wide named
  counters/gauges/histograms (the grown-up ``StepTimer``, which is now a
  thin wrapper); snapshots ride the event stream at epoch boundaries.
- :mod:`~dist_keras_tpu.observability.spans` — distributed tracing:
  nested ``span(name)`` regions minting ``trace_id``/``span_id``/
  ``parent_id``, capturable/resumable across threads, propagated
  cross-process via a ``traceparent`` header and the ``DK_TRACE_ID``
  env; forwarded to ``jax.profiler.TraceAnnotation`` while a device
  trace is active.
- :mod:`~dist_keras_tpu.observability.flight` — crash-safe flight
  recorder: a bounded ring of recent records, dumped to ``DK_OBS_DIR``
  on watchdog alerts, preemption, unhandled crash, or ``/tracez``.
- :mod:`~dist_keras_tpu.observability.trace_export` — Chrome
  trace-event (Perfetto-loadable) export + per-trace connectivity
  report; CLI ``--perfetto`` / ``--traces`` / ``--dumps``.
- :mod:`~dist_keras_tpu.observability.statusz` — the shared
  ``/statusz`` build/config/open-span renderer both HTTP servers serve.
- :mod:`~dist_keras_tpu.observability.report` — merge per-host logs
  into one (time, rank)-ordered timeline with per-phase summaries;
  also the CLI: ``python -m dist_keras_tpu.observability <dir>``
  (``--perf`` adds the perf-attribution + watchdog section).
- :mod:`~dist_keras_tpu.observability.timeseries` — bounded per-metric
  ``(t, value)`` rings sampled from the registry by a background
  ``MetricsSampler`` at ``DK_OBS_SAMPLE_S`` — post-mortem snapshots
  grown into a live, queryable signal.
- :mod:`~dist_keras_tpu.observability.perf` — always-on CPU-measurable
  perf attribution: jit retrace/trace counts, dispatch counts, H2D/D2H
  bytes+walls, per-phase (data/step/comm/ckpt) host wall histograms.
- :mod:`~dist_keras_tpu.observability.watchdog` — declarative anomaly
  rules over the time series (step-time regression, throughput stall,
  queue growth, quiet hosts) -> typed ``watchdog_alert`` events + the
  ``resilience.supervisor`` alert seam.
- :mod:`~dist_keras_tpu.observability.prometheus` — text exposition of
  the registry; serving ``/metricsz?format=prometheus`` and the
  standalone per-host ``DK_METRICS_PORT`` exporter serve it.

See the README "Observability" section for the env knobs
(``DK_OBS_DIR`` / ``DK_OBS_FLUSH``), the event schema table and CLI
examples.
"""

import importlib

from dist_keras_tpu.observability import events, metrics, report, spans
from dist_keras_tpu.observability.events import (
    EventWriter,
    emit,
    enabled,
    obs_dir,
)
from dist_keras_tpu.observability.metrics import (
    counter,
    emit_snapshot,
    gauge,
    histogram,
    snapshot,
    to_prometheus,
)
from dist_keras_tpu.observability.spans import span

# the telemetry plane (sampler thread, watchdog rules, http exposition)
# resolves lazily: every process imports `events` at startup — through
# checkpoint/faults/retry — and must not pay for numpy rule math or
# http.server unless it actually arms the sampler or an exporter
_LAZY = {
    "flight": "dist_keras_tpu.observability.flight",
    "perf": "dist_keras_tpu.observability.perf",
    "prometheus": "dist_keras_tpu.observability.prometheus",
    "statusz": "dist_keras_tpu.observability.statusz",
    "timeseries": "dist_keras_tpu.observability.timeseries",
    "trace_export": "dist_keras_tpu.observability.trace_export",
    "watchdog": "dist_keras_tpu.observability.watchdog",
    "Exporter": ("dist_keras_tpu.observability.prometheus", "Exporter"),
    "MetricsSampler": ("dist_keras_tpu.observability.timeseries",
                       "MetricsSampler"),
    "TimeSeries": ("dist_keras_tpu.observability.timeseries",
                   "TimeSeries"),
    "Watchdog": ("dist_keras_tpu.observability.watchdog", "Watchdog"),
}


def __getattr__(name):
    spec = _LAZY.get(name)
    if spec is None:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}")
    if isinstance(spec, tuple):
        value = getattr(importlib.import_module(spec[0]), spec[1])
    else:
        value = importlib.import_module(spec)
    globals()[name] = value  # resolve once
    return value


def __dir__():
    return sorted(set(globals()) | set(_LAZY))

__all__ = [
    "events", "flight", "metrics", "perf", "prometheus", "report",
    "spans", "statusz", "timeseries", "trace_export", "watchdog",
    "EventWriter", "emit", "enabled", "obs_dir",
    "counter", "gauge", "histogram", "snapshot", "emit_snapshot",
    "to_prometheus", "span",
    "TimeSeries", "MetricsSampler", "Watchdog", "Exporter",
]
