"""Run telemetry: structured events, metrics registry, span tracing,
multi-host run reports.

The reference's only instrumentation is trainer wall-clock timing
(``record_training_start/stop``); this subsystem is the §5 "tracing" row
grown to production shape, recording what a run was *doing* — so a hang,
a ``BarrierTimeout`` or an unresponsive backend leaves a timeline naming
the host and phase that stalled instead of silence:

- :mod:`~dist_keras_tpu.observability.events` — append-only per-host
  JSONL under ``DK_OBS_DIR`` (atomic line writer; zero-cost no-op when
  the env is unset; never throws into training code).  Every seam emits
  typed events: epoch ends, chunk boundaries, checkpoint
  save/promote/restore, retry attempts, fault-point fires, preemption
  signals, coordination votes/barriers with durations, dead-peer
  transitions, NaN-sentinel hits.
- :mod:`~dist_keras_tpu.observability.metrics` — process-wide named
  counters/gauges/histograms (the grown-up ``StepTimer``, which is now a
  thin wrapper); snapshots ride the event stream at epoch boundaries.
- :mod:`~dist_keras_tpu.observability.spans` — nested ``span(name)``
  regions stamped into the event log and forwarded to
  ``jax.profiler.TraceAnnotation`` while a device trace is active.
- :mod:`~dist_keras_tpu.observability.report` — merge per-host logs
  into one (time, rank)-ordered timeline with per-phase summaries;
  also the CLI: ``python -m dist_keras_tpu.observability <dir>``.

See the README "Observability" section for the env knobs
(``DK_OBS_DIR`` / ``DK_OBS_FLUSH``), the event schema table and CLI
examples.
"""

from dist_keras_tpu.observability import events, metrics, report, spans
from dist_keras_tpu.observability.events import (
    EventWriter,
    emit,
    enabled,
    obs_dir,
)
from dist_keras_tpu.observability.metrics import (
    counter,
    emit_snapshot,
    gauge,
    histogram,
    snapshot,
)
from dist_keras_tpu.observability.spans import span

__all__ = [
    "events", "metrics", "report", "spans",
    "EventWriter", "emit", "enabled", "obs_dir",
    "counter", "gauge", "histogram", "snapshot", "emit_snapshot",
    "span",
]
