"""Chrome trace-event export — open a run in Perfetto.

Turns a merged event timeline (``report.read_events``) or a set of
flight-recorder dumps (``flight.read_dumps``) into Chrome trace-event
JSON (the ``{"traceEvents": [...]}`` shape ``ui.perfetto.dev`` and
``chrome://tracing`` both load):

- every ``span_end`` record becomes a complete ("X") slice — track =
  (rank as pid, emitting thread as tid), wall-clock microseconds,
  ``args`` carrying the trace identity (``trace_id``/``span_id``/
  ``parent_id``) and the span's own fields;
- a parent→child edge that crosses a thread or a host becomes a flow
  arrow ("s"/"f" pair keyed by the child's span id) — the serving
  handler→batcher→replica handoff and the trainer→async-writer
  checkpoint handoff render as connected arrows, and two hosts' dumps
  stitch into one timeline because both sides carry the same
  ``trace_id``;
- breadcrumb events (``chunk``, ``ckpt_save``, ``preempt``,
  ``watchdog_alert``, ...) become thread-scoped instants so the
  incident context sits inline with the slices.

:func:`connected_traces` is the verification half: it groups spans by
``trace_id`` and reports, per trace, the roots (no ``parent_id``), any
ORPHANS (a ``parent_id`` that resolves to no span in the trace — a
broken link), and which edges crossed threads/ranks — the ``--obs-only``
gate asserts every serving request is one fully connected trace.
"""

from __future__ import annotations

import json

# registry snapshots and sampler ticks are bulk payloads, not moments —
# rendering them as instants buries the timeline
_SKIP_INSTANTS = ("metrics", "perf_sample", "span_begin", "span_end")


def _span_ends(records, trace_id=None):
    out = []
    for ev in records:
        if ev.get("kind") != "span_end":
            continue
        if trace_id is not None and ev.get("trace_id") != trace_id:
            continue
        out.append(ev)
    return out


def _slice_ts_us(ev):
    """Slice start in wall-clock microseconds: ``span_at`` records
    carry an explicit ``t0``; live spans emit ``span_end`` right at the
    end, so start = emit time - duration."""
    dur = float(ev.get("duration_s", 0.0) or 0.0)
    t0 = ev.get("t0")
    if t0 is None:
        t0 = float(ev.get("t", 0.0)) - dur
    return float(t0) * 1e6, dur * 1e6


def chrome_trace(records, trace_id=None, instants=True):
    """-> the Chrome trace-event document for a merged timeline.

    ``records``: ``report.read_events`` or ``flight.read_dumps``
    output.  ``trace_id`` restricts the export to one trace (spans
    only; instants are rank-wide context and stay unless ``instants``
    is off)."""
    spans = _span_ends(records, trace_id=trace_id)
    events = []
    seen_tracks = {}  # (pid, tid) -> True (thread_name metadata once)
    seen_pids = set()
    index = {}        # span_id -> (pid, tid, ts_us)
    for ev in spans:
        pid = int(ev.get("rank", 0))
        tid = int(ev.get("tid", 0) or 0)
        ts, dur = _slice_ts_us(ev)
        sid = ev.get("span_id")
        if sid:
            index[sid] = (pid, tid, ts)
        if pid not in seen_pids:
            seen_pids.add(pid)
            events.append({"ph": "M", "name": "process_name", "pid": pid,
                           "tid": 0, "args": {"name": f"rank {pid}"}})
        if (pid, tid) not in seen_tracks:
            seen_tracks[(pid, tid)] = True
            events.append({"ph": "M", "name": "thread_name", "pid": pid,
                           "tid": tid, "args": {"name": f"tid {tid}"}})
        args = {k: v for k, v in ev.items()
                if k not in ("t", "seq", "kind", "tid", "t0")}
        events.append({"ph": "X", "name": str(ev.get("span", "?")),
                       "cat": "span", "pid": pid, "tid": tid,
                       "ts": ts, "dur": max(dur, 1.0), "args": args})
    # flow arrows for every cross-thread / cross-host parent edge
    for ev in spans:
        parent = ev.get("parent_id")
        sid = ev.get("span_id")
        if not parent or parent not in index or not sid:
            continue
        ppid, ptid, pts = index[parent]
        cpid = int(ev.get("rank", 0))
        ctid = int(ev.get("tid", 0) or 0)
        if (ppid, ptid) == (cpid, ctid):
            continue  # same track: nesting already shows the edge
        cts, _ = _slice_ts_us(ev)
        events.append({"ph": "s", "cat": "handoff", "name": "handoff",
                       "id": sid, "pid": ppid, "tid": ptid, "ts": pts})
        events.append({"ph": "f", "cat": "handoff", "name": "handoff",
                       "bp": "e", "id": sid, "pid": cpid, "tid": ctid,
                       "ts": max(cts, pts)})
    # critical-path flow arrows (round 22): the dominant chain of each
    # request renders as its own arrow family, so Perfetto shows WHERE
    # a slow request's time went without hand-tracing the tree
    traces = {}
    for ev in spans:
        tr = ev.get("trace_id")
        if tr:
            traces.setdefault(tr, []).append(ev)
    for tr, tspans in sorted(traces.items()):
        cp = critical_path(tspans)
        if cp is None or len(cp["path"]) < 2:
            continue
        for parent_hop, child_hop in zip(cp["path"], cp["path"][1:]):
            sid = child_hop.get("span_id")
            if not sid or sid not in index \
                    or parent_hop.get("span_id") not in index:
                continue
            ppid, ptid, pts = index[parent_hop["span_id"]]
            cpid, ctid, cts = index[sid]
            events.append({"ph": "s", "cat": "critical_path",
                           "name": "critical_path", "id": f"cp-{sid}",
                           "pid": ppid, "tid": ptid, "ts": pts})
            events.append({"ph": "f", "cat": "critical_path",
                           "name": "critical_path", "bp": "e",
                           "id": f"cp-{sid}", "pid": cpid, "tid": ctid,
                           "ts": max(cts, pts)})
    if instants:
        for ev in records:
            kind = ev.get("kind", "?")
            if kind in _SKIP_INSTANTS:
                continue
            events.append({
                "ph": "i", "s": "t", "name": str(kind), "cat": "event",
                "pid": int(ev.get("rank", 0)),
                "tid": int(ev.get("tid", 0) or 0),
                "ts": float(ev.get("t", 0.0)) * 1e6,
                "args": {k: v for k, v in ev.items()
                         if k not in ("t", "seq", "kind", "tid")}})
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def write_chrome_trace(path, records, trace_id=None, instants=True):
    """Write :func:`chrome_trace` output to ``path``; -> the event
    count (load the file at ``ui.perfetto.dev`` → "Open trace file")."""
    doc = chrome_trace(records, trace_id=trace_id, instants=instants)
    with open(path, "w") as f:
        json.dump(doc, f, default=str)
    return len(doc["traceEvents"])


# span name -> hop category for the per-request latency attribution
# table (suffix-matched on the dotted path, so a root nested under an
# outer span still classifies)
_HOP_CATEGORY = {
    "serve.client": "client_overhead",
    "route.forward": "forward_hop",
    "serve.request": "host_overhead",
    "serve.queue_wait": "queue_wait",
    "serve.batch": "batch",
    "serve.exec": "replica_compute",
    "serve.reload": "reload_stall",
}


def _category(span_name):
    path = str(span_name)
    for name, cat in _HOP_CATEGORY.items():
        if path == name or path.endswith("." + name):
            return cat
    return "other"


def _interval_s(ev):
    ts, dur = _slice_ts_us(ev)
    return ts / 1e6, (ts + dur) / 1e6


def _union_len(intervals):
    """Total length of a union of (a, b) intervals."""
    total, end = 0.0, None
    for a, b in sorted(intervals):
        if b <= a:
            continue
        if end is None or a >= end:
            total += b - a
            end = b
        elif b > end:
            total += b - end
            end = b
    return total


def critical_path(spans):
    """Per-request latency attribution over ONE trace's ``span_end``
    records (possibly spanning ranks — the router-stitched tree).

    -> ``{"trace_id", "root", "rank", "total_s", "path": [hop, ...],
    "by_category": {category: seconds}, "critical": hop}`` or None
    when the trace has no usable root.

    Two complementary views of the same tree:

    - ``by_category``: exact decomposition of the root's elapsed time
      by hop SELF time (duration minus the union of direct children's
      overlap), so queue wait vs forward hop vs replica compute vs
      reload stall sum to the total — nothing double-counted, nothing
      lost;
    - ``path``: the dominant chain root -> deepest hop, descending
      into the longest child at each level (each hop:
      ``{"span", "category", "rank", "tid", "duration_s", "self_s"}``)
      — the "where did THIS request's time go" answer; ``critical``
      is the single hop with the largest self time anywhere in the
      tree (the one to fix).
    """
    by_id = {ev["span_id"]: ev for ev in spans if ev.get("span_id")}
    children = {}
    roots = []
    for ev in spans:
        parent = ev.get("parent_id")
        if parent and parent in by_id:
            children.setdefault(parent, []).append(ev)
        else:
            roots.append(ev)
    if not roots:
        return None
    root = max(roots, key=lambda ev: float(ev.get("duration_s", 0.0)
                                           or 0.0))

    def _hop(ev):
        a, b = _interval_s(ev)
        kids = children.get(ev.get("span_id"), ())
        overlap = _union_len(
            [(max(a, ka), min(b, kb))
             for ka, kb in (_interval_s(k) for k in kids)])
        return {
            "span": str(ev.get("span", "?")),
            "category": _category(ev.get("span", "")),
            "rank": int(ev.get("rank", 0)),
            "tid": int(ev.get("tid", 0) or 0),
            "span_id": ev.get("span_id"),
            "duration_s": round(max(0.0, b - a), 6),
            "self_s": round(max(0.0, (b - a) - overlap), 6),
        }

    # exact decomposition: every reachable node's self time, grouped
    by_category = {}
    hops = []
    stack = [root]
    seen = set()
    while stack:
        ev = stack.pop()
        sid = ev.get("span_id")
        if sid in seen:
            continue
        seen.add(sid)
        hop = _hop(ev)
        hops.append(hop)
        by_category[hop["category"]] = round(
            by_category.get(hop["category"], 0.0) + hop["self_s"], 6)
        stack.extend(children.get(sid, ()))
    # the dominant chain: descend into the longest child each level
    path = []
    ev = root
    while ev is not None:
        path.append(_hop(ev))
        kids = children.get(ev.get("span_id"), ())
        ev = max(kids, key=lambda k: float(k.get("duration_s", 0.0)
                                           or 0.0)) if kids else None
    critical = max(hops, key=lambda h: h["self_s"])
    return {
        "trace_id": root.get("trace_id"),
        "root": str(root.get("span", "?")),
        "rank": int(root.get("rank", 0)),
        "total_s": round(float(root.get("duration_s", 0.0) or 0.0), 6),
        "path": path,
        "by_category": by_category,
        "critical": critical,
    }


def request_paths(records, worst=None):
    """:func:`critical_path` for every trace in a merged timeline,
    sorted worst-first by root duration (``worst`` caps the list) —
    the report's exemplar-linked worst-N table: each row's
    ``trace_id`` is exactly what a scrape exemplar references."""
    traces = {}
    for ev in _span_ends(records):
        tr = ev.get("trace_id")
        if tr:
            traces.setdefault(tr, []).append(ev)
    out = []
    for spans in traces.values():
        cp = critical_path(spans)
        if cp is not None:
            out.append(cp)
    out.sort(key=lambda cp: cp["total_s"], reverse=True)
    return out[:worst] if worst else out


def connected_traces(records):
    """Connectivity report per ``trace_id`` over the ``span_end``
    records of a merged timeline:

    ``{trace_id: {"spans": n, "roots": [span names], "orphans":
    [span names], "ranks": [...], "cross_thread": n, "cross_rank": n,
    "connected": bool}}``

    ``connected`` means every span reaches a root of its trace via
    ``parent_id`` links — the acceptance shape for "one request is one
    trace"."""
    traces = {}
    for ev in _span_ends(records):
        tr = ev.get("trace_id")
        if not tr:
            continue
        traces.setdefault(tr, []).append(ev)
    out = {}
    for tr, spans in traces.items():
        ids = {ev["span_id"]: ev for ev in spans if ev.get("span_id")}
        roots, orphans = [], []
        cross_thread = cross_rank = 0
        for ev in spans:
            parent = ev.get("parent_id")
            if parent is None:
                roots.append(ev.get("span", "?"))
            elif parent not in ids:
                orphans.append(ev.get("span", "?"))
            else:
                pev = ids[parent]
                if pev.get("rank") != ev.get("rank"):
                    cross_rank += 1
                elif pev.get("tid") != ev.get("tid"):
                    cross_thread += 1
        out[tr] = {
            "spans": len(spans),
            "roots": sorted(roots),
            "orphans": sorted(orphans),
            "ranks": sorted({int(ev.get("rank", 0)) for ev in spans}),
            "cross_thread": cross_thread,
            "cross_rank": cross_rank,
            "connected": not orphans and bool(roots),
        }
    return out


def render_traces(records):
    """Human-readable per-trace connectivity summary (the CLI's
    ``--traces`` section)."""
    traces = connected_traces(records)
    if not traces:
        return ("no traced spans found (spans carry trace ids when "
                "DK_OBS_DIR was set during the run)")
    lines = [f"# traces ({len(traces)})"]
    for tr, row in sorted(traces.items()):
        mark = "ok " if row["connected"] else "BROKEN"
        lines.append(
            f"{mark} {tr}: {row['spans']} spans, roots "
            f"{row['roots']}, ranks {row['ranks']}, "
            f"{row['cross_thread']} thread-handoffs, "
            f"{row['cross_rank']} host-handoffs"
            + (f", ORPHANS {row['orphans']}" if row["orphans"] else ""))
    return "\n".join(lines)
