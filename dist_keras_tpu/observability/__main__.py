"""CLI for the run report — ``python -m dist_keras_tpu.observability``.

  # human-readable timeline summary + last-N events per host
  python -m dist_keras_tpu.observability /path/to/obs_dir [--last 20]

  # machine-readable: the merged summary (or the full merged timeline)
  python -m dist_keras_tpu.observability /path/to/obs_dir --json
  python -m dist_keras_tpu.observability /path/to/obs_dir --json --raw

  # perf attribution: retraces/dispatches/transfers per rank, the
  # data/step/comm/ckpt host-wall breakdown, watchdog alerts
  python -m dist_keras_tpu.observability /path/to/obs_dir --perf

  # SLOs: objective status + burn rates per window at alert time, and
  # the worst-N retained requests with critical-path attribution
  python -m dist_keras_tpu.observability /path/to/obs_dir --slo \
      [--worst 5]

  # tracing: stitch the multi-host timeline into Perfetto-loadable
  # Chrome trace JSON (open at ui.perfetto.dev), or summarize trace
  # connectivity per trace_id
  python -m dist_keras_tpu.observability /path/to/obs_dir \
      --perfetto trace.json [--trace <trace_id>]
  python -m dist_keras_tpu.observability /path/to/obs_dir --traces
  # --dumps sources records from the flight-recorder dumps
  # (flightrec-*.json) instead of the event log — the crash-time tail
  # when the run died before flushing its log
  python -m dist_keras_tpu.observability /path/to/obs_dir \
      --dumps --perfetto crash.json

Point it at the directory a run exported as ``DK_OBS_DIR`` (for a pod
job launched with ``Job(obs_dir=...)``, the launcher's
``collect_obs(dest)`` rsyncs every host's directory back first).
Exit code 1 when the directory holds no events — a monitoring loop can
distinguish "nothing recorded" from an empty-but-healthy run.
"""

from __future__ import annotations

import argparse
import json
import sys

from dist_keras_tpu.observability import report


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="python -m dist_keras_tpu.observability",
        description="Merge per-host DK_OBS_DIR event logs into one "
                    "timeline and summarize the run.")
    ap.add_argument("obs_dir", help="directory holding "
                                    "events-rank_*.jsonl files")
    ap.add_argument("--last", type=int, default=10,
                    help="events per host in the tail section "
                         "(default 10)")
    ap.add_argument("--json", action="store_true",
                    help="print the merged summary as JSON")
    ap.add_argument("--raw", action="store_true",
                    help="with --json: print the full merged event "
                         "timeline instead of the summary")
    ap.add_argument("--perf", action="store_true",
                    help="append the perf-attribution section: per-"
                         "rank retrace/dispatch/transfer totals, the "
                         "data/step/comm/ckpt host-wall breakdown, "
                         "and every watchdog alert in the timeline "
                         "(with --json: a 'perf' key on the summary)")
    ap.add_argument("--slo", action="store_true",
                    help="append the SLO section: per-objective "
                         "burn-rate status from the slo_burn_rate "
                         "alerts in the timeline plus the worst-N "
                         "retained requests with critical-path "
                         "attribution (with --json: a 'slo' key on "
                         "the summary)")
    ap.add_argument("--worst", type=int, default=5,
                    help="requests in the --slo critical-path section "
                         "(default 5)")
    ap.add_argument("--perfetto", metavar="PATH",
                    help="write the merged timeline as Chrome trace-"
                         "event JSON (Perfetto-loadable) to PATH")
    ap.add_argument("--dumps", action="store_true",
                    help="source records from the flight-recorder "
                         "dumps (flightrec-*.json, deduplicated and "
                         "stitched across hosts) instead of the "
                         "event log")
    ap.add_argument("--trace", metavar="TRACE_ID",
                    help="restrict --perfetto to one trace id")
    ap.add_argument("--traces", action="store_true",
                    help="print the per-trace connectivity summary "
                         "(roots, orphans, thread/host handoffs)")
    args = ap.parse_args(argv)

    if args.dumps:
        from dist_keras_tpu.observability import flight

        events = flight.read_dumps(args.obs_dir)
    else:
        events = report.read_events(args.obs_dir)

    if args.perfetto or args.traces:
        from dist_keras_tpu.observability import trace_export

        if args.perfetto:
            n = trace_export.write_chrome_trace(
                args.perfetto, events, trace_id=args.trace)
            print(f"wrote {n} trace events to {args.perfetto} "
                  "(open at ui.perfetto.dev)")
        if args.traces:
            print(trace_export.render_traces(events))
        return 0 if events else 1

    if args.json:
        doc = events if args.raw else report.summarize(events)
        if args.perf and not args.raw:
            doc["perf"] = report.perf_summary(events)
        if args.slo and not args.raw:
            doc["slo"] = report.slo_summary(events)
        json.dump(doc, sys.stdout, indent=1, default=str)
        print()
    else:
        print(report.render(args.obs_dir, last_n=args.last))
        if args.perf:
            print()
            print(report.render_perf(args.obs_dir, events=events))
        if args.slo:
            print()
            print(report.render_slo(args.obs_dir, events=events,
                                    worst=args.worst))
    return 0 if events else 1


if __name__ == "__main__":
    sys.exit(main())
