"""Structured event log — append-only, per-host JSONL under ``DK_OBS_DIR``.

The paper's only instrumentation was trainer wall-clock timing; after the
two resilience PRs this repo has retries, fault points, two-phase
checkpoint commits, coordination votes, barriers and heartbeats all
happening silently — and the r05 bench died with an unattributable
"backend unresponsive" because nothing recorded what the run was doing
when it stopped.  This module is the recording layer every seam emits
into:

- **One JSONL file per host** (``events-rank_{i}.jsonl``), so hosts never
  contend on a shared file; ``report.py`` merges them post-hoc into a
  single (time, rank)-ordered timeline.
- **Atomic line writer**: each event is serialized to one line and
  written with a single ``os.write`` on an ``O_APPEND`` fd — concurrent
  writers (the heartbeat thread, deadline probe threads) never interleave
  partial lines, and a crash mid-run loses at most the event being
  written, never the file.
- **Zero-cost when off**: with ``DK_OBS_DIR`` unset, :func:`emit` is one
  cached boolean check — no file handles, no JSON encoding, no host
  sync.  That is the tier-1 contract: instrumented seams cost nothing
  unless an operator opts in.
- **Never throws into training code**: any failure (disk full, bad
  field, closed fd) degrades to a dropped event plus ONE warning per
  process on stderr.  Observability must never be the thing that kills
  the run it observes.

Env knobs:

- ``DK_OBS_DIR`` — directory for the per-host event files (created on
  first emit).  Unset = disabled.
- ``DK_OBS_FLUSH=1`` — fsync after every line (power-loss durable;
  default is write-per-line, which already survives a process crash).
- ``DK_OBS_ROTATE_MB`` — size cap per event file: once the active
  ``events-rank_{i}.jsonl`` exceeds this many MB it is rotated to
  ``events-rank_{i}.jsonl.1`` (older segments shift to ``.2``, ``.3``,
  ...) and a fresh file is opened, so a week-long run's log stays
  bounded.  ``DK_OBS_ROTATE_KEEP`` (default 3) bounds how many rotated
  segments are retained — total disk per host is at most
  ``(keep + 1) * cap`` (+ one event).  The report merger reads rotated
  segments back in order; ``seq`` stays monotonic across rotations, so
  the merged timeline is seamless.  Unset/0 = never rotate (the
  pre-round-9 behaviour).

Event schema: every record carries ``t`` (``time.time()``), ``seq`` (a
per-process monotonic counter — the tiebreaker for same-timestamp
ordering), ``rank`` (``DK_COORD_RANK`` > ``JAX_PROCESS_ID`` > 0, read at
writer construction so no jax import is needed), ``kind``, and the
emitting seam's keyword fields.  See the README "Observability" section
for the kind-by-kind table.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time

from dist_keras_tpu.utils import knobs

_lock = threading.Lock()
_resolved = False      # has the DK_OBS_DIR decision been made?
_writer = None         # EventWriter when enabled, None when disabled
_warned = False        # one dropped-event warning per process
_ctx_provider = None   # spans.py: current trace identity per thread
_sink = None           # flight.py: in-memory ring copy of each record
_retainer = None       # flight.py: tail-based trace-retention policy


def _set_context_provider(fn):
    """Register the trace-context provider (``spans._current_ids``):
    every emitted event is stamped with the current thread's open-span
    trace identity via ``setdefault`` — so breadcrumb events stitch
    into the span tree without their seams knowing about tracing."""
    global _ctx_provider
    _ctx_provider = fn


def _set_retainer(fn):
    """Register the tail-based retention policy
    (``flight.TraceRetention.offer``): called with each fully-stamped
    record and the writer BEFORE the file write; returning True means
    the policy took custody (buffered for a keep/drop decision at
    request end) and the record is not written now.  ``None`` (the
    default, and whenever ``DK_TRACE_RETAIN`` is off) keeps the write
    path untouched."""
    global _retainer
    _retainer = fn

# The event vocabulary — every ``kind`` any seam emits (including the
# repo-root ``bench.py`` driver's).  Adding an emit("...") call site?
# Register the kind here AND add a row to the README event-schema
# table, or the ``event-unregistered`` / ``event-undocumented`` lint
# rules (``python -m dist_keras_tpu.analysis``) fail the tree.  The
# registry is deliberately a flat tuple: report.py and operator
# tooling treat it as the closed set of kinds they can attribute.
KNOWN_EVENTS = (
    # training lifecycle (trainers/base.py, trainers/chunking.py)
    "train_start", "train_end", "epoch_end", "chunk", "resume",
    "metrics",
    # spans (observability/spans.py)
    "span_begin", "span_end",
    # checkpointing (checkpoint.py)
    "ckpt_save", "ckpt_promote", "ckpt_restore", "ckpt_verify",
    "ckpt_corrupt",
    "ckpt_async_enqueue", "ckpt_async_coalesced", "ckpt_async_error",
    # differential + remote checkpoint tier (checkpoint.py,
    # resilience/store.py)
    "ckpt_diff", "ckpt_gc", "ckpt_push", "ckpt_pull",
    "ckpt_remote_prune",
    # resilience seams
    "retry", "retry_exhausted", "fault", "nonfinite", "nan_halt",
    "preempt_signal", "preempt", "preempt_exit",
    "coord", "coord_error", "barrier", "peer_dead",
    "supervisor_restart", "supervisor_giveup",
    "elastic_resize", "reshard_restore",
    # serving (serving/)
    "serve_enqueue", "serve_batch_flush", "serve_batch_error",
    "serve_predict", "serve_predict_error",
    "serve_reload", "serve_reload_error", "reload_skipped_corrupt",
    "serve_listen", "serve_drain_begin", "serve_drain_signal",
    "serve_drain",
    # serving router + autoscaler (serving/router.py,
    # serving/autoscale.py, serving/reload.py)
    "route_evict", "route_readmit", "route_cutover",
    "autoscale_resize",
    # parameter-server training mode (ps/)
    "ps_pull", "ps_commit", "ps_stale_scaled",
    "ps_worker_join", "ps_worker_lapse",
    # fused flash backward graduation (ops/pallas)
    "fused_bwd_rejected",
    # telemetry plane (observability/)
    "perf_sample", "watchdog_alert", "watchdog_clear",
    "metrics_exporter_listen", "flight_dump",
    # SLO plane (observability/slo.py)
    "slo_transition",
    # bench driver (repo-root bench.py)
    "bench_probe_begin", "bench_probe_end", "bench_config_begin",
    "bench_config_end", "bench_config_skipped", "bench_complete",
    # cluster simulator (sim/)
    "sim_scenario_begin", "sim_scenario_end",
    # continuous-batching decode engine (serving/decode.py,
    # ops/pallas/decode_attention.py)
    "decode_admit", "decode_prefill", "decode_step",
    "decode_complete", "decode_cancel", "decode_error",
    "decode_drain", "decode_kernel_rejected",
    # decode survivability (serving/decode.py): replica quarantine +
    # sequence-level recovery, deadline rejection/expiry, brownout
    # shedding, allocator self-check leak reports
    "decode_quarantine", "decode_recover", "decode_deadline",
    "decode_shed", "decode_kv_leak",
    # router hedged retries + streaming relay (serving/router.py)
    "route_hedge", "route_stream_error",
)


def _default_rank():
    """This host's rank WITHOUT importing jax (the event log must work
    before — and while — the device backend is wedged): the coordination
    identity wins, then the launcher's jax.distributed id, then 0."""
    for v in (knobs.raw("DK_COORD_RANK"),
              os.environ.get("JAX_PROCESS_ID")):
        if v is not None:
            try:
                return int(v)
            except ValueError:
                pass
    return 0


class EventWriter:
    """Append-only JSONL writer for one host's event file.

    Exposed as a class (rather than only the module-level singleton) so
    tests and launcher-side tools can write a specific rank's file
    explicitly; training code should use :func:`emit`.
    """

    def __init__(self, directory, rank=None, fsync=None,
                 rotate_bytes=None, rotate_keep=None):
        self.directory = os.path.abspath(os.path.expanduser(directory))
        self.rank = _default_rank() if rank is None else int(rank)
        if fsync is None:
            # registry bool convention ("fsync" is just another truthy
            # spelling); unset -> the registered False default
            fsync = knobs.get("DK_OBS_FLUSH")
        self.fsync = bool(fsync)
        if rotate_bytes is None:
            # registry-parsed: malformed falls back to the registered
            # default (log unbounded, not die)
            rotate_bytes = int(knobs.get("DK_OBS_ROTATE_MB") * 2**20)
        self.rotate_bytes = max(0, int(rotate_bytes))  # 0 = never rotate
        if rotate_keep is None:
            rotate_keep = int(knobs.get("DK_OBS_ROTATE_KEEP"))
        self.rotate_keep = max(1, int(rotate_keep))
        self.path = os.path.join(self.directory,
                                 f"events-rank_{self.rank}.jsonl")
        os.makedirs(self.directory, exist_ok=True)
        self._fd = os.open(self.path,
                           os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
        try:
            self._bytes = os.fstat(self._fd).st_size
        except OSError:  # pragma: no cover - exotic fs
            self._bytes = 0
        self._seq = 0
        self._lock = threading.Lock()

    def _rotate(self):
        """Shift ``path.N`` -> ``path.N+1`` (dropping past ``keep``),
        retire the active file to ``path.1``, open a fresh one.  Caller
        holds the lock; ``seq`` keeps counting, so the merged timeline
        orders seamlessly across segments.

        The OLD fd closes LAST: POSIX renames follow the open file, so
        every step up to the new ``os.open`` leaves ``self._fd`` valid —
        a rotation that dies midway (ENOSPC, a log cleaner racing the
        shifts) keeps appending to the still-open descriptor and simply
        retries at the next emit, instead of stranding a CLOSED fd
        number that a later ``os.write`` could spray into whatever
        unrelated file the process reused it for."""
        last = f"{self.path}.{self.rotate_keep}"
        if os.path.exists(last):
            os.remove(last)
        for i in range(self.rotate_keep - 1, 0, -1):
            src = f"{self.path}.{i}"
            if os.path.exists(src):
                os.replace(src, f"{self.path}.{i + 1}")
        os.replace(self.path, f"{self.path}.1")
        old = self._fd
        self._fd = os.open(self.path,
                           os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
        self._bytes = 0
        try:
            os.close(old)
        except OSError:  # pragma: no cover - double close
            pass

    def make_record(self, kind, **fields):
        """Stamp one record (``t``/``seq``/``rank``/``kind`` + fields)
        WITHOUT writing it.  Split from :meth:`write` for tail-based
        retention: a buffered record keeps its event-time stamps, so a
        trace flushed seconds later still merges into the timeline at
        the instant it happened (the report sorts by ``(t, rank,
        seq)``, not file order)."""
        with self._lock:
            seq = self._seq
            self._seq += 1
        record = {"t": time.time(), "seq": seq, "rank": self.rank,
                  "kind": str(kind)}
        record.update(fields)
        return record

    def emit(self, kind, **fields):
        """Write one event line; -> the record dict (the flight
        recorder's ring copy).  Raises on failure — the module-level
        :func:`emit` is the never-throws wrapper."""
        return self.write(self.make_record(kind, **fields))

    def write(self, record):
        """Serialize + append one already-stamped record; -> it."""
        # default=str: an event must not be droppable by an exotic field
        # type (numpy scalar, Path, exception instance)
        line = (json.dumps(record, default=str) + "\n").encode("utf-8")
        if not self.rotate_bytes:
            # unbounded log: the O_APPEND write alone is the atomicity
            # story — concurrent writers need no lock at all
            os.write(self._fd, line)
            if self.fsync:
                os.fsync(self._fd)
            return record
        # size-capped log: the write, the size check and a possible
        # rotation must be one unit, or a concurrent writer could emit
        # into a just-retired fd
        with self._lock:
            os.write(self._fd, line)
            if self.fsync:
                os.fsync(self._fd)
            self._bytes += len(line)
            if self._bytes >= self.rotate_bytes:
                self._rotate()
        return record

    def close(self):
        try:
            os.close(self._fd)
        except OSError:  # pragma: no cover - double close
            pass


def _resolve():
    global _resolved, _writer
    with _lock:
        if _resolved:
            return
        directory = knobs.raw("DK_OBS_DIR")
        if directory:
            try:
                _writer = EventWriter(directory)
            # dklint: ignore[broad-except] event-log open failure degrades to disabled + one warning
            except Exception as e:
                _warn_once(f"could not open event log in "
                           f"{directory!r}: {e!r}")
                _writer = None
        _resolved = True
    if _writer is not None:
        try:
            # the flight recorder rides the same DK_OBS_DIR gate: it
            # rings a copy of every record and arms the crash hooks
            from dist_keras_tpu.observability import flight

            flight.attach()
        # dklint: ignore[broad-except] the recorder is best-effort; the event log must come up without it
        except Exception as e:  # pragma: no cover - recorder optional
            _warn_once(f"flight recorder unavailable: {e!r}")


def _warn_once(msg):
    global _warned
    if _warned:
        return
    _warned = True
    print(f"[dk.observability] WARNING: {msg} — further events are "
          "dropped silently", file=sys.stderr, flush=True)


def enabled():
    """True iff ``DK_OBS_DIR`` selected an event log (cached; call
    :func:`reset` after changing the env)."""
    if not _resolved:
        _resolve()
    return _writer is not None


def obs_dir():
    """The active event-log directory, or None when disabled."""
    if not _resolved:
        _resolve()
    return _writer.directory if _writer is not None else None


def rank():
    """The active writer's rank (None when disabled) — lets seams make
    leader-only decisions (e.g. who writes the merged report) without
    re-deriving the identity env."""
    if not _resolved:
        _resolve()
    return _writer.rank if _writer is not None else None


def emit(kind, **fields):
    """Emit one structured event — the seam-facing entry point.

    No-op when ``DK_OBS_DIR`` is unset (one boolean check).  NEVER
    raises: a failed write degrades to a dropped event plus one warning,
    because this is called from checkpoint commits, signal-adjacent
    paths and retry loops that must not die of their own telemetry.
    """
    if not _resolved:
        _resolve()
    w = _writer
    if w is None:
        return
    try:
        prov = _ctx_provider
        if prov is not None:
            ctx = prov()
            if ctx:
                for k, v in ctx.items():
                    fields.setdefault(k, v)
        rec = w.make_record(kind, **fields)
        ret = _retainer
        if ret is not None and ret(rec, w):
            # retention took custody: written (or dropped) when the
            # request ends — the tail-based decision point
            return
        w.write(rec)
        sink = _sink
        if sink is not None:
            sink(rec)
    # dklint: ignore[broad-except] the never-throws emit contract: dropped event + one warning
    except Exception as e:
        _warn_once(f"event emit failed ({kind}): {e!r}")


def reset():
    """Close the writer and forget the cached ``DK_OBS_DIR`` decision —
    tests that flip the env need a fresh resolution.  The flight-
    recorder sink detaches too (re-attached at the next resolution)."""
    global _resolved, _writer, _warned, _sink, _retainer
    with _lock:
        if _writer is not None:
            _writer.close()
        _writer = None
        _resolved = False
        _warned = False
        _sink = None
        _retainer = None
