"""Span tracing — nested named regions stamped into the event stream.

``span("ckpt.save")`` wraps a block with ``span_begin`` / ``span_end``
events (the end event carries ``duration_s``), nests — the emitted name
is the dot-joined path of every open span on this thread — and records
the duration into ``metrics.histogram("span.<path>")`` so the run report
can summarize per-phase time without re-deriving it from timestamps.

When a **device trace is active** (``utils.profiling.trace``), each span
additionally opens a ``jax.profiler.TraceAnnotation`` so the same names
show up inside the XProf/TensorBoard timeline — one annotation
vocabulary for both the host-side event log and the device trace.
``utils.profiling.trace`` flips :func:`set_device_trace`; nothing here
imports jax unless that flag is on, so spans stay usable in processes
that never touch a device (the launcher, the report CLI).

Zero-cost contract: with ``DK_OBS_DIR`` unset and no device trace, a
span is a single shared no-op context manager — no clock read, no
allocation beyond the generator frame.
"""

from __future__ import annotations

import contextlib
import threading
import time

from dist_keras_tpu.observability import events, metrics

_tls = threading.local()           # per-thread open-span name stack
_device_trace_active = False       # toggled by utils.profiling.trace


def set_device_trace(active):
    """Record whether a ``jax.profiler`` device trace is running —
    spans forward to ``TraceAnnotation`` only while it is."""
    global _device_trace_active
    _device_trace_active = bool(active)


def device_trace_active():
    return _device_trace_active


def _stack():
    st = getattr(_tls, "stack", None)
    if st is None:
        st = _tls.stack = []
    return st


@contextlib.contextmanager
def _noop():
    yield


_NOOP = _noop  # one shared factory; the generator frame is the only cost


@contextlib.contextmanager
def _span_impl(name, fields):
    st = _stack()
    st.append(str(name))
    path = ".".join(st)
    ann = None
    if _device_trace_active:
        try:
            import jax

            ann = jax.profiler.TraceAnnotation(path)
            ann.__enter__()
        # dklint: ignore[broad-except] the device trace must not break host spans
        except Exception:  # the device trace must not break host spans
            ann = None
    events.emit("span_begin", span=path, **fields)
    t0 = time.perf_counter()
    try:
        yield path
    finally:
        dt = time.perf_counter() - t0
        if ann is not None:
            try:
                ann.__exit__(None, None, None)
            # dklint: ignore[broad-except] profiler teardown is best-effort
            except Exception:  # pragma: no cover - profiler teardown
                pass
        events.emit("span_end", span=path, duration_s=dt, **fields)
        if events.enabled():
            # dklint: metrics=span.*
            metrics.histogram(f"span.{path}").observe(dt)
        st.pop()


def span(name, **fields):
    """Context manager: a named, nested, timed region.

    >>> with span("train.run"):
    ...     with span("chunk", i=0):
    ...         ...   # events: train.run, train.run.chunk
    """
    if not events.enabled() and not _device_trace_active:
        return _NOOP()
    return _span_impl(name, fields)


def current_path():
    """The dot-joined open-span path on this thread ('' at top level)."""
    return ".".join(_stack())
