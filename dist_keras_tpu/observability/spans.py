"""Distributed span tracing — causal, cross-thread, cross-process regions.

``span("ckpt.save")`` wraps a block with ``span_begin`` / ``span_end``
events (the end event carries ``duration_s``), nests — the emitted name
is the dot-joined path of every open span on this thread — and records
the duration into ``metrics.histogram("span.<path>")`` so the run report
can summarize per-phase time without re-deriving it from timestamps.

**Trace context** (round 16): every span carries identity —

- a **root** span (no open parent on its thread, no resumed context)
  mints a fresh ``trace_id`` (32 hex chars) — or joins the job-wide
  trace when ``DK_TRACE_ID`` is exported (``launch.Job`` mints one per
  job, so every host of a pod shares it);
- every span mints its own ``span_id`` (16 hex chars) and records its
  ``parent_id``, so a post-hoc reader can reconstruct the tree;
- a context can be **captured on one thread and resumed on another**
  (:func:`capture` / :func:`resume`) — the serving engine hands the
  handler thread's context across the batcher/replica handoff, and the
  async checkpoint writer resumes the training thread's context, so
  one request (or one save) is a single connected trace across threads;
- cross-process propagation rides a ``traceparent``-style header
  (:func:`traceparent` / :func:`parse_traceparent` — the W3C
  ``00-<trace>-<span>-01`` shape) on serving requests, and the
  ``DK_TRACE_ID`` env on launched pods.

Ids come from one process-wide RNG seeded by ``DK_TRACE_SEED`` when set
(deterministic replay for gates and tests) and by OS entropy otherwise.
Spans that cannot be a context manager (the batch picked my request up
on another thread *then*) are stamped retroactively with
:func:`span_at`, which emits a single ``span_end`` record carrying
explicit ``t0`` + ``duration_s``.

When a **device trace is active** (``utils.profiling.trace``), each span
additionally opens a ``jax.profiler.TraceAnnotation`` so the same names
show up inside the XProf/TensorBoard timeline — one annotation
vocabulary for both the host-side event log and the device trace.
``utils.profiling.trace`` flips :func:`set_device_trace`; nothing here
imports jax unless that flag is on, so spans stay usable in processes
that never touch a device (the launcher, the report CLI).

Zero-cost contract: with ``DK_OBS_DIR`` unset and no device trace, a
span is ONE SHARED no-op context-manager object — no clock read, no id
mint, no per-call allocation retained (the ``--obs-only`` gate checks
the disabled path allocates nothing across 10k calls).  ``capture``
returns None and ``resume(None)`` is a no-op, so instrumented seams pay
a boolean check when tracing is off.
"""

from __future__ import annotations

import contextlib
import random
import threading
import time

from dist_keras_tpu.observability import events, metrics
from dist_keras_tpu.utils import knobs

# The span vocabulary — every name a `span(...)` / `span_at(...)` call
# site may open.  Entries containing ``*`` are fnmatch patterns for
# dynamic families (the call site carries a ``# dklint: spans=<pat>``
# annotation).  Adding a span call site?  Register the name here or the
# ``span-unregistered`` lint rule (``python -m dist_keras_tpu.analysis``)
# fails the tree — the report, the Perfetto export and operator tooling
# treat this as the closed set of phase names they can attribute.
KNOWN_SPANS = (
    # trainer dispatch loop (trainers/chunking.py)
    "train.run",
    # checkpointing (checkpoint.py — also opened on the async writer
    # thread, resumed from the saving thread's context)
    "ckpt.save",
    # serving request lifecycle (serving/server.py + serving/engine.py;
    # serve.client is the CALLER-side root a traced client opens before
    # sending its traceparent header — the gate's client worker does)
    "serve.request", "serve.batch", "serve.queue_wait", "serve.exec",
    "serve.reload", "serve.client",
    # decode serving (serving/decode.py + serving/server.py): the
    # /generate handler's live span and the scheduler's retro-stamped
    # prefill window — together with serve.queue_wait they attribute
    # time-to-first-token per request
    "serve.generate", "serve.prefill",
    # router forward hop (serving/router.py — parent of the backend's
    # serve.request via the propagated traceparent header)
    "route.forward",
    # parameter-server commit apply (ps/server.py)
    "ps.commit",
    # perf phases under an open device trace (observability/perf.py)
    "perf.*",
)

_tls = threading.local()           # per-thread open-span stack + base ctx
_device_trace_active = False       # toggled by utils.profiling.trace

# id minting: one process-wide RNG; DK_TRACE_SEED makes the id sequence
# a pure function of the seed (the chaos/gate replay convention)
_rng_lock = threading.Lock()
_rng = None

# thread-stack registry for the /statusz open-span summary: ident ->
# (thread name, live stack reference).  Entries for dead threads are
# pruned on read (open_spans) under the same lock.
_reg_lock = threading.Lock()
_stacks = {}


def _get_rng():
    global _rng
    with _rng_lock:
        if _rng is None:
            seed = knobs.get("DK_TRACE_SEED")
            _rng = (random.Random(seed) if seed is not None
                    else random.Random())
        return _rng


def new_trace_id():
    """Mint a 32-hex-char trace id (128 bits)."""
    rng = _get_rng()
    with _rng_lock:
        return f"{rng.getrandbits(128):032x}"


def new_span_id():
    """Mint a 16-hex-char span id (64 bits)."""
    rng = _get_rng()
    with _rng_lock:
        return f"{rng.getrandbits(64):016x}"


class SpanContext:
    """A capturable, resumable position in a trace: ``(trace_id,
    span_id)``.  Spans opened under a resumed context parent to
    ``span_id`` and share ``trace_id`` — across threads, and (via the
    ``traceparent`` header / ``DK_TRACE_ID`` env) across processes."""

    __slots__ = ("trace_id", "span_id")

    def __init__(self, trace_id, span_id):
        self.trace_id = str(trace_id)
        self.span_id = str(span_id)

    def __eq__(self, other):
        return (isinstance(other, SpanContext)
                and self.trace_id == other.trace_id
                and self.span_id == other.span_id)

    def __hash__(self):
        return hash((self.trace_id, self.span_id))

    def __repr__(self):
        return f"SpanContext({self.trace_id!r}, {self.span_id!r})"


def set_device_trace(active):
    """Record whether a ``jax.profiler`` device trace is running —
    spans forward to ``TraceAnnotation`` only while it is."""
    global _device_trace_active
    _device_trace_active = bool(active)


def device_trace_active():
    return _device_trace_active


def _prune_stacks_locked():
    """Drop registry entries for dead threads (caller holds
    ``_reg_lock``)."""
    alive = {t.ident for t in threading.enumerate()}
    for ident in [i for i in _stacks if i not in alive]:
        del _stacks[ident]


def _stack():
    st = getattr(_tls, "stack", None)
    if st is None:
        st = _tls.stack = []
        t = threading.current_thread()
        with _reg_lock:
            # prune at REGISTRATION cadence (once per thread, not per
            # span): per-request HTTP handler threads would otherwise
            # grow the registry without bound on a server whose
            # operator never polls /statusz (the read-side prune)
            _prune_stacks_locked()
            _stacks[t.ident] = (t.name, st)
    return st


class _NoopSpan:
    """The disabled path: one shared reusable context manager — entering
    and exiting it allocates nothing and reads no clock."""

    __slots__ = ()

    def __enter__(self):
        return ""

    def __exit__(self, *exc):
        return False


_NOOP = _NoopSpan()  # the one shared instance; span() hands it out


def _root_ids():
    """(trace_id, parent_id) for a span with no open parent on this
    thread: the resumed base context wins, then the job-wide
    ``DK_TRACE_ID``, then a freshly minted trace."""
    base = getattr(_tls, "base", None)
    if base is not None:
        return base.trace_id, base.span_id
    job_trace = knobs.raw("DK_TRACE_ID")
    if job_trace:
        return job_trace, None
    return new_trace_id(), None


@contextlib.contextmanager
def _span_impl(name, fields):
    st = _stack()
    sid = new_span_id()
    if st:
        trace, parent = st[-1][2], st[-1][1]
    else:
        trace, parent = _root_ids()
    st.append((str(name), sid, trace))
    path = ".".join(e[0] for e in st)
    ann = None
    if _device_trace_active:
        try:
            import jax

            ann = jax.profiler.TraceAnnotation(path)
            ann.__enter__()
        # dklint: ignore[broad-except] the device trace must not break host spans
        except Exception:  # the device trace must not break host spans
            ann = None
    events.emit("span_begin", span=path, trace_id=trace, span_id=sid,
                parent_id=parent, tid=threading.get_ident(), **fields)
    t0 = time.perf_counter()
    try:
        yield path
    finally:
        dt = time.perf_counter() - t0
        if ann is not None:
            try:
                ann.__exit__(None, None, None)
            # dklint: ignore[broad-except] profiler teardown is best-effort
            except Exception:  # pragma: no cover - profiler teardown
                pass
        events.emit("span_end", span=path, trace_id=trace, span_id=sid,
                    parent_id=parent, tid=threading.get_ident(),
                    duration_s=dt, **fields)
        if events.enabled():
            # dklint: metrics=span.*
            metrics.histogram(f"span.{path}").observe(dt)
        st.pop()


def span(name, **fields):
    """Context manager: a named, nested, timed region with trace
    identity.

    >>> with span("train.run"):
    ...     with span("chunk", i=0):
    ...         ...   # events: train.run, train.run.chunk
    """
    if not events.enabled() and not _device_trace_active:
        return _NOOP
    return _span_impl(name, fields)


def span_at(name, ctx, t0, t1, **fields):
    """Stamp a span RETROACTIVELY: one ``span_end`` record with explicit
    ``t0`` + ``duration_s``, parented to ``ctx`` (or a fresh root when
    None).  The cross-thread stages that cannot be a live context
    manager — the queue wait a request paid before the batcher popped
    it, the inference window a replica executed for a whole batch — are
    recorded this way, one record per request.  -> the new span's
    :class:`SpanContext`, or None when the event log is off."""
    if not events.enabled():
        return None
    sid = new_span_id()
    if ctx is not None:
        trace, parent = ctx.trace_id, ctx.span_id
    else:
        trace, parent = _root_ids()
    dur = float(t1) - float(t0)
    events.emit("span_end", span=str(name), trace_id=trace, span_id=sid,
                parent_id=parent, tid=threading.get_ident(),
                t0=float(t0), duration_s=dur, **fields)
    # dklint: metrics=span.*
    metrics.histogram(f"span.{name}").observe(dur)
    return SpanContext(trace, sid)


def current():
    """The innermost open span's :class:`SpanContext` on this thread —
    or the resumed base context, or None (tracing off / no open span)."""
    st = getattr(_tls, "stack", None)
    if st:
        return SpanContext(st[-1][2], st[-1][1])
    return getattr(_tls, "base", None)


def capture():
    """Capture the current context for another thread to
    :func:`resume`.  None when there is nothing to capture (which
    :func:`resume` accepts as a no-op) — so the seam code is one
    unconditional ``capture()`` / ``resume(ctx)`` pair."""
    if not events.enabled() and not _device_trace_active:
        return None
    return current()


@contextlib.contextmanager
def resume(ctx):
    """Adopt a captured :class:`SpanContext` on THIS thread: spans
    opened inside parent to ``ctx.span_id`` and join its trace.  The
    previous base is restored on exit; ``resume(None)`` is a no-op."""
    if ctx is None:
        yield None
        return
    prev = getattr(_tls, "base", None)
    _tls.base = ctx
    try:
        yield ctx
    finally:
        _tls.base = prev


def current_path():
    """The dot-joined open-span path on this thread ('' at top level)."""
    st = getattr(_tls, "stack", None)
    return ".".join(e[0] for e in st) if st else ""


def traceparent(ctx=None):
    """The W3C-style ``00-<trace>-<span>-01`` header value for ``ctx``
    (default: the current context), or None with nothing to carry."""
    if ctx is None:
        ctx = current()
    if ctx is None:
        return None
    return f"00-{ctx.trace_id}-{ctx.span_id}-01"


def parse_traceparent(header):
    """Parse a ``traceparent`` header -> :class:`SpanContext`, or None
    for a missing/malformed value (a bad header degrades to a fresh
    root trace — never an error into the serving path)."""
    if not header:
        return None
    parts = str(header).strip().split("-")
    if len(parts) != 4:
        return None
    _, trace, parent, _ = parts
    if len(trace) != 32 or len(parent) != 16:
        return None
    try:
        int(trace, 16), int(parent, 16)
    except ValueError:
        return None
    return SpanContext(trace, parent)


def open_spans():
    """Per-thread open-span paths — the ``/statusz`` summary.  Dead
    threads' registry entries are pruned here; only threads with at
    least one open span appear."""
    out = {}
    with _reg_lock:
        _prune_stacks_locked()
        items = list(_stacks.items())
    for ident, (name, st) in items:
        if st:
            out[f"{name} ({ident})"] = ".".join(e[0] for e in st)
    return out


def _current_ids():
    """events.py context provider: the trace identity every event
    emitted under an open span is stamped with (``setdefault``, so span
    events' explicit ids win).  None when no span is open."""
    st = getattr(_tls, "stack", None)
    if st:
        return {"trace_id": st[-1][2], "span_id": st[-1][1]}
    base = getattr(_tls, "base", None)
    if base is not None:
        return {"trace_id": base.trace_id, "span_id": base.span_id}
    return None


def reset():
    """Forget the seeded RNG so ``DK_TRACE_SEED`` is re-read — tests
    that flip the env need this.  The thread-stack registry is NOT
    cleared: live threads keep their cached thread-local stack object,
    so wiping the registry would orphan them from ``open_spans`` for
    the rest of the process; dead threads are pruned on read anyway."""
    global _rng
    with _rng_lock:
        _rng = None


# every event emitted while a span is open carries the trace identity —
# the "chunk"/"coord"/"ckpt_save" breadcrumbs stitch into the same tree
# as the spans without any extra emission
def _exemplar_ids():
    """metrics.py exemplar provider: the current span's ``(trace_id,
    span_id)`` tuple, or None when no span is open.  Only consulted
    when the SLO plane (``DK_SLO``) is armed — the disarmed observe
    path never calls this."""
    ids = _current_ids()
    return (ids["trace_id"], ids["span_id"]) if ids else None


events._set_context_provider(_current_ids)
metrics._set_exemplar_provider(_exemplar_ids)
