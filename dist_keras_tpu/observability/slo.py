"""Request-level SLO engine — declarative objectives, burn-rate math.

Round 21 shipped the serving fabric with one stitched trace per
request, but nothing *consumed* the ``serve.*`` / ``route.*`` rings at
production scale: no objective said what "good" means, and no alert
translated a bad p99 sample into "you are burning error budget".  This
module closes that loop:

- **Objectives** are declarative good/total ratios over existing
  telemetry: :func:`availability` objectives read cumulative counters
  from the per-metric ``TimeSeries`` rings (``serve.completed`` vs
  ``serve.errors``, ``route.requests`` vs ``route.errors``);
  :func:`latency` objectives count requests over a threshold via
  :meth:`metrics.Histogram.track_over` (``span.serve.request``
  durations vs ``DK_SLO_LATENCY_S``).  The closed vocabulary lives in
  :data:`KNOWN_SLOS` (lint-checked against the README table, like
  events).
- **Multi-window / multi-burn-rate** evaluation, the standard SRE
  recipe: the *fast* page needs BOTH the 5 m and 1 h windows burning
  at >= 14.4x the sustainable rate (budget gone in under ~6 h); the
  *slow* page needs both 1 h and 6 h burning at >= 6x.  Requiring the
  short AND the long window makes a page mean "still happening AND
  significant"; the short window alone would page on blips, the long
  alone would page an hour after the incident ended.  Windows are
  measured in *ring time* (every entry point takes an explicit
  ``now``), so the sim's ``World`` clock drives the math
  deterministically and a wall-clock process just passes
  ``time.time()``.
- **Surfaces**: ``slo.<objective>.*`` gauges (→ ``dk_slo_*`` after
  Prometheus sanitization), the :class:`SLOBurnRate` watchdog rule
  (transition-only + hysteresis via the existing ``Watchdog``
  machinery), the ``/slz`` section of ``statusz``, and
  :func:`breaching` — the signal ``ReplicaAutoscaler`` consumes
  alongside ``QueueDepthGrowth``.

Everything here is never-throws toward the sampler thread and inert
unless ``DK_SLO`` is armed (one cached knob read).
"""

from __future__ import annotations

import bisect
import sys
import threading
import time

from dist_keras_tpu.observability import events, metrics, timeseries
# one-way dependency: watchdog never imports slo at module level (its
# default_rules() reaches back only inside the function body)
from dist_keras_tpu.observability.watchdog import Rule
from dist_keras_tpu.utils import knobs


# The objective vocabulary — every SLO name any registry may register,
# with what it means.  Adding an objective?  Register it here AND add a
# row to the README SLO table, or the ``slo-undocumented`` /
# ``slo-doc-drift`` lint rules fail the tree (the same both-ways
# contract events and metrics follow).
KNOWN_SLOS = {
    "serve_availability": ("serving requests answered without error or "
                           "rejection (good = serve.completed, bad = "
                           "serve.errors + serve.rejected)"),
    "serve_latency": ("serve.request spans completing under the "
                      "DK_SLO_LATENCY_S threshold"),
    "route_availability": ("router forwards that returned a backend "
                           "answer (bad = route.errors over "
                           "route.requests)"),
    "generate_ttft": ("decode requests whose first generated token "
                      "lands under the DK_SLO_TTFT_S threshold "
                      "(histogram = decode.ttft_s)"),
    "generate_tokens": ("decode sequences that ran to completion "
                        "(good = decode.completed, bad = "
                        "decode.errors + decode.rejected)"),
}

# (label, window seconds) — shared by burn math, gauges, and the
# report renderer.  Ring time, not wall time.
WINDOWS = (("5m", 300.0), ("1h", 3600.0), ("6h", 21600.0))
FAST_BURN = 14.4   # 5m AND 1h both over => budget gone in < ~6h
SLOW_BURN = 6.0    # 1h AND 6h both over => sustained significant burn
_PRUNE_S = 27000.0  # keep a bit more than the slowest window

_warned = set()
_warn_lock = threading.Lock()


def _warn_once(key, msg):
    with _warn_lock:
        if key in _warned:
            return
        _warned.add(key)
    print(f"[dk.slo] WARNING: {msg}", file=sys.stderr, flush=True)


class Objective:
    """One good/total objective with its own cumulative sample ring.

    ``source()`` returns the CUMULATIVE ``(good, total)`` pair at call
    time; :meth:`evaluate` appends ``(now, good, total)`` and computes
    per-window burn rates from interval deltas, so the math needs no
    per-request hook — one cheap sample per sampler tick.  A window
    the ring does not fully cover yet degrades to the covered span
    (deltas against the oldest retained point): a fresh process
    failing hard fires FAST instead of waiting an hour for data.
    """

    def __init__(self, name, target, source, description="",
                 threshold_s=None):
        if name not in KNOWN_SLOS:
            raise ValueError(
                f"unknown SLO objective {name!r} — add it to "
                f"slo.KNOWN_SLOS (and the README table) first")
        self.name = str(name)
        self.target = float(target)
        if not 0.0 < self.target < 1.0:
            raise ValueError(f"SLO target must be in (0, 1), "
                             f"got {self.target}")
        self.source = source
        self.description = str(description) or KNOWN_SLOS[name]
        self.threshold_s = (None if threshold_s is None
                            else float(threshold_s))
        self._t, self._good, self._total = [], [], []
        self._lock = threading.Lock()

    def _burn(self, window_s, now):
        """Burn rate over the trailing window: (bad fraction of the
        interval) / (allowed bad fraction).  1.0 = burning exactly the
        budget; 14.4 = the whole 30-day budget in ~2 days."""
        t = self._t
        if len(t) < 2:
            return 0.0
        # baseline = the sample at/just before the window start (the
        # standard cumulative-counter approximation); if the ring is
        # younger than the window, the oldest point (partial window)
        i = bisect.bisect_left(t, float(now) - float(window_s))
        b = max(i - 1, 0) if i else 0
        if b >= len(t) - 1:
            b = len(t) - 2
        d_total = self._total[-1] - self._total[b]
        if d_total <= 0:
            return 0.0
        d_good = self._good[-1] - self._good[b]
        bad_frac = min(1.0, max(0.0, (d_total - d_good) / d_total))
        return bad_frac / max(1e-9, 1.0 - self.target)

    def evaluate(self, now):
        """Sample the source, append to the ring, -> the result doc
        (burn per window + firing flags) for this instant."""
        now = float(now)
        good, total = self.source()
        good, total = float(good), float(total)
        with self._lock:
            # idempotent per timestamp: the sampler and a standalone
            # SLOBurnRate rule may both evaluate the same tick
            if not self._t or now > self._t[-1]:
                self._t.append(now)
                self._good.append(good)
                self._total.append(total)
                cut = now - _PRUNE_S
                k = bisect.bisect_left(self._t, cut)
                if k:
                    del self._t[:k], self._good[:k], self._total[:k]
            burn = {label: self._burn(w, now) for label, w in WINDOWS}
            covered = self._t[-1] - self._t[0] if self._t else 0.0
        fast = burn["5m"] >= FAST_BURN and burn["1h"] >= FAST_BURN
        slow = burn["1h"] >= SLOW_BURN and burn["6h"] >= SLOW_BURN
        doc = {
            "objective": self.name,
            "target": self.target,
            "good": good,
            "total": total,
            "burn": {k: round(v, 4) for k, v in burn.items()},
            "fast_firing": fast,
            "slow_firing": slow,
            "firing": fast or slow,
            "covered_s": round(covered, 3),
        }
        if self.threshold_s is not None:
            doc["threshold_s"] = self.threshold_s
        return doc

    def reset(self):
        with self._lock:
            self._t, self._good, self._total = [], [], []


def availability(name, bad, good=None, total=None, target=0.999):
    """Availability objective over cumulative COUNTER rings.

    Either ``good=(names,)`` (total = good + bad) or
    ``total=(names,)`` (good = total - bad).  Counters are read from
    the per-metric ``TimeSeries`` rings the sampler populates, so the
    objective sees exactly what the watchdog sees; a ring that does
    not exist yet reads 0 and the objective stays quiet.
    """
    if (good is None) == (total is None):
        raise ValueError("availability() needs exactly one of "
                         "good= or total=")
    bad, base = tuple(bad), tuple(good if good is not None else total)

    def _ring(metric):
        s = timeseries.get(metric)
        latest = s.latest if s is not None else None
        return float(latest[1]) if latest is not None else 0.0

    def source():
        b = sum(_ring(m) for m in bad)
        if good is not None:
            g = sum(_ring(m) for m in base)
            return g, g + b
        n = sum(_ring(m) for m in base)
        return max(0.0, n - b), n

    return Objective(name, target, source)


def latency(name, histogram="span.serve.request", threshold_s=None,
            target=0.99):
    """Latency-threshold objective over a registry histogram: good =
    observations at/under ``threshold_s`` (default
    ``DK_SLO_LATENCY_S``), counted exactly via
    :meth:`Histogram.track_over` — one float compare per observe, no
    ring scan."""
    thr = (knobs.get("DK_SLO_LATENCY_S") if threshold_s is None
           else float(threshold_s))
    # dklint: metrics=span.*
    h = metrics.histogram(histogram)
    h.track_over(thr)

    def source():
        count = float(h.totals()["count"])
        return count - float(h.over(thr)), count

    return Objective(name, target, source, threshold_s=thr)


class Registry:
    """A set of objectives evaluated together.  The module-level
    default registry feeds the gauges / watchdog / statusz surfaces;
    the sim builds private registries so scenario math never touches
    process globals."""

    def __init__(self, gauges=False):
        self._objectives = []
        self._results = []
        self._last_now = None
        self._firing = frozenset()
        self._gauges = bool(gauges)
        self._lock = threading.Lock()

    def register(self, objective):
        with self._lock:
            if any(o.name == objective.name for o in self._objectives):
                raise ValueError(
                    f"SLO objective {objective.name!r} already "
                    f"registered")
            self._objectives.append(objective)
        return objective

    def objectives(self):
        with self._lock:
            return list(self._objectives)

    def results(self):
        """Last evaluation's result docs (empty before the first)."""
        with self._lock:
            return list(self._results)

    def breaching(self):
        """Names of objectives firing as of the last evaluation — the
        autoscaler's scale-up evidence."""
        with self._lock:
            return sorted(self._firing)

    def evaluate(self, now=None):
        """Evaluate every objective at ``now`` (ring time) -> result
        docs.  Idempotent per timestamp; a broken objective degrades
        to absent-with-one-warning, never a raise into the sampler."""
        now = time.time() if now is None else float(now)
        with self._lock:
            if self._last_now is not None and now <= self._last_now:
                return list(self._results)
            objectives = list(self._objectives)
            was_firing = self._firing
        results = []
        for obj in objectives:
            try:
                results.append(obj.evaluate(now))
            # dklint: ignore[broad-except] a broken objective degrades to one warning, never a sampler raise
            except Exception as e:
                _warn_once(("objective", obj.name),
                           f"objective {obj.name!r} raised {e!r} — "
                           f"skipped")
        firing = frozenset(r["objective"] for r in results if r["firing"])
        if self._gauges:
            for r in results:
                n = r["objective"]
                # dklint: metrics=slo.*
                metrics.gauge(f"slo.{n}.burn_fast").set(r["burn"]["5m"])
                # dklint: metrics=slo.*
                metrics.gauge(f"slo.{n}.burn_slow").set(r["burn"]["1h"])
                # dklint: metrics=slo.*
                metrics.gauge(f"slo.{n}.firing").set(
                    1 if r["firing"] else 0)
        with self._lock:
            self._results = results
            self._last_now = now
            self._firing = firing
        if firing != was_firing and events.enabled():
            events.emit("slo_transition",
                        firing=sorted(firing),
                        cleared=sorted(was_firing - firing),
                        t_eval=now)
        return list(results)

    def clear(self):
        with self._lock:
            self._objectives = []
            self._results = []
            self._last_now = None
            self._firing = frozenset()


class SLOBurnRate(Rule):
    """Watchdog rule: any registered objective is burning error budget
    past the multi-window thresholds.  The alert names the WORST
    objective (and every firing one), its burn per window, and which
    page class (fast/slow) tripped; transitions and hysteresis come
    from the surrounding ``Watchdog``, like every other rule.

    Evaluates the registry itself (idempotent per timestamp), so the
    rule works under a bare ``Watchdog.check`` with no sampler.
    """

    name = "slo_burn_rate"

    def __init__(self, registry=None):
        self._registry = registry

    def evaluate(self, now):
        reg = self._registry if self._registry is not None else _default
        if not reg.objectives():
            return False, {}
        results = reg.evaluate(now)
        firing = [r for r in results if r["firing"]]
        if not firing:
            return False, {}
        worst = max(firing,
                    key=lambda r: max(r["burn"]["5m"], r["burn"]["1h"]))
        return True, {
            "objective": worst["objective"],
            "target": worst["target"],
            "burn_5m": worst["burn"]["5m"],
            "burn_1h": worst["burn"]["1h"],
            "burn_6h": worst["burn"]["6h"],
            "page": "fast" if worst["fast_firing"] else "slow",
            "objectives": sorted(r["objective"] for r in firing),
        }


_default = Registry(gauges=True)
_enabled = None


def enabled():
    """One cached ``DK_SLO`` read — the zero-cost gate every surface
    checks first."""
    global _enabled
    if _enabled is None:
        _enabled = bool(knobs.get("DK_SLO"))
    return _enabled


def register(objective):
    """Register an objective with the process-default registry."""
    return _default.register(objective)


def objectives():
    return _default.objectives()


def results():
    return _default.results()


def breaching():
    """Firing objective names from the default registry's last
    evaluation — empty when ``DK_SLO`` is off or all is well."""
    if not enabled():
        return []
    return _default.breaching()


def install_defaults():
    """Register the standard serving objectives (idempotent): serving
    availability + latency, router availability, decode TTFT +
    sequence completion.  A process that never
    records the underlying metrics keeps the objectives quiet (a
    source reading (0, 0) produces zero burn)."""
    if _default.objectives():
        return
    _default.register(availability(
        "serve_availability", good=("serve.completed",),
        bad=("serve.errors", "serve.rejected"), target=0.999))
    _default.register(latency("serve_latency", target=0.99))
    _default.register(availability(
        "route_availability", total=("route.requests",),
        bad=("route.errors",), target=0.999))
    _default.register(latency(
        "generate_ttft", histogram="decode.ttft_s",
        threshold_s=float(knobs.get("DK_SLO_TTFT_S")), target=0.99))
    _default.register(availability(
        "generate_tokens", good=("decode.completed",),
        bad=("decode.errors", "decode.rejected"), target=0.999))


def maybe_evaluate(now=None):
    """The sampler-tick hook: no-op unless ``DK_SLO`` is armed;
    otherwise install the default objectives once and evaluate.
    Never throws."""
    if not enabled():
        return
    try:
        install_defaults()
        _default.evaluate(now)
    # dklint: ignore[broad-except] SLO evaluation must never kill the sampler tick
    except Exception as e:
        _warn_once("evaluate", f"evaluation raised {e!r}")


def burn_rules():
    """The rules :func:`watchdog.default_rules` appends when ``DK_SLO``
    is armed (installing the default objectives so the rule has
    something to evaluate)."""
    if not enabled():
        return []
    try:
        install_defaults()
    # dklint: ignore[broad-except] objective install failure degrades to no SLO rule + warning
    except Exception as e:
        _warn_once("install", f"default objectives raised {e!r}")
        return []
    return [SLOBurnRate()]


def status_doc():
    """The ``/slz`` section of statusz: armed-or-not, each objective's
    last result (burn per window, firing flags)."""
    return {
        "enabled": enabled(),
        "windows": {label: w for label, w in WINDOWS},
        "fast_burn": FAST_BURN,
        "slow_burn": SLOW_BURN,
        "objectives": _default.results(),
    }


def reset():
    """Forget objectives, results, and the cached knob (tests)."""
    global _enabled
    _default.clear()
    _enabled = None
    with _warn_lock:
        _warned.clear()
