"""Perf attribution — CPU-measurable proxies for device-side perf claims.

The device bench has been unresponsive since round 5 (BENCH_r05.json:
probe timeout), which left every device-only perf claim unattributable.
This layer records what the HOST can always measure, cheaply enough to
stay on in production (<5% of train wall, gated):

- **Retraces** — every XLA executable build, counted via a
  ``jax.monitoring`` duration listener on
  ``/jax/core/compile/backend_compile_duration`` (plus ``perf.traces``
  for jaxpr traces and a ``perf.compile_s`` histogram).  Steady-state
  training and a ladder-bounded serving engine should both read ZERO
  after warm-up; a nonzero rate in the time series is the "why did this
  run get slow" answer no wall clock gives.
- **Dispatches** — compiled-program launches enqueued by the
  framework's own hot loops (:func:`count_dispatch` at the
  ``ChunkRunner`` chunk dispatch and each serving replica batch).  A
  deliberate seam count, not an XLA-internal hook: it measures the
  dispatch *granularity the framework chose*, which is exactly the knob
  chunk plans and batch ladders turn.
- **H2D / D2H bytes + walls** — :func:`h2d` at the ``ChunkFeed``
  transfer (bytes shipped + the async enqueue wall) and :func:`d2h` at
  the trainers' blocking loss retire (bytes fetched + the blocking
  wall, which on the streamed path is the documented backpressure
  barrier — the honest "host overlap wall").
- **Per-phase step-time breakdown** — :func:`phase` wraps the dispatch
  loop's host-side phases (``data`` / ``step`` / ``comm`` / ``ckpt``)
  into always-on ``perf.phase.<name>`` registry histograms.  The time
  domain rides the sampler's ``perf_sample`` events, NOT per-call span
  events: phases run at per-chunk cadence, and two JSON lines per phase
  per chunk is exactly the hot-loop emission volume the <5% overhead
  contract forbids (measured: it tripled the obs gate's emit wall).
  While a device trace is open the region still goes through
  ``spans.span`` — so XProf annotations and the histograms share one
  vocabulary when it matters, at a cadence an operator opted into.

Everything lands in the process metrics registry, so it rides the
epoch-boundary snapshots, the ``MetricsSampler`` time series, the
``perf_sample`` events, and the Prometheus exposition with no extra
plumbing.  No device profiler is ever required.
"""

from __future__ import annotations

import contextlib
import threading
import time

from dist_keras_tpu.observability import metrics, spans

# one executable build per fire — the retrace proxy
_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"
# one jaxpr trace per fire — the (noisier) Python-side tracing proxy
_TRACE_EVENT = "/jax/core/compile/jaxpr_trace_duration"

_lock = threading.Lock()
_installed = False

# comm_overlap / comm_blocked are the round-19 split of the boundary
# collective wall: AsyncMerge (parallel/collectives.py) charges the
# async enqueue to comm_overlap and the deferred block_until_ready to
# comm_blocked, so "how much of the collective hid under compute" is a
# first-class histogram instead of a guess inside "comm"
PHASES = ("data", "step", "comm", "comm_overlap", "comm_blocked", "ckpt")


def _on_duration(name, duration_secs, **kw):
    if name == _COMPILE_EVENT:
        metrics.counter("perf.retraces").inc()
        metrics.histogram("perf.compile_s").observe(duration_secs)
    elif name == _TRACE_EVENT:
        metrics.counter("perf.traces").inc()


def install():
    """Register the retrace listener (idempotent; one module flag check
    per call, so hot loops may call it freely).  -> True when the
    listener is active, False when jax/monitoring is unavailable —
    callers never gate on the result, the counters just stay zero."""
    global _installed
    if _installed:
        return True
    with _lock:
        if _installed:
            return True
        try:
            import jax.monitoring

            jax.monitoring.register_event_duration_secs_listener(
                _on_duration)
        # dklint: ignore[broad-except] jax.monitoring is optional; no listener means no retrace counts
        except Exception:
            return False
        _installed = True
    return True


def installed():
    return _installed


def count_dispatch(n=1):
    """Count ``n`` compiled-program launches enqueued by a framework
    hot loop (per chunk / per serving batch — NOT per compiled step,
    which lives inside the dispatch and cannot host a Python hook)."""
    metrics.counter("perf.dispatches").inc(n)


def h2d(nbytes, seconds):
    """Record one host->device transfer: bytes shipped + the enqueue
    wall (``device_put`` is async — the DMA itself overlaps compute by
    design, so the enqueue wall is the host-side cost that exists)."""
    metrics.counter("perf.h2d_bytes").inc(int(nbytes))
    metrics.histogram("perf.h2d_s").observe(seconds)


def d2h(nbytes, seconds):
    """Record one device->host fetch: bytes + the BLOCKING wall.  On
    the streamed training path this wall doubles as the depth-2
    backpressure barrier (see ``ChunkRunner``), so it includes the wait
    for the dispatched compute — which is precisely the "host overlap
    wall" a device-only claim needs a CPU-measurable proxy for."""
    metrics.counter("perf.d2h_bytes").inc(int(nbytes))
    metrics.histogram("perf.d2h_s").observe(seconds)


@contextlib.contextmanager
def phase(name, **fields):
    """Always-on timed phase: observes ``perf.phase.<name>`` (registry
    histogram — a clock read + deque append, no I/O, per-chunk-cadence
    safe).  Only while a device trace is open does the region also run
    through ``spans.span`` (-> ``TraceAnnotation`` + span events), so
    XProf and the histograms share a vocabulary without per-chunk JSON
    emission on production runs."""
    # dklint: spans=perf.*
    cm = (spans.span(f"perf.{name}", **fields)
          if spans.device_trace_active() else contextlib.nullcontext())
    with cm:
        t0 = time.perf_counter()
        try:
            yield
        finally:
            # dklint: metrics=perf.phase.*
            metrics.histogram(f"perf.phase.{name}").observe(
                time.perf_counter() - t0)


def snapshot(snap=None):
    """Compact JSON-ready perf-attribution snapshot — the
    ``perf_sample`` event payload and the report's per-rank row.
    Percentile-free (totals only): this runs on every sampler tick,
    which passes its already-taken registry ``snap`` in so one tick
    walks the registry once, not twice."""
    if snap is None:
        snap = metrics.snapshot(percentiles=False)
    counters, hists = snap["counters"], snap["histograms"]
    phases = {}
    for name, h in hists.items():
        if name.startswith("perf.phase."):
            phases[name[len("perf.phase."):]] = {
                "count": h["count"],
                "total_s": round(h["total"], 6),
                "mean_s": (round(h["total"] / h["count"], 6)
                           if h["count"] else None),
            }
    out = {
        "retraces": counters.get("perf.retraces", 0),
        "traces": counters.get("perf.traces", 0),
        "dispatches": counters.get("perf.dispatches", 0),
        "h2d_bytes": counters.get("perf.h2d_bytes", 0),
        "d2h_bytes": counters.get("perf.d2h_bytes", 0),
        "phases": phases,
    }
    compile_h = hists.get("perf.compile_s")
    if compile_h and compile_h["count"]:
        out["compile_s_total"] = round(compile_h["total"], 4)
    return out
