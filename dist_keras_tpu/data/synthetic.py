"""Faithfully-shaped synthetic datasets for the examples + accuracy gates.

The reference ships small sample data under ``examples/data/`` (MNIST
csv/gz, ``atlas_higgs.csv`` — SURVEY.md §2.4) and its examples double as the
integration tests.  This image has no network and no cached copies of the
real datasets, so the examples/gates here use *procedural* datasets with the
exact shapes, value ranges and difficulty character of the originals:

- ``synthetic_mnist``    — 28x28x1 grayscale digits in [0,255], labels 0-9.
  Each digit is rendered from a stroke skeleton (polylines/arcs) under a
  random affine jitter + stroke-width/intensity/pixel noise, so the class
  signal is spatial structure (what a CNN must exploit), not a lookup table.
- ``synthetic_higgs``    — 28 continuous physics-flavoured features, binary
  signal/background labels with overlapping nonlinear class structure
  (invariant-mass peak vs falling background + angular correlations),
  mixed by a fixed rotation so no single column separates the classes.
- ``synthetic_cifar10``  — 32x32x3 color images in [0,255], 10 classes of
  textured patterns (oriented gratings / checkers / radial blobs x class
  palettes) with per-sample phase/angle/brightness jitter.

All generators are deterministic in ``seed`` and return ``Dataset`` objects
with the same column layout the reference examples build from their CSVs
(``features`` flat float row + integer ``label``).  ``to_csv`` round-trips
through the native fastcsv reader so the example scripts exercise the real
ingestion path (reference examples load MNIST from CSV, examples/mnist.py).
"""

from __future__ import annotations

import numpy as np

from dist_keras_tpu.data.dataset import Dataset

__all__ = [
    "synthetic_mnist",
    "synthetic_higgs",
    "synthetic_cifar10",
    "to_csv",
]


# ---------------------------------------------------------------------------
# digit stroke skeletons, in a unit box (x right, y down)
# ---------------------------------------------------------------------------
def _arc(cx, cy, rx, ry, a0, a1, n=12):
    t = np.linspace(np.radians(a0), np.radians(a1), n)
    return np.stack([cx + rx * np.cos(t), cy + ry * np.sin(t)], axis=1)


def _digit_strokes():
    """-> list of 10 lists of polylines (each an (P,2) array)."""
    s = [None] * 10
    s[0] = [_arc(0.5, 0.5, 0.19, 0.32, 0, 360, 24)]
    s[1] = [np.array([[0.38, 0.30], [0.52, 0.16], [0.52, 0.84]]),
            np.array([[0.38, 0.84], [0.66, 0.84]])]
    s[2] = [np.concatenate([
        _arc(0.5, 0.33, 0.18, 0.17, 180, 360, 10),
        np.array([[0.66, 0.45], [0.33, 0.82]]),
        np.array([[0.33, 0.84], [0.70, 0.84]])])]
    s[3] = [np.concatenate([
        _arc(0.47, 0.31, 0.17, 0.15, 160, 400, 10),
        _arc(0.47, 0.66, 0.19, 0.18, -80, 160, 12)])]
    s[4] = [np.array([[0.62, 0.84], [0.62, 0.16], [0.30, 0.62], [0.74, 0.62]])]
    s[5] = [np.concatenate([
        np.array([[0.68, 0.17], [0.36, 0.17], [0.33, 0.47]]),
        _arc(0.49, 0.64, 0.19, 0.19, -60, 160, 12)])]
    s[6] = [np.concatenate([
        np.array([[0.62, 0.16], [0.40, 0.45]]),
        _arc(0.50, 0.64, 0.17, 0.19, -180, 180, 16)])]
    s[7] = [np.array([[0.30, 0.17], [0.70, 0.17], [0.44, 0.84]])]
    s[8] = [_arc(0.5, 0.32, 0.15, 0.15, 0, 360, 16),
            _arc(0.5, 0.66, 0.18, 0.17, 0, 360, 16)]
    s[9] = [np.concatenate([
        _arc(0.50, 0.34, 0.17, 0.18, -180, 180, 16),
        np.array([[0.67, 0.34], [0.60, 0.84]])])]
    return s


def _segments(polylines):
    """polylines -> (S, 2, 2) array of line segments."""
    segs = []
    for pl in polylines:
        segs.append(np.stack([pl[:-1], pl[1:]], axis=1))
    return np.concatenate(segs, axis=0)


_DIGIT_SEGS = None


def _digit_segments():
    global _DIGIT_SEGS
    if _DIGIT_SEGS is None:
        _DIGIT_SEGS = [_segments(p) for p in _digit_strokes()]
    return _DIGIT_SEGS


def _render_digits(labels, rng, size=28, chunk=256):
    """Rasterize stroke skeletons with per-sample affine + noise.

    -> (n, size, size) float32 in [0, 255].
    """
    n = len(labels)
    px = (np.arange(size) + 0.5) / size
    gx, gy = np.meshgrid(px, px, indexing="xy")
    grid = np.stack([gx.ravel(), gy.ravel()], axis=1)  # (G, 2), G=size²

    out = np.empty((n, size * size), dtype=np.float32)
    segs_by_digit = _digit_segments()

    for lo in range(0, n, chunk):
        hi = min(lo + chunk, n)
        m = hi - lo
        # per-sample affine: rotation, anisotropic scale, shear, translation
        th = rng.normal(0.0, np.radians(11.0), size=m)
        sx = rng.uniform(0.78, 1.15, size=m)
        sy = rng.uniform(0.78, 1.15, size=m)
        sh = rng.normal(0.0, 0.13, size=m)
        tx = rng.uniform(-0.09, 0.09, size=m)
        ty = rng.uniform(-0.09, 0.09, size=m)
        c, s_ = np.cos(th), np.sin(th)
        # A = R(th) @ [[sx, sh],[0, sy]]
        A = np.empty((m, 2, 2))
        A[:, 0, 0] = c * sx
        A[:, 0, 1] = c * sh - s_ * sy
        A[:, 1, 0] = s_ * sx
        A[:, 1, 1] = s_ * sh + c * sy
        width = rng.uniform(0.035, 0.09, size=m)
        gain = rng.uniform(0.6, 1.0, size=m)

        dmin = np.full((m, grid.shape[0]), np.inf, dtype=np.float32)
        # group samples in this chunk by digit so segments batch cleanly
        lab = np.asarray(labels[lo:hi])
        for d in range(10):
            idx = np.nonzero(lab == d)[0]
            if idx.size == 0:
                continue
            segs = segs_by_digit[d]  # (S, 2, 2)
            ctr = np.array([0.5, 0.5])
            pts = segs - ctr  # center, transform, un-center
            # (k, S, 2, 2): per-sample transformed endpoints
            tp = np.einsum("kij,spj->kspi", A[idx], pts)
            tp = tp + ctr + np.stack([tx[idx], ty[idx]], 1)[:, None, None, :]
            a, b = tp[:, :, 0], tp[:, :, 1]        # (k, S, 2)
            ab = b - a
            denom = np.maximum((ab * ab).sum(-1, keepdims=True), 1e-12)
            # t = clip(((g - a)·ab)/|ab|², 0, 1) per (k, S, G)
            pa = grid[None, None] - a[:, :, None]  # (k, S, G, 2)
            t = np.clip((pa * ab[:, :, None]).sum(-1)
                        / denom, 0.0, 1.0)
            proj = a[:, :, None] + t[..., None] * ab[:, :, None]
            dist = np.linalg.norm(grid[None, None] - proj, axis=-1)
            dmin[idx] = np.minimum(dmin[idx], dist.min(axis=1))

        aa = 0.022  # anti-alias falloff in unit coords (~0.6 px)
        ink = np.clip((width[:, None] - dmin) / aa + 1.0, 0.0, 1.0)
        img = ink * gain[:, None] * 255.0
        img += rng.normal(0.0, 16.0, size=img.shape)
        out[lo:hi] = np.clip(img, 0.0, 255.0)
    return out.reshape(n, size, size)


def synthetic_mnist(n=8192, seed=0, flat=True):
    """MNIST-faithful digits: 28x28 grayscale in [0,255], labels 0-9.

    ``flat=True`` gives a (n, 784) ``features`` column (the CSV layout the
    reference's examples/mnist.py loads); reshape with ReshapeTransformer
    for CNNs exactly as the reference does.
    """
    rng = np.random.default_rng(seed)
    labels = rng.integers(0, 10, size=n)
    imgs = _render_digits(labels, rng)
    feats = imgs.reshape(n, -1) if flat else imgs[..., None]
    return Dataset({"features": feats.astype(np.float32),
                    "label": labels.astype(np.int64)})


# ---------------------------------------------------------------------------
# ATLAS-Higgs-flavoured tabular binary classification
# ---------------------------------------------------------------------------
def synthetic_higgs(n=16384, seed=0, signal_fraction=0.5):
    """28 continuous features, binary label (1 = signal).

    Structure mirrors the character of the ATLAS Higgs challenge set the
    reference's workflow.ipynb trains on: a resonance-mass feature (peak for
    signal, falling exponential for background), transverse-momentum-like
    positive features with class-dependent scales, angular features with
    class-dependent correlation, derived nonlinear combinations, and pure
    noise columns — all mixed by a fixed rotation so no single column is
    separating on its own.
    """
    rng = np.random.default_rng(seed)
    y = (rng.random(n) < signal_fraction).astype(np.int64)
    sig = y == 1

    cols = []
    # resonance mass: signal peaks at 125, background falls exponentially
    mass = np.where(sig, rng.normal(125.0, 18.0, n),
                    35.0 + rng.exponential(70.0, n))
    cols.append(mass)
    # transverse momenta: heavier tails for signal
    for scale_s, scale_b in ((48.0, 40.0), (36.0, 31.0), (29.0, 27.0)):
        cols.append(np.where(sig, rng.gamma(2.1, scale_s, n),
                             rng.gamma(2.0, scale_b, n)))
    # missing-energy magnitude
    cols.append(np.where(sig, rng.gamma(1.9, 33.0, n),
                         rng.gamma(1.7, 30.0, n)))
    # angular features: signal has correlated Δφ structure
    phi1 = rng.uniform(-np.pi, np.pi, n)
    dphi = np.where(sig, rng.normal(np.pi, 1.2, n),
                    rng.uniform(-np.pi, np.pi, n))
    phi2 = np.mod(phi1 + dphi + np.pi, 2 * np.pi) - np.pi
    eta1 = rng.normal(0.0, 1.2, n)
    eta2 = np.where(sig, eta1 + rng.normal(0.0, 1.3, n),
                    rng.normal(0.0, 1.4, n))
    cols += [np.cos(phi1), np.sin(phi1), np.cos(phi2), np.sin(phi2),
             eta1, eta2, np.abs(eta1 - eta2)]
    # derived nonlinear combinations (the "DER_*" columns of the real set)
    pt_ratio = cols[1] / (cols[2] + 1.0)
    cols += [np.sqrt(np.abs(mass - 125.0)), pt_ratio,
             np.log1p(cols[1] + cols[2]),
             np.cos(dphi) * np.sqrt(cols[4] / 30.0)]
    base = np.stack(cols, axis=1)  # 19 informative columns
    base = (base - base.mean(0)) / (base.std(0) + 1e-8)
    noise = rng.normal(0.0, 1.0, size=(n, 28 - base.shape[1]))
    x = np.concatenate([base, noise], axis=1)
    # fixed rotation mixes informative and noise directions
    q, _ = np.linalg.qr(np.random.default_rng(1234).normal(size=(28, 28)))
    x = x @ q
    # mild label noise keeps the problem realistically unsaturable
    flip = rng.random(n) < 0.05
    y = np.where(flip, 1 - y, y)
    return Dataset({"features": x.astype(np.float32), "label": y})


# ---------------------------------------------------------------------------
# CIFAR-10-flavoured textured color images
# ---------------------------------------------------------------------------
_CIFAR_PALETTES = np.array([
    [[0.85, 0.30, 0.25], [0.15, 0.10, 0.30]],
    [[0.20, 0.65, 0.85], [0.90, 0.85, 0.30]],
    [[0.30, 0.75, 0.35], [0.55, 0.20, 0.60]],
    [[0.95, 0.60, 0.20], [0.10, 0.35, 0.55]],
    [[0.80, 0.80, 0.80], [0.20, 0.20, 0.20]],
    [[0.70, 0.25, 0.55], [0.25, 0.65, 0.60]],
    [[0.95, 0.85, 0.70], [0.35, 0.15, 0.10]],
    [[0.25, 0.30, 0.80], [0.85, 0.45, 0.40]],
    [[0.45, 0.85, 0.75], [0.60, 0.35, 0.15]],
    [[0.90, 0.40, 0.65], [0.15, 0.45, 0.25]],
])


def synthetic_cifar10(n=8192, seed=0, flat=True):
    """CIFAR-shaped 32x32x3 images in [0,255], 10 texture classes.

    Class signal = (pattern family, orientation, palette); per-sample jitter
    in phase/angle/frequency/brightness plus pixel noise keeps a convnet
    honest (it must learn oriented filters, not a mean color).
    """
    rng = np.random.default_rng(seed)
    size = 32
    labels = rng.integers(0, 10, size=n)
    yy, xx = np.meshgrid(np.linspace(0, 1, size), np.linspace(0, 1, size),
                         indexing="ij")
    imgs = np.empty((n, size, size, 3), dtype=np.float32)
    for lo in range(0, n, 512):
        hi = min(lo + 512, n)
        m = hi - lo
        lab = labels[lo:hi]
        angle = np.radians(lab * 18.0 + rng.normal(0, 6.0, m))
        freq = rng.uniform(2.5, 4.0, m) + (lab % 3)
        phase = rng.uniform(0, 2 * np.pi, m)
        u = (xx[None] * np.cos(angle)[:, None, None]
             + yy[None] * np.sin(angle)[:, None, None])
        v = (-xx[None] * np.sin(angle)[:, None, None]
             + yy[None] * np.cos(angle)[:, None, None])
        wave = 2 * np.pi * freq[:, None, None]
        fam = lab % 3
        stripes = 0.5 + 0.5 * np.sin(wave * u + phase[:, None, None])
        checker = (0.5 + 0.5 * np.sin(wave * u + phase[:, None, None])
                   * np.sin(wave * v + phase[:, None, None]))
        cx = rng.uniform(0.3, 0.7, m)[:, None, None]
        cy = rng.uniform(0.3, 0.7, m)[:, None, None]
        r = np.sqrt((xx[None] - cx) ** 2 + (yy[None] - cy) ** 2)
        radial = 0.5 + 0.5 * np.sin(wave * r * 2 + phase[:, None, None])
        pat = np.where(fam[:, None, None] == 0, stripes,
                       np.where(fam[:, None, None] == 1, checker, radial))
        pal = _CIFAR_PALETTES[lab].copy()  # (m, 2, 3)
        # blend toward a random other palette so mean color alone is weak
        alt = _CIFAR_PALETTES[rng.integers(0, 10, m)]
        mix = rng.uniform(0.0, 0.45, (m, 1, 1))
        pal = (1 - mix) * pal + mix * alt
        img = (pat[..., None] * pal[:, None, None, 0]
               + (1 - pat[..., None]) * pal[:, None, None, 1])
        img *= rng.uniform(0.6, 1.05, m)[:, None, None, None]
        img = img * 255.0 + rng.normal(0, 26.0, img.shape)
        imgs[lo:hi] = np.clip(img, 0, 255)
    feats = imgs.reshape(n, -1) if flat else imgs
    return Dataset({"features": feats.astype(np.float32),
                    "label": labels.astype(np.int64)})


# ---------------------------------------------------------------------------
# CSV round-trip (the reference's examples load their data from CSV)
# ---------------------------------------------------------------------------
def to_csv(dataset, path, features_col="features", label_col="label"):
    """Write features+label as a numeric CSV readable by Dataset.from_csv.

    Layout matches the reference's MNIST CSVs: one row per sample, feature
    columns first, label last.
    """
    x = np.asarray(dataset[features_col], dtype=np.float32).reshape(
        len(dataset), -1)
    y = np.asarray(dataset[label_col], dtype=np.float32).reshape(-1, 1)
    mat = np.concatenate([x, y], axis=1)
    header = ",".join([f"f{i}" for i in range(x.shape[1])] + [label_col])
    np.savetxt(path, mat, delimiter=",", header=header, comments="",
               fmt="%.6g")
    return path
