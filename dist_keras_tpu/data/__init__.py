from dist_keras_tpu.data.dataset import Dataset
from dist_keras_tpu.data.evaluators import (
    AccuracyEvaluator,
    AUCEvaluator,
    Evaluator,
    LossEvaluator,
)
from dist_keras_tpu.data.predictors import ModelPredictor, Predictor
from dist_keras_tpu.data.streaming import (
    KafkaSource,
    QueueSource,
    SocketSource,
    StreamingPredictor,
    StreamSource,
    pack_rows,
    pad_rows,
    send_rows,
)
from dist_keras_tpu.data.transformers import (
    DenseTransformer,
    LabelIndexTransformer,
    MinMaxTransformer,
    OneHotTransformer,
    ReshapeTransformer,
    StandardScaleTransformer,
    Transformer,
)

__all__ = [
    "Dataset",
    "Transformer", "MinMaxTransformer", "OneHotTransformer",
    "LabelIndexTransformer", "ReshapeTransformer", "DenseTransformer",
    "StandardScaleTransformer",
    "Predictor", "ModelPredictor",
    "Evaluator", "AccuracyEvaluator", "LossEvaluator", "AUCEvaluator",
    "StreamSource", "QueueSource", "SocketSource", "KafkaSource",
    "StreamingPredictor", "send_rows", "pack_rows", "pad_rows",
]
