"""Metric evaluators — parity with ``distkeras/evaluators.py``.

Same verbs: ``evaluate(dataset) -> float``.  Vectorised numpy instead of RDD
count jobs (evaluators.py:~45).
"""

from __future__ import annotations

import numpy as np


class Evaluator:
    """Base (evaluators.py:~15)."""

    def evaluate(self, dataset):
        raise NotImplementedError


class AccuracyEvaluator(Evaluator):
    """Fraction of rows where prediction_col == label_col
    (evaluators.py:~30)."""

    def __init__(self, prediction_col="prediction_index", label_col="label"):
        self.prediction_col = prediction_col
        self.label_col = label_col

    def evaluate(self, dataset):
        pred = np.asarray(dataset[self.prediction_col]).reshape(-1)
        label = np.asarray(dataset[self.label_col])
        if label.ndim > 1:  # one-hot labels: compare to argmax
            label = np.argmax(label, axis=-1)
        label = label.reshape(-1)
        return float(np.mean(pred == label))


class LossEvaluator(Evaluator):
    """Mean loss of a prediction column vs labels (new capability — the
    reference only had accuracy)."""

    def __init__(self, loss="categorical_crossentropy",
                 prediction_col="prediction", label_col="label"):
        from dist_keras_tpu.ops.losses import get_loss
        self.loss_fn = get_loss(loss)
        self.prediction_col = prediction_col
        self.label_col = label_col

    def evaluate(self, dataset):
        import jax.numpy as jnp
        p = jnp.asarray(np.asarray(dataset[self.prediction_col], np.float32))
        y = jnp.asarray(np.asarray(dataset[self.label_col], np.float32))
        return float(self.loss_fn(p, y))


class AUCEvaluator(Evaluator):
    """Binary ROC-AUC over a score column (Higgs workflow metric)."""

    def __init__(self, score_col="prediction", label_col="label",
                 positive_index=1):
        self.score_col = score_col
        self.label_col = label_col
        self.positive_index = positive_index

    def evaluate(self, dataset):
        s = np.asarray(dataset[self.score_col], dtype=np.float64)
        if s.ndim > 1:
            s = s[:, self.positive_index]
        y = np.asarray(dataset[self.label_col])
        if y.ndim > 1:
            y = np.argmax(y, axis=-1)
        y = (y == self.positive_index).astype(np.int64) \
            if y.max() > 1 else y.astype(np.int64)
        order = np.argsort(s, kind="mergesort")
        ranks = np.empty(len(s), dtype=np.float64)
        ranks[order] = np.arange(1, len(s) + 1)
        # tied scores get their mean rank (Mann-Whitney convention);
        # arbitrary distinct ranks would bias AUC on quantized/saturated
        # scores
        _, inv = np.unique(s, return_inverse=True)
        sums = np.bincount(inv, weights=ranks)
        counts = np.bincount(inv)
        ranks = (sums / counts)[inv]
        n_pos = int(y.sum())
        n_neg = len(y) - n_pos
        if n_pos == 0 or n_neg == 0:
            return float("nan")
        return float((ranks[y == 1].sum() - n_pos * (n_pos + 1) / 2)
                     / (n_pos * n_neg))
