"""Feature transformers — parity with ``distkeras/transformers.py``.

The reference implements these as Spark UDF transformers; here each is a
vectorised numpy column transform over our ``Dataset``.  Same class names,
same constructor arguments, same ``transform(dataset) -> dataset`` verb:

- ``MinMaxTransformer``       (transformers.py:~50)
- ``OneHotTransformer``       (transformers.py:~120)
- ``LabelIndexTransformer``   (transformers.py:~180)
- ``ReshapeTransformer``      (transformers.py:~250)
- ``DenseTransformer``        (transformers.py:~310)

Being plain-numpy vectorised (not row-at-a-time UDFs) they run at memory
bandwidth on the host and never touch the device.
"""

from __future__ import annotations

import numpy as np

from dist_keras_tpu.utils.misc import one_hot


class Transformer:
    """Base verb: transform(dataset) -> dataset (transformers.py:~25)."""

    def transform(self, dataset):
        raise NotImplementedError


class MinMaxTransformer(Transformer):
    """Linear rescale from observed range [o_min,o_max] to [n_min,n_max]."""

    def __init__(self, n_min=0.0, n_max=1.0, o_min=0.0, o_max=255.0,
                 input_col="features", output_col="features_normalized"):
        self.n_min, self.n_max = float(n_min), float(n_max)
        self.o_min, self.o_max = float(o_min), float(o_max)
        self.input_col, self.output_col = input_col, output_col

    def transform(self, dataset):
        x = np.asarray(dataset[self.input_col], dtype=np.float32)
        scale = (self.n_max - self.n_min) / (self.o_max - self.o_min)
        y = (x - self.o_min) * scale + self.n_min
        return dataset.with_column(self.output_col, y)


class OneHotTransformer(Transformer):
    """Integer label column -> one-hot float vector column."""

    def __init__(self, output_dim, input_col="label",
                 output_col="label_encoded"):
        self.output_dim = int(output_dim)
        self.input_col, self.output_col = input_col, output_col

    def transform(self, dataset):
        y = one_hot(dataset[self.input_col], self.output_dim)
        return dataset.with_column(self.output_col, y)


class LabelIndexTransformer(Transformer):
    """Prediction vector column -> argmax index column."""

    def __init__(self, output_dim=None, input_col="prediction",
                 output_col="prediction_index"):
        self.output_dim = output_dim  # kept for signature parity; unused
        self.input_col, self.output_col = input_col, output_col

    def transform(self, dataset):
        p = np.asarray(dataset[self.input_col])
        idx = np.argmax(p, axis=-1).astype(np.int64)
        return dataset.with_column(self.output_col, idx)


class ReshapeTransformer(Transformer):
    """Flat feature vectors -> tensors (e.g. 784 -> (28,28,1) for CNNs)."""

    def __init__(self, input_col="features", output_col="features_reshaped",
                 shape=None):
        if shape is None:
            raise ValueError("ReshapeTransformer needs a target shape")
        self.shape = tuple(shape)
        self.input_col, self.output_col = input_col, output_col

    def transform(self, dataset):
        x = np.asarray(dataset[self.input_col])
        y = x.reshape(len(x), *self.shape)
        return dataset.with_column(self.output_col, y)


class DenseTransformer(Transformer):
    """Sparse (indices, values, size) rows -> dense vectors.

    The reference converts Spark SparseVector columns to DenseVector
    (transformers.py:~310).  We accept either scipy.sparse matrices or an
    object column of (indices, values) pairs with ``size``.
    """

    def __init__(self, input_col="features_sparse", output_col="features",
                 size=None):
        self.input_col, self.output_col = input_col, output_col
        self.size = size

    def transform(self, dataset):
        col = dataset[self.input_col]
        # audit fix (round 12): the old blanket try swallowed REAL
        # densify errors (a MemoryError from todense) and fell through
        # to the pair-row path's misleading "needs size=" ValueError —
        # only the optional-dependency probe may be forgiven
        try:  # scipy sparse matrix stored whole
            import scipy.sparse as sp
            is_sparse = sp.issparse(col)
        except ImportError:
            is_sparse = False
        if is_sparse:
            return dataset.with_column(
                self.output_col, np.asarray(col.todense(), np.float32))
        if self.size is None:
            raise ValueError("DenseTransformer needs size= for pair rows")
        out = np.zeros((len(col), self.size), dtype=np.float32)
        for i, row in enumerate(col):
            idx, vals = row
            out[i, np.asarray(idx, dtype=np.int64)] = vals
        return dataset.with_column(self.output_col, out)


class StandardScaleTransformer(Transformer):
    """(x - mean) / std per feature — common prep in the Higgs workflow."""

    def __init__(self, input_col="features", output_col="features_scaled",
                 epsilon=1e-8):
        self.input_col, self.output_col = input_col, output_col
        self.epsilon = epsilon

    def transform(self, dataset):
        x = np.asarray(dataset[self.input_col], dtype=np.float32)
        mu = x.mean(axis=0, keepdims=True)
        sd = x.std(axis=0, keepdims=True)
        return dataset.with_column(
            self.output_col, (x - mu) / (sd + self.epsilon))
