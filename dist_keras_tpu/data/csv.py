"""CSV ingestion.

The reference reads CSVs through Spark (``examples/mnist.py`` loads MNIST
CSVs into a DataFrame).  Here ingestion happens on the TPU host: a native C++
parser (``data/native/fastcsv.cpp``, loaded via ctypes) scans the file once
into an opaque handle, then parses rows multi-threaded straight into a
numpy-preallocated float32 matrix (no extra copy); pandas is the fallback
when the extension isn't built or the file isn't purely numeric.
"""

from __future__ import annotations

import os

import numpy as np

from dist_keras_tpu.data.dataset import Dataset


def _native_lib():
    from dist_keras_tpu.data.native import load_fastcsv
    return load_fastcsv()


_native_warned = False


def _warn_native_once(e):
    """One stderr warning per process when the native reader fails and
    the pandas fallback takes over — same convention as the event log's
    dropped-write warning."""
    global _native_warned
    if _native_warned:
        return
    _native_warned = True
    import sys

    print(f"[dk.data] WARNING: native CSV reader failed ({e!r}) - "
          "falling back to pandas", file=sys.stderr, flush=True)


def read_numeric_csv(path, has_header=True, dtype=np.float32):
    """Parse an all-numeric CSV into (matrix, column_names)."""
    lib = _native_lib()
    if lib is not None:
        try:
            return _read_native(lib, path, has_header, dtype)
        # audit fix: a native-reader bug used to be invisible here
        # dklint: ignore[broad-except] audible full-fidelity pandas fallback
        except Exception as e:
            _warn_native_once(e)  # fall back to pandas below
    import pandas as pd
    df = pd.read_csv(path, header=0 if has_header else None)
    names = [str(c) for c in df.columns]
    return df.to_numpy(dtype=dtype), names


def _read_native(lib, path, has_header, dtype):
    import ctypes

    with open(path, "rb") as f:
        header = f.readline() if has_header else b""
    names = ([c.strip() for c in header.decode().strip().split(",")]
             if has_header else None)

    rows = ctypes.c_longlong()
    cols = ctypes.c_longlong()
    handle = lib.fastcsv_scan(path.encode(), int(has_header),
                              ctypes.byref(rows), ctypes.byref(cols))
    if not handle:
        raise IOError(f"fastcsv_scan failed on {path}")
    out = np.empty((rows.value, cols.value), dtype=np.float32)
    if rows.value == 0 or cols.value == 0:
        lib.fastcsv_release(handle)
    else:
        # extract frees the handle (success or failure)
        rc = lib.fastcsv_extract(
            handle, out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            rows.value, cols.value)
        if rc != 0:
            raise IOError(f"fastcsv_extract failed rc={rc} on {path}")
    if names is None:
        names = [f"c{i}" for i in range(cols.value)]
    return out.astype(dtype, copy=False), names


def read_csv(path, features=None, label=None, features_col="features",
             label_col="label", has_header=True):
    """CSV -> Dataset.

    ``features``: list of column names (default: all but ``label``).
    ``label``: label column name (default: last column).
    """
    mat, names = read_numeric_csv(path, has_header=has_header)
    if label is None:
        label = names[-1]
    if features is None:
        features = [n for n in names if n != label]
    fidx = [names.index(c) for c in features]
    lidx = names.index(label)
    return Dataset({
        features_col: mat[:, fidx],
        label_col: mat[:, lidx],
    })
