"""Distributed inference — parity with ``distkeras/predictors.py``.

The reference's ``ModelPredictor.predict(df)`` maps a deserialized model over
DataFrame partitions row by row (predictors.py:~35-60).  TPU-native: one
``jax.jit`` forward over fixed-size batches, optionally sharded over all
devices along the batch axis, so the MXU sees large batched matmuls instead
of row-at-a-time predicts.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from dist_keras_tpu.utils.serialization import deserialize_model, serialize_model


class Predictor:
    """Base (predictors.py:~20): holds the serialized model."""

    def __init__(self, keras_model):
        self.serialized = serialize_model(keras_model)

    def predict(self, dataset):
        raise NotImplementedError


class ModelPredictor(Predictor):
    """predict(dataset) appends an output column of model outputs.

    Args mirror predictors.py:~35: features_col / output_col. ``batch_size``
    controls the device batch; rows are padded to a full final batch and the
    pad is stripped after, so shapes stay static under jit.
    """

    def __init__(self, keras_model, features_col="features",
                 output_col="prediction", batch_size=1024, sharded=True):
        super().__init__(keras_model)
        self.features_col = features_col
        self.output_col = output_col
        self.batch_size = int(batch_size)
        self.sharded = sharded

    def predict(self, dataset):
        model = deserialize_model(self.serialized)
        params = model.params
        apply_fn = model.apply

        x = np.asarray(dataset[self.features_col], dtype=np.float32)
        n = len(x)
        bs = min(self.batch_size, max(1, n))

        devices = jax.devices()
        shard = len(devices) if (self.sharded and len(devices) > 1) else 1
        bs = max(shard, (bs // shard) * shard)

        pad = (-n) % bs
        if pad:
            x = np.concatenate([x, np.repeat(x[-1:], pad, axis=0)])

        if shard > 1:
            from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
            mesh = Mesh(np.array(devices), ("batch",))
            data_sharding = NamedSharding(mesh, P("batch"))
            fn = jax.jit(
                lambda p, xb: apply_fn(p, xb),
                in_shardings=(NamedSharding(mesh, P()), data_sharding),
            )
        else:
            fn = jax.jit(lambda p, xb: apply_fn(p, xb))

        if n == 0:
            # empty dataset: run ONE zero batch through the same jitted
            # path so the output column carries the model's real output
            # shape/dtype (an empty np.concatenate would raise, and a
            # guessed shape would break downstream evaluators)
            dummy = jnp.zeros((bs,) + x.shape[1:], jnp.float32)
            out = np.asarray(fn(params, dummy))[:0]
            return dataset.with_column(self.output_col, out)
        outs = []
        for i in range(0, len(x), bs):
            outs.append(np.asarray(fn(params, jnp.asarray(x[i:i + bs]))))
        out = np.concatenate(outs, axis=0)[:n]
        return dataset.with_column(self.output_col, out)
