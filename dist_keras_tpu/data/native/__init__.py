"""Native extension loader: builds fastcsv from source with g++.

No pybind11 in the image, so the binding is a plain C ABI consumed through
ctypes (see csv.py).  Build failures degrade gracefully — callers fall back
to pandas.

The build artifact is keyed on a hash of the source (``fastcsv-<hash>.so``)
so a source fix can never be shadowed by a stale cached binary; prebuilt
binaries are never checked in (see .gitignore).
"""

from __future__ import annotations

import ctypes
import glob
import hashlib
import os
import subprocess
import threading

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "fastcsv.cpp")
_lock = threading.Lock()
_lib = None
_tried = False


def _so_path():
    with open(_SRC, "rb") as f:
        digest = hashlib.sha256(f.read()).hexdigest()[:12]
    return os.path.join(_HERE, f"fastcsv-{digest}.so")


def build_fastcsv(force=False):
    """Compile fastcsv.cpp -> fastcsv-<srchash>.so. Returns path or None."""
    so = _so_path()
    if os.path.exists(so) and not force:
        return so
    # Drop stale builds of other source versions (incl. any legacy
    # unversioned fastcsv.so).
    for old in glob.glob(os.path.join(_HERE, "fastcsv*.so")):
        if old != so:
            try:
                os.remove(old)
            except OSError:
                pass
    cmd = ["g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-pthread",
           _SRC, "-o", so]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        return so
    # dklint: ignore[broad-except] toolchain probe: no working g++ means no native lib
    except Exception:
        return None


def load_fastcsv():
    """Return the ctypes lib (building if needed) or None."""
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        # dklint: ignore[blocking-under-lock] the lock's PURPOSE is to
        # serialize the one-time g++ build: a concurrent caller must
        # park behind the compile rather than race a second one; the
        # subprocess itself is bounded (timeout=120)
        so = build_fastcsv()
        if so is None:
            return None
        try:
            lib = ctypes.CDLL(so)
            lib.fastcsv_scan.restype = ctypes.c_void_p
            lib.fastcsv_scan.argtypes = [
                ctypes.c_char_p, ctypes.c_int,
                ctypes.POINTER(ctypes.c_longlong),
                ctypes.POINTER(ctypes.c_longlong)]
            lib.fastcsv_extract.restype = ctypes.c_int
            lib.fastcsv_extract.argtypes = [
                ctypes.c_void_p, ctypes.POINTER(ctypes.c_float),
                ctypes.c_longlong, ctypes.c_longlong]
            lib.fastcsv_release.restype = None
            lib.fastcsv_release.argtypes = [ctypes.c_void_p]
            _lib = lib
        except OSError:
            _lib = None
        return _lib
