"""Native extension loader: builds fastcsv.so on first use with g++.

No pybind11 in the image, so the binding is a plain C ABI consumed through
ctypes (see csv.py).  Build failures degrade gracefully — callers fall back
to pandas.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "fastcsv.cpp")
_SO = os.path.join(_HERE, "fastcsv.so")
_lock = threading.Lock()
_lib = None
_tried = False


def build_fastcsv(force=False):
    """Compile fastcsv.cpp -> fastcsv.so. Returns path or None."""
    if os.path.exists(_SO) and not force and \
            os.path.getmtime(_SO) >= os.path.getmtime(_SRC):
        return _SO
    cmd = ["g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-pthread",
           _SRC, "-o", _SO]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        return _SO
    except Exception:
        return None


def load_fastcsv():
    """Return the ctypes lib (building if needed) or None."""
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        so = build_fastcsv()
        if so is None:
            return None
        try:
            lib = ctypes.CDLL(so)
            lib.fastcsv_dims.restype = ctypes.c_int
            lib.fastcsv_dims.argtypes = [
                ctypes.c_char_p, ctypes.c_int,
                ctypes.POINTER(ctypes.c_longlong),
                ctypes.POINTER(ctypes.c_longlong)]
            lib.fastcsv_parse.restype = ctypes.c_int
            lib.fastcsv_parse.argtypes = [
                ctypes.c_char_p, ctypes.c_int,
                ctypes.POINTER(ctypes.c_float),
                ctypes.c_longlong, ctypes.c_longlong]
            _lib = lib
        except OSError:
            _lib = None
        return _lib
