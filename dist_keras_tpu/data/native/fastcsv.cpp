// fastcsv — native numeric-CSV parser for the TPU host data path.
//
// Role: the reference feeds training data through Spark's CSV reader into
// DataFrames; our host-side equivalent parses numeric CSVs straight into a
// caller-provided (numpy-preallocated) float32 matrix.  The file is read
// ONCE into a buffer; a single scan indexes the [begin, end) byte range of
// every non-blank data line (so dims and parse can never disagree, and a
// file growing between calls cannot overflow); value parsing is then
// row-parallel with std::thread, each row hard-bounded to its own line
// range and output slot.
//
// C ABI (ctypes) — two-call, opaque-handle, zero-copy:
//   void* fastcsv_scan(const char* path, int has_header,
//                      long long* rows, long long* cols);
//     -> reads + indexes the file; returns a handle (NULL on error) and
//        the dims the caller should allocate.
//   int fastcsv_extract(void* handle, float* out,
//                       long long rows, long long cols);
//     -> parses into the caller's rows*cols float32 buffer, bounded by
//        BOTH the handle's index and the caller's dims; frees the handle.
//        Returns 0 on success, negative error codes otherwise.
//   void fastcsv_release(void* handle);
//     -> frees a handle without extracting (error-path cleanup).
// All entry points catch C++ exceptions (bad_alloc etc.) — nothing ever
// unwinds across the ctypes boundary.

#include <algorithm>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <new>
#include <string>
#include <thread>
#include <vector>

namespace {

struct ScanHandle {
  std::string buf;                // entire file (+ sentinel newline)
  std::vector<size_t> begins;     // per non-blank data line
  std::vector<size_t> ends;
  long long cols = 0;
};

// Read the whole file into a string (with trailing sentinel newline).
static int read_file(const char* path, std::string& buf) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return -1;
  if (std::fseek(f, 0, SEEK_END) != 0) {
    std::fclose(f);
    return -1;
  }
  long size = std::ftell(f);
  if (size < 0) {
    std::fclose(f);
    return -1;
  }
  std::fseek(f, 0, SEEK_SET);
  buf.resize(static_cast<size_t>(size));
  if (size > 0 && std::fread(&buf[0], 1, static_cast<size_t>(size), f) !=
                      static_cast<size_t>(size)) {
    std::fclose(f);
    return -2;
  }
  std::fclose(f);
  if (buf.empty() || buf.back() != '\n') buf.push_back('\n');
  return 0;
}

// Skip the header line, returning the offset of the first data byte.
static size_t data_start(const std::string& buf, int has_header) {
  if (!has_header) return 0;
  size_t p = buf.find('\n');
  return p == std::string::npos ? buf.size() : p + 1;
}

// One pass over the buffer: record [begin, end) of every non-blank data
// line (blank = only \r/space/tab, matching the pandas fallback's
// skip_blank_lines) and the column count from the first data line.  This
// index is the single source of truth for both row count and parse
// targets — a two-call dims/parse API over separate reads could
// desynchronize on blank lines and on files modified between the calls.
static void scan_lines(const std::string& buf, int has_header,
                       std::vector<size_t>& begins, std::vector<size_t>& ends,
                       long long& cols) {
  cols = 0;
  size_t i = data_start(buf, has_header);
  const size_t n = buf.size();
  while (i < n) {
    size_t eol = buf.find('\n', i);
    if (eol == std::string::npos) eol = n;  // unreachable: sentinel newline
    bool blank = true;
    for (size_t j = i; j < eol; ++j) {
      if (buf[j] != '\r' && buf[j] != ' ' && buf[j] != '\t') {
        blank = false;
        break;
      }
    }
    if (!blank) {
      if (cols == 0) {
        cols = 1;
        for (size_t j = i; j < eol; ++j)
          if (buf[j] == ',') ++cols;
      }
      begins.push_back(i);
      ends.push_back(eol);
    }
    i = eol + 1;
  }
}

// Parse rows [r0, r1), reading at most `cols` values per row and writing
// rows at `out_stride` floats apart.  Every read stays inside the row's
// recorded [begin, end) line range and every write inside its cols-wide
// output slot; short/ragged lines fill 0 rather than running into a
// neighbor.
static void parse_rows(const char* base, const size_t* begins,
                       const size_t* ends, long long r0, long long r1,
                       float* out, long long cols, long long out_stride) {
  for (long long r = r0; r < r1; ++r) {
    const char* p = base + begins[r];
    const char* stop = base + ends[r];
    float* dst = out + r * out_stride;
    for (long long c = 0; c < cols; ++c) {
      while (p < stop && (*p == ' ' || *p == '\t' || *p == '\r')) ++p;
      float v = 0.0f;
      if (p < stop && *p != ',') {
        char* next = nullptr;
        float parsed = std::strtof(p, &next);
        // A numeric token never contains '\n', so next <= stop whenever
        // the token starts before stop; the guard is belt-and-braces.
        if (next && next > p && next <= stop) {
          v = parsed;
          p = next;
        }
      }
      dst[c] = v;
      while (p < stop && *p != ',') ++p;  // tolerate ragged tails
      if (p < stop) ++p;                  // consume separator
    }
  }
}

}  // namespace

extern "C" {

void* fastcsv_scan(const char* path, int has_header, long long* rows,
                   long long* cols) {
  if (!path || !rows || !cols) return nullptr;
  *rows = 0;
  *cols = 0;
  try {
    ScanHandle* h = new ScanHandle();
    if (read_file(path, h->buf) != 0) {
      delete h;
      return nullptr;
    }
    scan_lines(h->buf, has_header, h->begins, h->ends, h->cols);
    *rows = static_cast<long long>(h->begins.size());
    *cols = h->cols;
    return h;
  } catch (...) {
    return nullptr;  // bad_alloc / length_error: caller falls back to pandas
  }
}

int fastcsv_extract(void* handle, float* out, long long rows,
                    long long cols) {
  ScanHandle* h = static_cast<ScanHandle*>(handle);
  if (!h) return -3;
  if (!out || rows < 0 || cols < 0) {
    delete h;
    return -3;
  }
  try {
    // Bound by both the caller's allocation and the scan index.
    const long long nrows =
        std::min<long long>(rows, static_cast<long long>(h->begins.size()));
    const long long ncols = std::min<long long>(cols, h->cols);
    if (nrows > 0 && ncols > 0) {
      if (ncols < cols || nrows < rows)
        std::memset(out, 0, sizeof(float) * rows * cols);
      unsigned n_threads = std::thread::hardware_concurrency();
      if (n_threads == 0) n_threads = 1;
      if (static_cast<long long>(n_threads) > nrows)
        n_threads = static_cast<unsigned>(nrows);
      std::vector<std::thread> threads;
      const long long per = (nrows + n_threads - 1) / n_threads;
      for (unsigned t = 0; t < n_threads; ++t) {
        const long long r0 = static_cast<long long>(t) * per;
        const long long r1 = std::min(nrows, r0 + per);
        if (r0 >= r1) break;
        threads.emplace_back(parse_rows, h->buf.data(), h->begins.data(),
                             h->ends.data(), r0, r1, out, ncols, cols);
      }
      for (auto& th : threads) th.join();
    }
    delete h;
    return 0;
  } catch (...) {
    delete h;
    return -5;
  }
}

void fastcsv_release(void* handle) {
  delete static_cast<ScanHandle*>(handle);
}

}  // extern "C"
