// fastcsv — native numeric-CSV parser for the TPU host data path.
//
// Role: the reference feeds training data through Spark's CSV reader into
// DataFrames; our host-side equivalent parses numeric CSVs straight into a
// preallocated float32 matrix that the Dataset wraps zero-copy.  Parsing is
// chunk-parallel with std::thread (row boundaries resolved per chunk), and
// uses strtof directly on a single mmap-style buffer read.
//
// C ABI (ctypes):
//   int fastcsv_dims(const char* path, int has_header,
//                    long long* rows, long long* cols);
//   int fastcsv_parse(const char* path, int has_header,
//                     float* out, long long rows, long long cols);
// Returns 0 on success, negative error codes otherwise.

#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

namespace {

// Read the whole file into a string (with trailing sentinel newline).
static int read_file(const char* path, std::string& buf) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return -1;
  std::fseek(f, 0, SEEK_END);
  long size = std::ftell(f);
  std::fseek(f, 0, SEEK_SET);
  buf.resize(static_cast<size_t>(size));
  if (size > 0 && std::fread(&buf[0], 1, static_cast<size_t>(size), f) !=
                      static_cast<size_t>(size)) {
    std::fclose(f);
    return -2;
  }
  std::fclose(f);
  if (buf.empty() || buf.back() != '\n') buf.push_back('\n');
  return 0;
}

// Skip the header line, returning the offset of the first data byte.
static size_t data_start(const std::string& buf, int has_header) {
  if (!has_header) return 0;
  size_t p = buf.find('\n');
  return p == std::string::npos ? buf.size() : p + 1;
}

static void parse_chunk(const char* base, size_t begin, size_t end,
                        float* out, long long cols, long long row0) {
  const char* p = base + begin;
  const char* stop = base + end;
  long long row = row0;
  while (p < stop) {
    float* dst = out + row * cols;
    for (long long c = 0; c < cols; ++c) {
      char* next = nullptr;
      dst[c] = std::strtof(p, &next);
      p = (next && next != p) ? next : p + 1;
      while (p < stop && (*p == ',' || *p == ' ' || *p == '\r')) ++p;
    }
    while (p < stop && *p != '\n') ++p;  // tolerate ragged tails
    if (p < stop) ++p;                   // consume newline
    ++row;
  }
}

}  // namespace

extern "C" {

int fastcsv_dims(const char* path, int has_header, long long* rows,
                 long long* cols) {
  std::string buf;
  int rc = read_file(path, buf);
  if (rc != 0) return rc;
  size_t start = data_start(buf, has_header);
  long long nrows = 0, ncols = 0;
  // Column count from the first data line.
  size_t eol = buf.find('\n', start);
  if (eol == std::string::npos) {
    *rows = 0;
    *cols = 0;
    return 0;
  }
  ncols = 1;
  for (size_t i = start; i < eol; ++i)
    if (buf[i] == ',') ++ncols;
  for (size_t i = start; i < buf.size(); ++i) {
    if (buf[i] == '\n') {
      // Count only non-empty lines.
      if (i > start && buf[i - 1] != '\n') ++nrows;
      else if (i == start) { /* empty first line */ }
    }
  }
  *rows = nrows;
  *cols = ncols;
  return 0;
}

int fastcsv_parse(const char* path, int has_header, float* out,
                  long long rows, long long cols) {
  std::string buf;
  int rc = read_file(path, buf);
  if (rc != 0) return rc;
  size_t start = data_start(buf, has_header);
  if (rows == 0) return 0;

  unsigned n_threads = std::thread::hardware_concurrency();
  if (n_threads == 0) n_threads = 1;
  if (static_cast<long long>(n_threads) > rows)
    n_threads = static_cast<unsigned>(rows);

  // Split [start, size) into n_threads chunks on row boundaries, tracking
  // the starting row index of each chunk so outputs land in place.
  std::vector<size_t> chunk_begin;
  std::vector<long long> chunk_row;
  size_t size = buf.size();
  chunk_begin.push_back(start);
  chunk_row.push_back(0);
  if (n_threads > 1) {
    size_t approx = (size - start) / n_threads;
    long long row_cursor = 0;
    size_t pos = start;
    for (unsigned t = 1; t < n_threads; ++t) {
      size_t target = start + approx * t;
      if (target <= pos) continue;
      // Count rows from pos to the newline at/after target.
      while (pos < size && pos < target) {
        if (buf[pos] == '\n') ++row_cursor;
        ++pos;
      }
      while (pos < size && buf[pos - 1] != '\n') {
        if (buf[pos] == '\n') ++row_cursor;
        ++pos;
      }
      if (pos >= size) break;
      chunk_begin.push_back(pos);
      chunk_row.push_back(row_cursor);
    }
  }
  chunk_begin.push_back(size);

  std::vector<std::thread> threads;
  for (size_t t = 0; t + 1 < chunk_begin.size(); ++t) {
    threads.emplace_back(parse_chunk, buf.data(), chunk_begin[t],
                         chunk_begin[t + 1], out, cols, chunk_row[t]);
  }
  for (auto& th : threads) th.join();
  return 0;
}

}  // extern "C"
