"""Double-buffered host->device chunk feed — breaks the HBM residency cap.

The reference streams training data partition-by-partition through each
worker's iterator (workers.py:~60: ``LabeledBatchIterator`` over the Spark
partition; trainers.py:~360 repartitions the full DataFrame) — an epoch
never has to fit in any single executor's memory.  The round-1..3 trainers
instead materialized the whole run's data as ONE device-resident
``(workers, steps, batch, ...)`` tensor: fastest possible dispatch, but an
epoch larger than per-chip HBM could not run at all.

``ChunkFeed`` restores the reference's streaming property TPU-first:

- the epoch tensor stays in HOST memory (numpy views, zero-copy slices);
- the training loop dispatches per *chunk* of the scan axis, and the feed
  ``device_put``s chunk ``k+1`` while chunk ``k`` is still executing —
  ``jax.device_put`` is async, so the H2D transfer rides the DMA engines
  under the running computation instead of serializing with it;
- at most TWO chunks are device-resident at any moment (the executing one
  and the prefetched one): device memory is bounded by
  ``2 * chunk_bytes`` regardless of epoch size.

The loop contract (see ``trainers/windowed.py``)::

    feed = ChunkFeed(spans, put, xs, ys)
    for i, (span, K) in enumerate(spans):
        data = feed.get(i)        # device arrays (prefetched or put now)
        out = dispatch(carry, *data)   # async
        feed.prefetch(i + 1)      # H2D overlaps the running dispatch
        drain(out)                # chunk really finished
        feed.release(i)           # chunk i's HBM is reclaimable

Instrumentation (``peak_resident_chunks``, ``put_count``) exists so tests
can PROVE the residency bound instead of trusting it.
"""

from __future__ import annotations

import time

from dist_keras_tpu.observability import perf


class ChunkFeed:
    """Serve device-resident chunks of host arrays, one-chunk-ahead.

    Parameters
    ----------
    spans : list of (start, length)
        Slices along axis 1 of every host array (axis 0 is the worker
        axis), one per dispatch, in dispatch order.
    put : callable
        ``put(*host_views) -> tuple of device arrays`` — must be
        asynchronous (``jax.device_put`` /
        ``make_array_from_process_local_data`` both are).
    *arrays
        Host arrays of shape ``(workers, N, ...)``; each chunk is the
        zero-copy view ``a[:, start:start+length]``.
    """

    def __init__(self, spans, put, *arrays):
        self._spans = list(spans)
        self._put = put
        self._arrays = arrays
        self._bufs = {}
        self.put_count = 0
        self.peak_resident_chunks = 0

    def __len__(self):
        return len(self._spans)

    def prefetch(self, i):
        """Start the async H2D transfer of chunk ``i`` (idempotent)."""
        if i >= len(self._spans) or i in self._bufs:
            return
        start, length = self._spans[i]
        views = tuple(a[:, start:start + length] for a in self._arrays)
        # perf attribution: bytes shipped + the async ENQUEUE wall (the
        # DMA itself overlaps compute by design — that overlap is the
        # point of this feed; the blocking side lands in the retire's
        # d2h wall)
        t0 = time.perf_counter()
        self._bufs[i] = self._put(*views)
        perf.h2d(sum(v.nbytes for v in views),
                 time.perf_counter() - t0)
        self.put_count += 1
        self.peak_resident_chunks = max(self.peak_resident_chunks,
                                        len(self._bufs))

    def get(self, i):
        """Device arrays for chunk ``i`` (transfers now if not prefetched)."""
        self.prefetch(i)
        return self._bufs[i]

    def release(self, i):
        """Drop the feed's reference to chunk ``i`` — its device memory is
        reclaimed as soon as the computation that consumed it retires."""
        self._bufs.pop(i, None)

    def close(self):
        """Drop every buffer AND the host-array references.  Trainers call
        this when the run ends so a feed kept for introspection
        (``trainer._last_feed``) pins only the span/counter stats, not the
        multi-GB host epoch tensors."""
        self._bufs.clear()
        self._arrays = ()
