"""Streaming inference — the Kafka/Spark-Streaming pipeline counterpart.

The reference ships ``examples/kafka_producer.py`` plus a Spark Streaming
notebook (SURVEY.md §2.4): rows arrive on a Kafka topic, Spark micro-batches
them, and a trained Keras model appends predictions to each micro-batch.
TPU-native re-design:

- ``StreamSource`` — a pull iterator of feature rows.  Implementations:
  ``QueueSource`` (in-process; the test/local stand-in for a topic),
  ``SocketSource`` (length-prefixed JSON rows over TCP — the reference's
  own wire-layer flavour, stdlib-only), and ``KafkaSource`` (gated import:
  the image has no kafka client; raises with instructions if absent).
- ``StreamingPredictor`` — micro-batching exactly like Spark Streaming,
  but TPU-first: rows are packed into **fixed-shape** device batches
  (padded, pad stripped after) so ONE jitted executable serves the whole
  stream — no retraces, the MXU sees the same program every tick.  A
  ``max_latency_s`` bound flushes partial batches so a trickling topic
  still gets timely predictions.

Use ``predict_stream`` as a generator of (features, predictions) ticks, or
``run(source, sink)`` to push batches at a callback.  See
``examples/streaming_inference.py`` for the producer/consumer pipeline.
"""

from __future__ import annotations

import json
import queue
import socket
import struct
import threading
import time

import numpy as np

import jax
import jax.numpy as jnp

from dist_keras_tpu.data.predictors import Predictor
from dist_keras_tpu.observability import metrics as _metrics
from dist_keras_tpu.resilience.faults import fault_point
from dist_keras_tpu.resilience.retry import RetryPolicy
from dist_keras_tpu.utils.serialization import deserialize_model

_SENTINEL = object()


def pad_rows(x, batch_size):
    """Pad a (n, ...) row block up to ``batch_size`` by replicating the
    last row — the fixed-shape device batch every online path here
    dispatches (the pad is stripped from the output after), shared by
    :class:`StreamingPredictor` and ``serving.ServingEngine``."""
    n = len(x)
    pad = batch_size - n
    if pad < 0:
        raise ValueError(f"{n} rows exceed batch_size={batch_size}")
    if pad:
        x = np.concatenate([x, np.repeat(x[-1:], pad, axis=0)])
    return x


def pack_rows(rows, batch_size):
    """Stack a list of feature rows into one fixed-shape padded batch;
    -> ``(x (batch_size, ...), n)`` with ``n`` the real row count."""
    n = len(rows)
    return pad_rows(np.stack(rows), batch_size), n


class StreamSource:
    """Pull interface: ``get(timeout) -> row | None`` (None = nothing yet),
    ``closed`` property ends the stream."""

    def get(self, timeout):
        raise NotImplementedError

    @property
    def closed(self):
        raise NotImplementedError


class QueueSource(StreamSource):
    """In-process source backed by ``queue.Queue`` — the local stand-in
    for a Kafka topic (the reference's kafka_producer pushes rows the same
    way).  Producers call ``put(row)`` / ``close()``."""

    def __init__(self, maxsize=0):
        self._q = queue.Queue(maxsize=maxsize)
        self._closed = False

    def put(self, row):
        if self._closed:
            raise ValueError("source is closed")
        self._q.put(np.asarray(row, dtype=np.float32))

    def close(self):
        if self._closed:  # idempotent: a second sentinel would make
            return        # `closed` (qsize <= 1) unreachable forever
        self._closed = True
        self._q.put(_SENTINEL)

    def get(self, timeout):
        try:
            item = self._q.get(timeout=timeout)
        except queue.Empty:
            return None
        if item is _SENTINEL:
            self._q.put(_SENTINEL)  # keep draining consumers unblocked
            return None
        return item

    @property
    def closed(self):
        return self._closed and self._q.qsize() <= 1


class SocketSource(StreamSource):
    """Rows as length-prefixed JSON arrays over TCP (4-byte big-endian
    length + utf-8 JSON list — the reference's networking.py framing, with
    JSON instead of pickle for safety).

    Producers connect sequentially (one at a time, like partitioned Kafka
    consumers); a plain disconnect ends that producer and the accept loop
    waits for the next, while an explicit empty frame (length 0) is the
    END-OF-STREAM marker that closes the whole source.  The loop runs on a
    daemon thread feeding an internal queue, so ``get`` has the same
    semantics as QueueSource.
    """

    def __init__(self, host="127.0.0.1", port=0, backlog=4):
        self._inner = QueueSource()
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind((host, port))
        self._srv.listen(backlog)
        self.address = self._srv.getsockname()  # (host, bound port)
        self.error = None      # serve-thread failure, re-raised in get()
        self._shutdown = False
        self._thread = threading.Thread(target=self._serve, daemon=True)
        self._thread.start()

    def _serve(self):
        try:
            end_of_stream = False
            while not end_of_stream:
                try:
                    conn, _ = self._srv.accept()
                except OSError:
                    break  # close() shut the listener down
                with conn:
                    while True:
                        hdr = _recvall(conn, 4)
                        if hdr is None:
                            break  # producer disconnected; accept next
                        (n,) = struct.unpack(">I", hdr)
                        if n == 0:
                            end_of_stream = True
                            break
                        payload = _recvall(conn, n)
                        if payload is None:
                            break
                        self._inner.put(
                            json.loads(payload.decode("utf-8")))
        # dklint: ignore[broad-except] listener thread surfaces the error to the consumer via self.error
        except Exception as e:
            if not self._shutdown:  # surface to the consumer, never a
                self.error = e      # silent clean end-of-stream; but a
                # consumer-initiated close() racing a producer is a clean
                # shutdown, not a stream failure
        finally:
            self._inner.close()
            self._srv.close()

    def close(self):
        """Consumer-side shutdown: stop accepting, end the stream (the
        only way to terminate when a producer died before its
        end-of-stream frame)."""
        self._shutdown = True
        try:
            self._srv.close()  # unblocks accept() with OSError
        except OSError:  # pragma: no cover
            pass
        self._inner.close()

    def get(self, timeout):
        if self.error is not None:
            raise RuntimeError(
                "SocketSource producer stream failed") from self.error
        return self._inner.get(timeout)

    @property
    def closed(self):
        return self._inner.closed


def _recvall(conn, n):
    buf = b""
    while len(buf) < n:
        chunk = conn.recv(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return buf


def send_rows(address, rows, close=True):
    """Producer helper: stream rows to a ``SocketSource`` (the
    kafka_producer.py role).  ``rows``: iterable of 1-D feature arrays."""
    with socket.create_connection(address) as conn:
        for row in rows:
            payload = json.dumps(
                np.asarray(row, dtype=np.float32).tolist()).encode("utf-8")
            conn.sendall(struct.pack(">I", len(payload)) + payload)
        if close:
            conn.sendall(struct.pack(">I", 0))


class KafkaSource(StreamSource):
    """Kafka topic source (gated: the TPU image bakes no kafka client)."""

    def __init__(self, topic, value_deserializer=None, **consumer_kw):
        try:
            from kafka import KafkaConsumer  # type: ignore
        except ImportError as e:  # pragma: no cover - no kafka in image
            raise ImportError(
                "KafkaSource needs the kafka-python package, which is not "
                "baked into this image; use SocketSource/QueueSource, or "
                "install kafka-python in your own environment.") from e
        de = value_deserializer or (
            lambda b: np.asarray(json.loads(b.decode("utf-8")), np.float32))
        self._consumer = KafkaConsumer(
            topic, value_deserializer=de, **consumer_kw)
        self._closed = False

    def get(self, timeout):  # pragma: no cover - no kafka in image
        recs = self._consumer.poll(timeout_ms=int(timeout * 1000),
                                   max_records=1)
        for batch in recs.values():
            for rec in batch:
                return rec.value
        return None

    @property
    def closed(self):  # pragma: no cover
        return self._closed

    def close(self):  # pragma: no cover
        self._closed = True
        self._consumer.close()


class StreamingPredictor(Predictor):
    """Micro-batching streaming inference with one fixed-shape executable.

    Mirrors the reference's Spark-Streaming pipeline role: predictions for
    rows arriving on a source, in arrival order.  ``batch_size`` rows are
    packed per device dispatch; a partial batch is flushed after
    ``max_latency_s`` (padded to the fixed shape, pad stripped from the
    output), so shape-stability — and therefore zero retraces — holds for
    the whole stream.
    """

    def __init__(self, keras_model, batch_size=256, max_latency_s=0.05,
                 poll_timeout_s=0.01, fetch_retry=None):
        super().__init__(keras_model)  # serialized-model round-trip
        self.batch_size = int(batch_size)
        self.max_latency_s = float(max_latency_s)
        self.poll_timeout_s = float(poll_timeout_s)
        # transient transport errors (a reconnecting producer surfaces as
        # OSError/ConnectionError from the socket layer) are retried; a
        # clean end-of-stream or a RuntimeError stream failure is final
        self.fetch_retry = fetch_retry or RetryPolicy(
            attempts=3, backoff=0.02, jitter=0.0, retryable=(OSError,),
            name="stream.fetch")
        # per-micro-batch accounting (not per row) riding the registry
        # snapshots; resolved ONCE — the yield loop must not pay the
        # registry lock per tick
        self._m_batches = _metrics.counter("stream.batches")
        self._m_rows = _metrics.counter("stream.rows")
        model = deserialize_model(self.serialized)
        params = model.params
        apply_fn = model.apply
        self._predict = jax.jit(lambda x: apply_fn(params, x))

    def _fetch(self, source):
        """One retried poll of the source (the ``"stream.fetch"`` fault
        point covers each attempt)."""
        def attempt():
            fault_point("stream.fetch")
            return source.get(self.poll_timeout_s)

        return self.fetch_retry.call(attempt)

    def predict_stream(self, source):
        """-> generator of (rows (n, F), predictions (n, C)) micro-batches."""
        pending = []
        deadline = None
        while True:
            row = self._fetch(source)
            now = time.monotonic()
            if row is not None:
                pending.append(np.asarray(row, dtype=np.float32))
                if deadline is None:
                    deadline = now + self.max_latency_s
            flush = (len(pending) >= self.batch_size
                     or (pending and deadline is not None
                         and now >= deadline)
                     or (pending and source.closed))
            if flush:
                n = min(len(pending), self.batch_size)
                chunk, pending = pending[:n], pending[n:]
                deadline = (time.monotonic() + self.max_latency_s
                            if pending else None)
                x, n = pack_rows(chunk, self.batch_size)
                preds = np.asarray(self._predict(jnp.asarray(x)))[:n]
                self._m_batches.inc()
                self._m_rows.inc(n)
                yield x[:n], preds
            elif not pending and source.closed:
                return

    def run(self, source, sink, max_batches=None):
        """Push mode: ``sink(rows, predictions)`` per micro-batch.
        Returns the number of rows predicted."""
        total = 0
        for i, (rows, preds) in enumerate(self.predict_stream(source)):
            sink(rows, preds)
            total += len(rows)
            if max_batches is not None and i + 1 >= max_batches:
                break
        return total
