"""Columnar dataset — the Spark-DataFrame role, TPU-host-native.

In the reference, training data is a Spark DataFrame and every component
(transformers, trainers, predictors, evaluators) speaks DataFrame:
``df.select/withColumn/repartition/rdd.mapPartitions`` (see call stacks in
SURVEY.md §3).  On a TPU host the equivalent working set is columnar numpy in
host RAM that we slice into device-ready shards; this class provides that,
with a deliberately DataFrame-flavoured API so reference users map over:

- ``select``, ``with_column``, ``count`` — DataFrame verbs.
- ``repartition(n)`` / ``coalesce(n)`` — become logical shard counts used by
  trainers (``trainers.py:~365`` repartitions to num_workers).
- ``shuffle`` — ``distkeras/utils.py:~140``.
- ``batches`` / ``device_epoch`` — the TPU-native exit: fixed-shape batched
  arrays ready for ``lax.scan``; remainders are dropped the way the
  reference's fixed mini-batching does (``workers.py:~60``).

Interop: ``from_pandas``, ``from_arrays``, ``from_csv`` (see csv.py native
loader), ``to_pandas``.
"""

from __future__ import annotations

import numpy as np


class Dataset:
    def __init__(self, columns: dict, num_partitions: int = 1):
        cols = {k: np.asarray(v) for k, v in columns.items()}
        if not cols:
            raise ValueError("Dataset needs at least one column")
        n = {len(v) for v in cols.values()}
        if len(n) != 1:
            raise ValueError(f"ragged columns: { {k: len(v) for k, v in cols.items()} }")
        self._cols = cols
        self.num_partitions = int(num_partitions)

    # ------------------------------------------------------------------
    # constructors
    # ------------------------------------------------------------------
    @staticmethod
    def from_arrays(features, labels, features_col="features",
                    label_col="label"):
        return Dataset({features_col: np.asarray(features),
                        label_col: np.asarray(labels)})

    @staticmethod
    def from_pandas(df):
        return Dataset({c: df[c].to_numpy() for c in df.columns})

    @staticmethod
    def from_spark(sdf):
        """Spark DataFrame -> Dataset, via a pandas round trip — the
        SURVEY §7 stage-6 adapter ("Spark survives only as an optional
        data loader"): a reference user's existing Spark ETL output
        drops straight into the TPU trainers.  Array-typed columns
        (e.g. the reference's assembled feature vectors,
        workflow.ipynb:~cell 12) become 2-D numpy columns, matching
        ``from_csv``'s layout.

        UNTESTED IN THIS IMAGE: no pyspark is installed here (and the
        reference mount is empty) — the shim is a thin, reviewable
        pandas bridge precisely so it carries no Spark-version-specific
        surface.  ``sdf.toPandas()`` collects to the driver, which is
        the reference's own behavior at training time
        (trainers.py:~365 collects partitions to ship to workers)."""
        # look the method up separately from calling it: an
        # AttributeError raised INSIDE a genuine toPandas() (e.g. a
        # pyspark/pandas version clash) must surface as itself, not as
        # a misleading "not a Spark DataFrame" type error
        to_pandas = getattr(sdf, "toPandas", None)
        if to_pandas is None:
            raise TypeError(
                "from_spark expects a pyspark.sql.DataFrame (an object "
                f"with .toPandas()); got {type(sdf).__name__}")
        pdf = to_pandas()
        if len(pdf) == 0:
            raise ValueError(
                "from_spark got an empty DataFrame (0 rows) — check the "
                "upstream Spark query/filters")
        cols = {}
        for c in pdf.columns:
            v = pdf[c].to_numpy()
            if v.dtype == object:  # array<float> columns come back ragged
                try:
                    v = np.stack([np.asarray(e) for e in v])
                except (ValueError, TypeError) as e:
                    raise ValueError(
                        f"from_spark: column {c!r} has rows that do not "
                        "stack into one array — variable-length arrays "
                        "or NULL entries; pad/filter them in Spark "
                        "first") from e
                if v.dtype == object:
                    # all-NULL columns stack "successfully" into an
                    # object array of Nones — catch it here, not as a
                    # cryptic device-transfer dtype error later
                    raise ValueError(
                        f"from_spark: column {c!r} stacked to a non-"
                        "numeric object array (NULL rows?); pad/filter "
                        "them in Spark first")
            cols[c] = v
        return Dataset(cols)

    @staticmethod
    def from_csv(path, **kw):
        from dist_keras_tpu.data.csv import read_csv
        return read_csv(path, **kw)

    def to_pandas(self):
        import pandas as pd
        flat = {}
        for k, v in self._cols.items():
            flat[k] = list(v) if v.ndim > 1 else v
        return pd.DataFrame(flat)

    # ------------------------------------------------------------------
    # DataFrame verbs
    # ------------------------------------------------------------------
    @property
    def columns(self):
        return list(self._cols)

    def __getitem__(self, col):
        return self._cols[col]

    def __len__(self):
        return len(next(iter(self._cols.values())))

    def count(self):
        return len(self)

    def select(self, *cols):
        return Dataset({c: self._cols[c] for c in cols}, self.num_partitions)

    def with_column(self, name, values):
        cols = dict(self._cols)
        cols[name] = np.asarray(values)
        return Dataset(cols, self.num_partitions)

    def drop(self, *cols):
        return Dataset({k: v for k, v in self._cols.items() if k not in cols},
                       self.num_partitions)

    def take(self, n):
        return Dataset({k: v[:n] for k, v in self._cols.items()},
                       self.num_partitions)

    def concat(self, other):
        return Dataset(
            {k: np.concatenate([self._cols[k], other._cols[k]])
             for k in self._cols},
            self.num_partitions)

    def repartition(self, n):
        """Logical shard count (trainers map shards onto mesh workers)."""
        return Dataset(self._cols, num_partitions=int(n))

    coalesce = repartition

    def shuffle(self, seed=None):
        rng = np.random.default_rng(seed)
        perm = rng.permutation(len(self))
        return Dataset({k: v[perm] for k, v in self._cols.items()},
                       self.num_partitions)

    def split(self, fraction, seed=None):
        """(train, test) row split — the reference examples' randomSplit."""
        n = len(self)
        k = int(n * fraction)
        if seed is not None:
            ds = self.shuffle(seed)
        else:
            ds = self
        left = Dataset({c: v[:k] for c, v in ds._cols.items()},
                       self.num_partitions)
        right = Dataset({c: v[k:] for c, v in ds._cols.items()},
                        self.num_partitions)
        return left, right

    # ------------------------------------------------------------------
    # TPU exits: fixed-shape batch tensors
    # ------------------------------------------------------------------
    def batches(self, batch_size, features_col="features", label_col="label",
                drop_remainder=True, dtype=np.float32):
        """-> (num_batches, batch, ...) feature and label arrays.

        Fixed shapes so one jit covers every batch; the remainder is dropped
        exactly like the reference's fixed mini-batch assembly
        (workers.py:~60).  ``dtype=None`` keeps the columns' own dtypes —
        the host->device transfer then ships e.g. uint8 image bytes at 1/4
        the float32 volume and the train step casts on-device (the
        reference feeds uint8 MNIST through the same cast-late pattern).
        """
        x = np.asarray(self._cols[features_col],
                       dtype=dtype or self._cols[features_col].dtype)
        y = np.asarray(self._cols[label_col],
                       dtype=dtype or self._cols[label_col].dtype)
        n = (len(x) // batch_size) * batch_size
        if n == 0:
            raise ValueError(
                f"dataset of {len(x)} rows has no full batch of {batch_size}")
        x, y = x[:n], y[:n]
        xb = x.reshape(n // batch_size, batch_size, *x.shape[1:])
        yb = y.reshape(n // batch_size, batch_size, *y.shape[1:])
        return xb, yb

    def worker_shards(self, num_workers, batch_size, features_col="features",
                      label_col="label", worker_range=None,
                      dtype=np.float32):
        """-> (num_workers, steps, batch, ...) arrays for shard_map training.

        Rows are dealt to workers contiguously (the reference's repartition
        deals Spark partitions to executors, trainers.py:~365).  Every worker
        gets the same step count (lockstep SPMD needs rectangular data);
        trailing rows beyond ``num_workers * steps * batch_size`` are
        truncated, exactly like the reference's fixed mini-batch assembly
        drops partial batches (workers.py:~60).

        ``worker_range=(lo, hi)`` materializes ONLY workers [lo, hi) —
        the multi-host path: every host computes the identical global
        geometry from the full length, then slices its own workers' rows,
        so concatenating hosts' results equals the full deal.

        ``dtype=None`` keeps the columns' own dtypes (uint8 image bytes
        ship at 1/4 float32 H2D volume; the train step casts on-device).
        """
        x = self._cols[features_col]
        y = self._cols[label_col]
        per = len(x) // num_workers
        steps = per // batch_size
        if steps == 0:
            raise ValueError(
                f"{len(x)} rows over {num_workers} workers x batch "
                f"{batch_size}: no full step")
        lo, hi = (0, num_workers) if worker_range is None else worker_range
        rows = slice(lo * steps * batch_size, hi * steps * batch_size)
        x = np.asarray(x[rows], dtype=dtype or x.dtype)
        y = np.asarray(y[rows], dtype=dtype or y.dtype)
        xs = x.reshape(hi - lo, steps, batch_size, *x.shape[1:])
        ys = y.reshape(hi - lo, steps, batch_size, *y.shape[1:])
        return xs, ys
