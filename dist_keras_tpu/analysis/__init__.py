"""dklint — stdlib-only AST invariant checker for this framework.

The runtime guards its invariants dynamically (chaos gate, watchdog,
typed errors); this package guards the SOURCE invariants that used to
live in comments and CHANGES.md prose: fault-point/knob/event/metric
registry consistency (``registries``), signal-handler purity and
never-throws observability entry points (``purity``), seam hygiene
— audited broad excepts, typed-error raises, jit-pure step functions,
stale-waiver detection (``hygiene`` + the ``unused-waiver`` sweep) —
and, since round 15, the concurrency invariants (``concurrency`` over
the ``threads`` registry): thread-root inventory, the
acquires-while-holding lock-order graph, the >= 2-roots shared-state
audit, bounded cross-thread waits, and no blocking calls under a
registered lock.

Run it as ``python -m dist_keras_tpu.analysis`` (see ``__main__``);
``gates.py --lint-only`` wraps it into the gate tier and
``tests/test_dklint.py`` self-checks the real tree on every CI run.
Programmatic entry: :func:`run_analysis` over any package root —
fixture trees lint exactly like the real one because registries are
extracted from the AST, never imported.
"""

from dist_keras_tpu.analysis.core import (
    RULES,
    Finding,
    apply_baseline,
    load_baseline,
    run_analysis,
    rules_table,
    write_baseline,
)

__all__ = ["RULES", "Finding", "run_analysis", "rules_table",
           "load_baseline", "write_baseline", "apply_baseline"]
