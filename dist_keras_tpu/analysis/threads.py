"""dklint thread-root registry — which functions execute off-main.

The concurrency pass (``analysis/concurrency.py``) needs ground truth
for *where threads start*: every ``threading.Thread(target=...)`` /
``threading.Timer`` / ``signal.signal`` registration site in the tree
must resolve to a named root here (``thread-root-unknown`` otherwise;
a dead row is ``thread-root-unused``).  Like the fault/event/metric
registries, this is extracted from the AST — never imported — so
fixture trees lint exactly like the real package.

Value forms:

- ``"rel:Qualname"`` — the target function of a plain registration
  site (``rel`` is the path inside the package root).  The shared-state
  audit seeds reachability here: everything statically reachable from
  this function runs on that thread.
- ``"~rel:Qualname"`` / ``"~rel:Class.*"`` — a framework-dispatched
  root with no visible registration site (``ThreadingHTTPServer``
  spawns one handler thread per request; the registration lives inside
  the stdlib).  Validated to exist; seeds reachability; the
  registration site that *starts* the framework loop carries a
  ``# dklint: thread-root=<name>`` annotation instead.
- ``"external"`` — a foreign/restored handler the tree re-registers
  (``preemption.restore`` re-installs whatever handler was there
  before): nothing to seed, the annotated site is the whole story.

Signal handlers run ON the main thread (re-entrantly — the round-12
``signal-unsafe`` pass owns their purity story) but are inventoried
here too: the registry is the one place that answers "what executes
outside straight-line main-thread code".
"""

# name -> location (see module docstring for the value forms)
KNOWN_THREAD_ROOTS = {
    # async checkpoint pipeline (round 14)
    "ckpt.async_writer": "checkpoint.py:Checkpointer._writer_loop",
    # remote checkpoint tier (round 18)
    "ckpt.uploader": "resilience/store.py:CheckpointUploader._loop",
    "ckpt.store_http": "resilience/store.py:ObjectStoreServer"
                       ".serve_forever",
    "ckpt.store_http_handler": "~resilience/store.py:_StoreHandler.*",
    # streaming data plane
    "stream.socket_server": "data/streaming.py:SocketSource._serve",
    # serving tier
    "serve.batcher": "serving/engine.py:ServingEngine._batcher_loop",
    "serve.replica": "serving/engine.py:ServingEngine._replica_loop",
    "serve.reload_watcher": "serving/reload.py:CheckpointWatcher._loop",
    "serve.http": "serving/server.py:ServingServer.serve_forever",
    "serve.http_handler": "~serving/server.py:_Handler.*",
    "decode.worker": "serving/decode.py:DecodeEngine._worker_main",
    # survivability bench chaos timer (the Timer target is a lambda, so
    # the registration site carries the annotation and this row seeds
    # reachability at the function the lambda actually calls)
    "bench.kill_timer": "~serving/decode.py:DecodeEngine.kill_replica",
    # serving router tier + autoscaler
    "route.http": "serving/router.py:RouterServer.serve_forever",
    "route.http_handler": "~serving/router.py:_Handler.*",
    "route.health": "serving/router.py:RouterServer._health_loop",
    "route.hedge": "serving/router.py:RouterServer"
                   "._hedged_generate.run",
    "serve.autoscaler": "serving/autoscale.py:ReplicaAutoscaler._loop",
    # coordination plane
    "coord.deadline": "resilience/coordination.py:with_deadline.run",
    "coord.heartbeat": "resilience/coordination.py:Heartbeat._loop",
    # preemption
    "preempt.signal_handler": "resilience/preemption.py:_handler",
    "preempt.watcher": "resilience/preemption.py:on_request._watch",
    "preempt.restore": "external",
    # telemetry plane (round 11)
    "obs.sampler": "observability/timeseries.py:MetricsSampler._loop",
    "obs.exporter": "~observability/prometheus.py:_Handler.*",
    # parameter-server training mode (round 17)
    "ps.http": "ps/server.py:PSServer.serve_forever",
    "ps.http_handler": "~ps/server.py:_Handler.*",
    "ps.lease_reaper": "ps/server.py:PSServer._reaper_loop",
}

# Declared-safe lock orderings: (outer, inner) pairs asserted ONCE, so
# the lock-order pass can convict a future acquisition that inverts
# them (the inverted edge closes a cycle through the declaration) even
# before both directions are observable statically.  Lock names are
# ``rel:Class.attr`` / ``rel:attr`` of the constructor-assignment the
# pass registers.
LOCK_ORDER = (
    # the serving engine updates registry instruments (gauge/counter
    # leaf locks) while holding its admission condition
    ("serving/engine.py:ServingEngine._cond",
     "observability/metrics.py:Gauge._lock"),
    ("serving/engine.py:ServingEngine._cond",
     "observability/metrics.py:Counter._lock"),
    # the decode engine does the same under its scheduler condition,
    # and additionally reads/updates the per-replica KV allocator
    # (strictly inner, never takes the engine lock back)
    ("serving/decode.py:DecodeEngine._cond",
     "observability/metrics.py:Gauge._lock"),
    ("serving/decode.py:DecodeEngine._cond",
     "observability/metrics.py:Counter._lock"),
    ("serving/decode.py:DecodeEngine._cond",
     "serving/kv_cache.py:PagedKVCache._lock"),
    # the async checkpoint writer may emit events between state
    # transitions; the event writer's lock is strictly inner
    ("checkpoint.py:Checkpointer._async_cv",
     "observability/events.py:EventWriter._lock"),
)
