"""dklint pass 3 — seam hygiene.

- ``broad-except``: every ``except Exception`` / bare ``except`` in the
  tree must carry a ``# dklint: ignore[broad-except] <reason>`` waiver
  naming WHY the swallow is intentional (best-effort telemetry, typed
  fallback, optional-dependency probe, ...).  The round-12 audit waived
  each existing site with its reason in place; a new broad except
  without one is a finding.
- ``untyped-raise``: modules with a typed-error contract (coordination:
  ``PeerLost``/``BarrierTimeout``/``CoordinatorPoisoned``; checkpoint:
  ``CheckpointCorrupt``; serving: ``Overloaded``; supervisor:
  ``CrashLoop``; ps: ``StaleCommit``/``PSUnavailable``) must not grow
  new ``raise RuntimeError``/``raise
  Exception`` sites — an untyped error is exactly what the supervisor
  cannot classify.  Deliberate fatal RuntimeErrors are waived in place
  with their rationale.
- ``jit-impure``: ``time.time()``/``perf_counter`` and ``random``/
  ``np.random`` calls inside a jit-compiled function trace ONCE and
  freeze — the call silently stops doing what it looks like it does.
  Covers ``@jax.jit``/``@jit``/``@partial(jax.jit, ...)`` decorations
  and ``jax.jit(fn)``/``jax.jit(lambda ...)`` call forms whose target
  is statically resolvable.  (``jax.random`` is fine — it is
  deterministic and traceable.)
"""

from __future__ import annotations

import ast

from dist_keras_tpu.analysis.core import Finding, is_broad_handler

# files where the typed-error contract applies (basenames + subtrees)
_TYPED_ERROR_BASENAMES = {"coordination.py", "supervisor.py",
                          "preemption.py", "backend.py",
                          "checkpoint.py"}
_TYPED_ERROR_SUBTREES = ("serving/", "ps/")
_UNTYPED = {"Exception", "RuntimeError"}

_TIME_IMPURE = {"time", "time_ns", "perf_counter", "perf_counter_ns",
                "monotonic", "monotonic_ns"}


def _enclosing_functions(tree):
    """-> {node: qualname} for every function, for stable keys."""
    quals = {}

    def visit(node, prefix):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                q = f"{prefix}{child.name}"
                quals[child] = q
                visit(child, q + ".")
            else:
                visit(child, prefix)

    visit(tree, "")
    return quals


def _qual_at(quals_spans, lineno):
    best = ""
    for (start, end), q in quals_spans:
        if start <= lineno <= end:
            best = q  # innermost wins: spans are visited outer-first
    return best


def _typed_error_scope(rel):
    basename = rel.rsplit("/", 1)[-1]
    if basename in _TYPED_ERROR_BASENAMES:
        return True
    return any(sub in rel for sub in _TYPED_ERROR_SUBTREES)


# -- jit detection -----------------------------------------------------

def _is_jit_expr(node):
    """``jit`` / ``jax.jit`` as an expression."""
    if isinstance(node, ast.Name) and node.id == "jit":
        return True
    return isinstance(node, ast.Attribute) and node.attr == "jit"


def _jit_targets(sf):
    """FunctionDef/Lambda nodes that are jit-compiled in this module."""
    functions = {n.name: n for n in ast.walk(sf.tree)
                 if isinstance(n, ast.FunctionDef)}
    targets = []
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.FunctionDef):
            for dec in node.decorator_list:
                expr = dec.func if isinstance(dec, ast.Call) else dec
                if _is_jit_expr(expr):
                    targets.append(node)
                elif isinstance(dec, ast.Call) and isinstance(
                        dec.func, (ast.Name, ast.Attribute)) \
                        and (getattr(dec.func, "id", None) == "partial"
                             or getattr(dec.func, "attr", None)
                             == "partial") \
                        and dec.args and _is_jit_expr(dec.args[0]):
                    targets.append(node)
        elif isinstance(node, ast.Call) and _is_jit_expr(node.func) \
                and node.args:
            arg = node.args[0]
            if isinstance(arg, ast.Lambda):
                targets.append(arg)
            elif isinstance(arg, ast.Name) \
                    and arg.id in functions:
                targets.append(functions[arg.id])
    return targets


def _impure_calls(fn):
    """(lineno, description) for impure calls inside a jit function."""
    out = []
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        if isinstance(func, ast.Attribute):
            base = func.value
            if isinstance(base, ast.Name) and base.id == "time" \
                    and func.attr in _TIME_IMPURE:
                out.append((node.lineno, f"time.{func.attr}()"))
            elif isinstance(base, ast.Name) and base.id == "random":
                out.append((node.lineno, f"random.{func.attr}()"))
            elif isinstance(base, ast.Attribute) \
                    and base.attr == "random" \
                    and isinstance(base.value, ast.Name) \
                    and base.value.id in ("np", "numpy"):
                out.append((node.lineno,
                            f"{base.value.id}.random.{func.attr}()"))
    return out


def run(project):
    findings = []
    for sf in project.files:
        quals = _enclosing_functions(sf.tree)
        quals_spans = [((n.lineno, getattr(n, "end_lineno", n.lineno)),
                        q) for n, q in quals.items()]

        for node in ast.walk(sf.tree):
            if isinstance(node, ast.ExceptHandler) \
                    and is_broad_handler(node):
                if not sf.waived("broad-except", node.lineno):
                    qual = _qual_at(quals_spans, node.lineno) \
                        or "<module>"
                    findings.append(Finding(
                        "broad-except", sf.rel, node.lineno,
                        "broad except without a waiver naming why the "
                        "swallow is intentional "
                        "(`# dklint: ignore[broad-except] <reason>`)",
                        key=f"broad-except:{qual}:"
                            f"{sf.line_text(node.lineno)}"))
            elif isinstance(node, ast.Raise) \
                    and _typed_error_scope(sf.rel):
                exc = node.exc
                name = None
                if isinstance(exc, ast.Call) \
                        and isinstance(exc.func, ast.Name):
                    name = exc.func.id
                elif isinstance(exc, ast.Name):
                    name = exc.id
                if name in _UNTYPED \
                        and not sf.waived("untyped-raise", node.lineno):
                    qual = _qual_at(quals_spans, node.lineno) \
                        or "<module>"
                    findings.append(Finding(
                        "untyped-raise", sf.rel, node.lineno,
                        f"raise {name} in a typed-error-contract "
                        "module — use the module's typed class, or "
                        "waive with the rationale",
                        key=f"untyped-raise:{qual}:{name}"))

        for fn in _jit_targets(sf):
            for lineno, what in _impure_calls(fn):
                if not sf.waived("jit-impure", lineno):
                    findings.append(Finding(
                        "jit-impure", sf.rel, lineno,
                        f"{what} inside a jit-compiled function is "
                        "traced once and frozen into the executable",
                        key=f"jit-impure:{what}:"
                            f"{sf.line_text(lineno)}"))
    return findings
