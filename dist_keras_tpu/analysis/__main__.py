"""``python -m dist_keras_tpu.analysis`` — the dklint CLI.

Exit 0 when every finding is waived or baselined; exit 1 otherwise,
printing one ``rule path:line message`` line per fresh finding.

    python -m dist_keras_tpu.analysis                 # lint the package
    python -m dist_keras_tpu.analysis --json          # machine-readable
    python -m dist_keras_tpu.analysis --rules broad-except,knob-read
    python -m dist_keras_tpu.analysis --write-baseline  # grandfather
    python -m dist_keras_tpu.analysis --knob-table    # README knob table
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from dist_keras_tpu.analysis import core


def _default_root():
    """The installed ``dist_keras_tpu`` package directory."""
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _default_readme(root):
    """``README.md`` next to (or one level above) the analyzed root."""
    for cand in (os.path.join(root, "README.md"),
                 os.path.join(os.path.dirname(root), "README.md")):
        if os.path.exists(cand):
            return cand
    return None


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="python -m dist_keras_tpu.analysis",
        description="dklint: AST invariant checker for the "
                    "fault/knob/event/metric registries and "
                    "signal-safe seams")
    ap.add_argument("--root", default=None,
                    help="package tree to lint (default: the installed "
                         "dist_keras_tpu package)")
    ap.add_argument("--readme", default=None,
                    help="markdown file for the doc-sync rules "
                         "(default: auto-discovered next to --root)")
    ap.add_argument("--no-readme", action="store_true",
                    help="skip the doc-sync rules")
    ap.add_argument("--baseline", default=None,
                    help="baseline file of grandfathered findings "
                         "(default: <root>/analysis/baseline.json "
                         "when present)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report baselined findings as failures too")
    ap.add_argument("--write-baseline", action="store_true",
                    help="grandfather every current finding into the "
                         "baseline file and exit 0")
    ap.add_argument("--rules", default=None,
                    help="comma list restricting which rules report")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable output")
    ap.add_argument("--knob-table", action="store_true",
                    help="print the README knob table generated from "
                         "utils/knobs.py and exit")
    ap.add_argument("--rules-table", action="store_true",
                    help="print the README rules table generated from "
                         "the --list-rules vocabulary and exit")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule table and exit")
    args = ap.parse_args(argv)

    if args.knob_table:
        from dist_keras_tpu.utils import knobs

        print(knobs.doc_table())
        return 0
    if args.rules_table:
        print(core.rules_table())
        return 0
    if args.list_rules:
        for rule, doc in core.RULES.items():
            print(f"{rule}: {' '.join(doc.split())}")
        return 0

    root = os.path.abspath(args.root or _default_root())
    if args.no_readme:
        readme = None
    else:
        readme = args.readme or _default_readme(root)
    rules = ([r.strip() for r in args.rules.split(",") if r.strip()]
             if args.rules else None)

    baseline_path = args.baseline
    if baseline_path is None:
        cand = os.path.join(root, "analysis", "baseline.json")
        baseline_path = cand if os.path.exists(cand) else None

    timings = {}
    findings = core.run_analysis(root, readme=readme, rules=rules,
                                 timings=timings)

    if args.write_baseline:
        # ALWAYS grandfather from an unfiltered run: writing a baseline
        # narrowed by --rules would silently drop every other rule's
        # fingerprints and turn them into fresh failures next full run
        if rules is not None:
            findings = core.run_analysis(root, readme=readme)
        out = baseline_path or os.path.join(root, "analysis",
                                            "baseline.json")
        core.write_baseline(out, findings)
        print(f"wrote {len(findings)} fingerprint(s) to {out}")
        return 0

    grandfathered = (set() if args.no_baseline
                     else core.load_baseline(baseline_path))
    fresh = core.apply_baseline(findings, grandfathered)

    if args.as_json:
        counts = {}
        for f in fresh:
            counts[f.rule] = counts.get(f.rule, 0) + 1
        print(json.dumps({
            "root": root,
            "readme": readme,
            "baseline": baseline_path,
            "total": len(findings),
            "baselined": len(findings) - len(fresh),
            "fresh": len(fresh),
            "counts": counts,
            # per-pass wall seconds: the static_lint gate records
            # these so a slow cross-module graph walk is visible in
            # the gate JSON, and tests/test_dklint.py budgets the sum
            "pass_seconds": {k: round(v, 4)
                             for k, v in timings.items()},
            "findings": [f.to_dict() for f in fresh],
        }, indent=1))
    else:
        for f in fresh:
            print(f"{f.rule} {f.path}:{f.line} {f.message}")
        n_base = len(findings) - len(fresh)
        suffix = f" ({n_base} baselined)" if n_base else ""
        if fresh:
            print(f"dklint: {len(fresh)} finding(s){suffix}")
        else:
            print(f"dklint: clean{suffix}")
    return 1 if fresh else 0


if __name__ == "__main__":
    sys.exit(main())
