"""dklint pass 2 — signal-safety and never-raise discipline.

Two invariants that previously lived only in comments and CHANGES.md
prose:

1. **Signal handlers stay lock-free, emit-free and I/O-free.**  CPython
   dispatches handlers re-entrantly on the main thread at bytecode
   boundaries; a handler that blocks on a lock the interrupted code
   holds (the observability writer's, the metrics registry's) deadlocks
   the process — the round-8 rule ``preemption._handler`` documents.
   This pass finds every function registered via ``signal.signal(sig,
   handler)``, walks the statically-resolvable call graph reachable
   from it (same-module calls by name; cross-module calls through
   ``from pkg.mod import fn`` / ``from pkg import mod`` /
   ``import pkg.mod as m`` bindings whose target file is part of the
   analyzed tree), and flags lock
   acquisitions (``with <lock>``, ``.acquire()``), event emission
   (any ``emit`` call) and blocking I/O (``open``/``print``/
   ``os.write``/``time.sleep``/...).  ``os.kill``/``os.getpid``/
   ``signal.signal`` are allowlisted — the escalation path needs them.

2. **Never-throws observability entry points keep their broad
   handler.**  ``events.emit``, ``supervisor.alert``,
   ``MetricsSampler.tick`` and ``Watchdog.check`` promise to degrade
   rather than raise into training code; deleting their
   ``except Exception`` guard is a contract break this pass catches
   (``obs-must-not-raise``).
"""

from __future__ import annotations

import ast

from dist_keras_tpu.analysis.core import (
    Finding,
    import_bindings,
    is_broad_handler,
)

# (file basename, enclosing class or None, function name) — the
# documented never-throws entry points
NEVER_RAISE = (
    ("events.py", None, "emit"),
    ("supervisor.py", None, "alert"),
    ("timeseries.py", "MetricsSampler", "tick"),
    ("watchdog.py", "Watchdog", "check"),
)

_ALLOWED_CALLS = {("os", "kill"), ("os", "getpid"),
                  ("signal", "signal"), ("signal", "getsignal")}
_IO_CALLS = {
    ("os", "write"), ("os", "read"), ("os", "fsync"), ("os", "open"),
    ("os", "close"), ("os", "makedirs"), ("os", "replace"),
    ("os", "remove"), ("os", "rename"), ("os", "unlink"),
    ("time", "sleep"),
    ("subprocess", "run"), ("subprocess", "call"),
    ("subprocess", "Popen"), ("subprocess", "check_call"),
    ("subprocess", "check_output"),
}
_IO_NAMES = {"open", "print", "input"}


def _lockish(expr):
    """A name whose terminal component smells like a lock
    (``_lock``, ``self._lock``, ``cond``...)."""
    if isinstance(expr, ast.Attribute):
        name = expr.attr
    elif isinstance(expr, ast.Name):
        name = expr.id
    else:
        return False
    name = name.lower()
    return "lock" in name or "cond" in name


class _ModuleIndex:
    """Per-module function defs + import bindings for call resolution."""

    def __init__(self, sf):
        self.sf = sf
        self.functions = {}   # name -> FunctionDef (module-level only)
        # local name -> dotted module or (module, attr) for
        # from-imports — the shared core.import_bindings extraction
        self.imports = import_bindings(sf.tree)
        for node in sf.tree.body:
            if isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)):
                self.functions[node.name] = node


def _handler_roots(index):
    """Functions this module registers via ``signal.signal(sig, F)``."""
    roots = []
    for node in ast.walk(index.sf.tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        is_signal = (isinstance(func, ast.Attribute)
                     and func.attr == "signal"
                     and isinstance(func.value, ast.Name)
                     and func.value.id == "signal")
        if not is_signal or len(node.args) < 2:
            continue
        target = node.args[1]
        if isinstance(target, ast.Name) \
                and target.id in index.functions:
            roots.append(index.functions[target.id])
    return roots


def _check_handler_body(index, fn, findings, root_name, visited,
                        indexes_by_module):
    key = (index.sf.rel, fn.name)
    if key in visited:
        return
    visited.add(key)
    sf = index.sf

    def flag(lineno, what):
        if not sf.waived("signal-unsafe", lineno):
            findings.append(Finding(
                "signal-unsafe", sf.rel, lineno,
                f"{what} is reachable from signal handler "
                f"{root_name!r} (handlers must stay lock-free, "
                "emit-free and I/O-free)",
                key=f"signal-unsafe:{fn.name}:{sf.line_text(lineno)}"))

    for node in ast.walk(fn):
        if isinstance(node, ast.With):
            for item in node.items:
                if _lockish(item.context_expr):
                    flag(node.lineno, "a `with <lock>` acquisition")
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        if isinstance(func, ast.Name):
            name = func.id
            if name in _IO_NAMES:
                flag(node.lineno, f"blocking I/O ({name})")
            elif name == "emit":
                flag(node.lineno, "event emission (emit)")
            elif name in index.functions:
                _check_handler_body(index, index.functions[name],
                                    findings, root_name, visited,
                                    indexes_by_module)
            else:
                # `from pkg.mod import fn` then `fn()`: resolve fn in
                # mod's file when mod is part of the analyzed tree
                bound = index.imports.get(name)
                if isinstance(bound, tuple):
                    other = indexes_by_module.get(
                        bound[0].split(".")[-1] + ".py")
                    if other and bound[1] in other.functions:
                        _check_handler_body(
                            other, other.functions[bound[1]],
                            findings, root_name, visited,
                            indexes_by_module)
        elif isinstance(func, ast.Attribute):
            attr = func.attr
            base = (func.value.id if isinstance(func.value, ast.Name)
                    else None)
            if (base, attr) in _ALLOWED_CALLS:
                continue
            if (base, attr) in _IO_CALLS:
                flag(node.lineno, f"blocking I/O ({base}.{attr})")
            elif attr == "acquire":
                flag(node.lineno, "a lock .acquire()")
            elif attr == "emit":
                flag(node.lineno, f"event emission ({base}.{attr})")
            elif base is not None:
                bound = index.imports.get(base)
                # `import pkg.mod as m` -> str; `from pkg import mod`
                # -> (pkg, mod): either way, follow the call into the
                # bound module's file IF it is part of the analyzed
                # tree (by_basename lookup — stdlib imports miss it)
                target = None
                if isinstance(bound, str):
                    target = bound.split(".")[-1] + ".py"
                elif isinstance(bound, tuple):
                    target = bound[1] + ".py"
                other = (indexes_by_module.get(target)
                         if target else None)
                if other and attr in other.functions:
                    _check_handler_body(
                        other, other.functions[attr], findings,
                        root_name, visited, indexes_by_module)


def run(project):
    findings = []
    indexes = [(sf, _ModuleIndex(sf)) for sf in project.files]
    by_basename = {}
    for sf, index in indexes:
        by_basename.setdefault(sf.rel.rsplit("/", 1)[-1], index)

    for sf, index in indexes:
        for root in _handler_roots(index):
            _check_handler_body(index, root, findings, root.name,
                                set(), by_basename)

    # never-throws entry points keep their broad handler
    for sf, index in indexes:
        basename = sf.rel.rsplit("/", 1)[-1]
        for want_base, want_class, want_fn in NEVER_RAISE:
            if basename != want_base:
                continue
            fn = _find_function(sf, want_class, want_fn)
            if fn is None:
                continue
            if not _has_broad_handler(fn) \
                    and not sf.waived("obs-must-not-raise", fn.lineno):
                scope = (f"{want_class}.{want_fn}" if want_class
                         else want_fn)
                findings.append(Finding(
                    "obs-must-not-raise", sf.rel, fn.lineno,
                    f"{scope} is a never-throws entry point but has "
                    "no `except Exception` guard — it can raise into "
                    "training code", key=f"obs-must-not-raise:{scope}"))
    return findings


def _find_function(sf, class_name, fn_name):
    if class_name is None:
        for node in sf.tree.body:
            if isinstance(node, ast.FunctionDef) \
                    and node.name == fn_name:
                return node
        return None
    for node in sf.tree.body:
        if isinstance(node, ast.ClassDef) and node.name == class_name:
            for sub in node.body:
                if isinstance(sub, ast.FunctionDef) \
                        and sub.name == fn_name:
                    return sub
    return None


def _has_broad_handler(fn):
    return any(isinstance(node, ast.ExceptHandler)
               and is_broad_handler(node) for node in ast.walk(fn))
