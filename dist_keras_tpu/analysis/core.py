"""dklint core — findings, waivers, baselines, source loading.

The analyzer is a plain AST walk over the package tree: no imports of
the analyzed code (fixture trees lint exactly like the real one), no
third-party dependencies, no I/O beyond reading sources and the
README.  Three building blocks live here:

- :class:`Finding` — one violation: rule, file, line, message, and a
  line-number-FREE fingerprint (rule + file + a stable key, normally
  the stripped source line), so a baseline survives unrelated edits
  above a grandfathered site.
- :class:`SourceFile` — a parsed module plus its ``# dklint:``
  comment maps.  Two comment forms, both honored on the flagged line
  or the line directly above it:

  - ``# dklint: ignore[rule-a,rule-b] <reason>`` — waive findings of
    those rules at this site (the reason is required by convention,
    ignored by the parser).
  - ``# dklint: key=a,b`` — an ANNOTATION feeding a pass: e.g.
    ``# dklint: fault-points=job.rsync,job.ssh`` declares the names a
    dynamic ``fault_point(var)`` call site can take, and
    ``# dklint: metrics=span.*`` names the registered pattern a
    dynamic metric name belongs to.

- the baseline — a checked-in JSON list of fingerprints for
  grandfathered findings, so a new rule lands incrementally: old
  findings are reported as "baselined" and do not fail the run, new
  ones do.  ``--write-baseline`` regenerates it; the shipped baseline
  (``dist_keras_tpu/analysis/baseline.json``) is kept EMPTY — every
  finding at introduction was fixed or explicitly waived in place.
"""

from __future__ import annotations

import ast
import io
import json
import os
import re
import tokenize

# rule -> one-line description (the README rule table is this dict)
RULES = {
    "syntax-error":
        "a source file failed to parse (every other pass skipped it); "
        "always reported, never filtered out by --rules",
    "fault-point-unknown":
        "a fault_point(\"name\") call site names a point missing from "
        "faults.KNOWN_POINTS (chaos mode could never arm it)",
    "fault-point-dynamic":
        "a fault_point call with a computed name lacks a "
        "`# dklint: fault-points=a,b` annotation declaring its names",
    "fault-point-unused":
        "a faults.KNOWN_POINTS entry has no call site (dead registry "
        "row: the chaos gate arms a point that never fires)",
    "knob-read":
        "a DK_* environment variable is read via os.environ/os.getenv "
        "instead of resolving through utils/knobs.py",
    "knob-unregistered":
        "knobs.raw()/knobs.get() is called with a DK_* name that the "
        "registry does not declare",
    "knob-undocumented":
        "a registered knob appears in no README table row",
    "knob-doc-drift":
        "a README table row documents a DK_* name that is not "
        "registered in utils/knobs.py",
    "event-unregistered":
        "an emit(\"kind\") call site names an event missing from "
        "events.KNOWN_EVENTS",
    "event-dynamic":
        "an emit call with a computed kind lacks a "
        "`# dklint: events=a,b` annotation",
    "event-undocumented":
        "a registered event kind is missing from the README "
        "event-schema table",
    "event-doc-drift":
        "the README event-schema table names a kind that is not in "
        "events.KNOWN_EVENTS",
    "metric-unregistered":
        "a counter/gauge/histogram name (or its kind) does not match "
        "metrics.KNOWN_METRICS",
    "metric-dynamic":
        "a metric call with a computed name lacks a "
        "`# dklint: metrics=<registered name or pattern>` annotation",
    "metric-collision":
        "two registered metric names collide after Prometheus "
        "sanitization (their scrape series would merge)",
    "metric-undocumented":
        "a registered metric is missing from the README metrics table",
    "metric-doc-drift":
        "the README metrics table names a metric that is not in "
        "metrics.KNOWN_METRICS",
    "slo-undocumented":
        "a slo.KNOWN_SLOS objective is missing from the README SLO "
        "table (the objective vocabulary is registry-closed like "
        "events and metrics)",
    "slo-doc-drift":
        "the README SLO table names an objective that is not in "
        "slo.KNOWN_SLOS",
    "span-unregistered":
        "a span(...)/span_at(...) call site names a span missing from "
        "spans.KNOWN_SPANS (the report, the Perfetto export and "
        "operator tooling treat the registry as the closed phase "
        "vocabulary)",
    "span-dynamic":
        "a span call with a computed name lacks a "
        "`# dklint: spans=<registered name or pattern>` annotation",
    "signal-unsafe":
        "a lock acquisition, event emission or blocking I/O call is "
        "reachable from a registered signal handler (handlers run "
        "re-entrantly on the main thread and must stay lock-free and "
        "emit-free)",
    "obs-must-not-raise":
        "a never-throws observability entry point lacks the broad "
        "handler its contract promises (it could raise into training "
        "code)",
    "broad-except":
        "`except Exception`/bare `except` without a waiver naming why "
        "the swallow is intentional",
    "untyped-raise":
        "`raise RuntimeError/Exception` in a module with a typed-error "
        "contract, without a waiver naming why no typed class applies",
    "jit-impure":
        "time.time()/perf_counter or random-module calls inside a "
        "jit-compiled function (traced once, frozen forever)",
    "thread-root-unknown":
        "a threading.Thread/Timer target or signal.signal handler does "
        "not resolve to a named root in analysis/threads.py "
        "KNOWN_THREAD_ROOTS (dynamic sites annotate "
        "`# dklint: thread-root=<name>`)",
    "thread-root-unused":
        "a KNOWN_THREAD_ROOTS entry matches no registration site (dead "
        "registry row), or a ~declared root names code that does not "
        "exist",
    "lock-order-cycle":
        "the acquires-while-holding graph (observed `with lock:` "
        "nesting and .acquire() reachability, plus the LOCK_ORDER "
        "declarations) contains a cycle — a potential deadlock",
    "unguarded-shared-write":
        "an instance attribute written from >= 2 distinct thread roots "
        "without a common guarding lock (and it is not a sync "
        "primitive) — waive only with the safety argument (e.g. "
        "reference assignment is atomic under the GIL)",
    "unbounded-wait":
        ".join()/.wait()/.wait_for()/lock.acquire()/future.result()/"
        "queue.get() on a cross-thread seam without a timeout/deadline "
        "— a wedged peer thread must cost one deadline, never a hang",
    "blocking-under-lock":
        "time.sleep, subprocess, socket/HTTP or a fault_point call "
        "(chaos delay = a sleep) reachable while holding a registered "
        "lock — every other acquirer stalls behind it",
    "unused-waiver":
        "a `# dklint: ignore[rule]` waiver whose rule no longer fires "
        "at that site — stale waivers must not accumulate",
    "rule-undocumented":
        "the README has no `<!-- dklint: rules-table -->` marked table, "
        "or a rule in core.RULES has no row in it",
    "rule-doc-drift":
        "the README rules table is out of sync with core.RULES "
        "(regenerate with `python -m dist_keras_tpu.analysis "
        "--rules-table`)",
}


def rules_table():
    """The README rules table, generated from :data:`RULES` (the same
    vocabulary ``--list-rules`` prints) — paste below the
    ``<!-- dklint: rules-table -->`` marker; the ``rule-undocumented`` /
    ``rule-doc-drift`` checks keep it strictly in sync both ways."""
    lines = ["| rule | meaning |", "|---|---|"]
    for rule, doc in RULES.items():
        lines.append(f"| `{rule}` | {' '.join(doc.split())} |")
    return "\n".join(lines)


class Finding:
    """One lint violation."""

    def __init__(self, rule, path, line, message, key=None):
        assert rule in RULES, rule
        self.rule = rule
        self.path = path          # rel path within the analyzed root
        self.line = int(line)
        self.message = message
        self.key = key if key is not None else message
        self.baselined = False

    @property
    def fingerprint(self):
        """Line-number-free identity for the baseline."""
        return f"{self.rule}::{self.path}::{self.key}"

    def sort_key(self):
        return (self.path, self.line, self.rule, self.message)

    def to_dict(self):
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "message": self.message, "baselined": self.baselined}

    def __repr__(self):  # pragma: no cover - debug aid
        return f"Finding({self.rule}, {self.path}:{self.line})"


_WAIVER_RE = re.compile(r"#\s*dklint:\s*ignore\[([a-z\-,\s]+)\]")
_ANNOT_RE = re.compile(
    r"#\s*dklint:\s*([a-z][a-z\-]*)=([A-Za-z0-9_.,*\s\-]+)")


class SourceFile:
    """One parsed module plus its dklint comment maps."""

    def __init__(self, path, rel, text):
        self.path = path
        self.rel = rel
        self.text = text
        self.lines = text.split("\n")
        self.tree = ast.parse(text)  # SyntaxError handled by load_tree
        self.waivers = {}      # lineno (1-based) -> set of rule names
        self.annotations = {}  # lineno -> {key: [values]}
        self.used_waivers = set()  # (waiver lineno, rule) that fired
        for i, line in self._comments():
            m = _WAIVER_RE.search(line)
            if m:
                rules = {r.strip() for r in m.group(1).split(",")
                         if r.strip()}
                self.waivers.setdefault(i, set()).update(rules)
            m = _ANNOT_RE.search(line)
            if m and not line[:m.start()].rstrip().endswith("ignore"):
                values = [v.strip() for v in m.group(2).split(",")
                          if v.strip()]
                self.annotations.setdefault(i, {})[m.group(1)] = values

    def _comments(self):
        """-> (lineno, text) of every real ``#`` comment, via tokenize —
        a docstring or string literal that merely *mentions*
        ``dklint: ignore[...]`` (the analyzer's own docs do) must
        neither waive anything nor trip the ``unused-waiver`` sweep."""
        try:
            return [(tok.start[0], tok.string) for tok in
                    tokenize.generate_tokens(
                        io.StringIO(self.text).readline)
                    if tok.type == tokenize.COMMENT]
        except (tokenize.TokenError, IndentationError):
            # the file parsed (SourceFile requires it), so this is an
            # exotic edge — fall back to the line scan rather than
            # silently dropping every waiver in the file
            return list(enumerate(self.lines, start=1))

    def _comment_block(self, lineno):
        """The flagged line plus the contiguous run of comment-only
        lines directly above it — where a waiver/annotation may sit
        (multi-line rationale comments are the norm in this tree)."""
        yield lineno
        ln = lineno - 1
        while ln >= 1 and self.lines[ln - 1].lstrip().startswith("#"):
            yield ln
            ln -= 1

    def waived(self, rule, lineno):
        """A waiver applies on the flagged line or anywhere in the
        comment block immediately above it.  A match records the waiver
        line as USED — the ``unused-waiver`` sweep flags the rest."""
        for ln in self._comment_block(lineno):
            if rule in self.waivers.get(ln, ()):
                self.used_waivers.add((ln, rule))
                return True
        return False

    def annotation(self, key, lineno):
        """-> the annotated value list at this site, or None."""
        for ln in self._comment_block(lineno):
            values = self.annotations.get(ln, {}).get(key)
            if values is not None:
                return values
        return None

    def line_text(self, lineno):
        """Stripped source text of ``lineno`` — the default stable
        fingerprint key for AST findings."""
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""


class Project:
    """The analyzed tree: parsed sources + optional README text."""

    def __init__(self, root, files, readme_path=None, readme=None,
                 parse_findings=()):
        self.root = root
        self.files = files
        self.readme_path = readme_path
        self.readme = readme
        self.parse_findings = list(parse_findings)


def load_tree(root, readme=None):
    """Parse every ``*.py`` under ``root`` -> :class:`Project`.

    ``readme`` is a path to the markdown file the doc-sync rules check
    (None disables them).  An unparseable source file is itself a
    finding (the tree must at minimum be syntactically valid), not a
    crash.
    """
    root = os.path.abspath(root)
    files, parse_findings = [], []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(d for d in dirnames
                             if d not in ("__pycache__",))
        for name in sorted(filenames):
            if not name.endswith(".py"):
                continue
            path = os.path.join(dirpath, name)
            rel = os.path.relpath(path, root)
            with open(path, encoding="utf-8") as f:
                text = f.read()
            try:
                files.append(SourceFile(path, rel, text))
            except SyntaxError as e:
                parse_findings.append(Finding(
                    "syntax-error", rel, e.lineno or 1,
                    f"unparseable source: {e.msg}", key="syntax-error"))
    readme_text = None
    if readme is not None and os.path.exists(readme):
        with open(readme, encoding="utf-8") as f:
            readme_text = f.read()
    return Project(root, files, readme_path=readme, readme=readme_text,
                   parse_findings=parse_findings)


def run_analysis(root, readme=None, rules=None, timings=None):
    """Run every pass over ``root`` -> sorted list of :class:`Finding`.

    ``readme``: path for the doc-sync rules (None = skipped).
    ``rules``: optional iterable restricting which rule names report.
    ``timings``: optional dict filled with per-pass wall seconds (the
    ``static_lint`` gate records them, and ``tests/test_dklint.py``
    budgets the total so the cross-module graph walks cannot quietly
    slow tier-1's self-check).
    """
    import time as _time

    # late imports: the passes import this module for Finding
    from dist_keras_tpu.analysis import (
        concurrency,
        hygiene,
        registries,
        purity,
    )

    if timings is None:
        timings = {}
    t0 = _time.perf_counter()
    project = load_tree(root, readme=readme)
    timings["load"] = _time.perf_counter() - t0
    findings = list(project.parse_findings)
    for name, pass_run in (("registries", registries.run),
                           ("purity", purity.run),
                           ("hygiene", hygiene.run),
                           ("concurrency", concurrency.run)):
        t0 = _time.perf_counter()
        findings += pass_run(project)
        timings[name] = _time.perf_counter() - t0
    # the unused-waiver sweep runs LAST: only after every pass consulted
    # its waivers do we know which `# dklint: ignore[...]` lines fired
    t0 = _time.perf_counter()
    findings += _unused_waivers(project)
    timings["waivers"] = _time.perf_counter() - t0
    if rules is not None:
        # syntax-error is never filterable: a --rules run that silently
        # skipped an unparseable file would report "clean" on a tree
        # the other passes never even read
        allowed = set(rules) | {"syntax-error"}
        unknown = allowed - set(RULES)
        if unknown:
            raise ValueError(f"unknown rule name(s): {sorted(unknown)}")
        findings = [f for f in findings if f.rule in allowed]
    return sorted(findings, key=Finding.sort_key)


def _unused_waivers(project):
    """A waiver whose rule never fired at its site is itself a finding
    — stale ``ignore[...]`` comments must not accumulate as the code
    under them is fixed or moves away."""
    findings = []
    for sf in project.files:
        for lineno in sorted(sf.waivers):
            for rule in sorted(sf.waivers[lineno]):
                if (lineno, rule) in sf.used_waivers:
                    continue
                if rule == "unused-waiver":
                    # the meta-waiver is consulted right below, never
                    # by a pass — it cannot be "used" in the pass sense
                    continue
                if sf.waived("unused-waiver", lineno):
                    continue
                findings.append(Finding(
                    "unused-waiver", sf.rel, lineno,
                    f"waiver ignore[{rule}] no longer matches a "
                    f"{rule} finding at this site — remove the stale "
                    "waiver (or fix the drifted rule name)",
                    key=f"unused-waiver:{rule}:{sf.line_text(lineno)}"))
    return findings


def import_bindings(tree):
    """-> {local name: binding} for every import in ``tree`` — the one
    extraction both cross-module call-graph walkers (the round-12
    signal-safety pass and the round-15 concurrency pass) resolve
    through.  ``import pkg.mod as m`` binds a dotted-module string;
    ``from pkg import name`` / ``from pkg.mod import fn`` bind a
    ``(module, name)`` tuple."""
    bindings = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                bindings[alias.asname
                         or alias.name.split(".")[0]] = alias.name
        elif isinstance(node, ast.ImportFrom) and node.module:
            for alias in node.names:
                bindings[alias.asname or alias.name] = \
                    (node.module, alias.name)
    return bindings


_BROAD_NAMES = ("Exception", "BaseException")


def is_broad_handler(handler):
    """``except:``, ``except Exception``/``BaseException``, or a tuple
    containing either — the one predicate both the ``broad-except``
    rule (hygiene) and the ``obs-must-not-raise`` rule (purity) share.
    BaseException counts: an even-broader swallow must not be the
    evasion route around the audit invariant."""
    t = handler.type
    if t is None:
        return True
    if isinstance(t, ast.Name) and t.id in _BROAD_NAMES:
        return True
    if isinstance(t, ast.Tuple):
        return any(isinstance(e, ast.Name) and e.id in _BROAD_NAMES
                   for e in t.elts)
    return False


# -- baseline ----------------------------------------------------------

def load_baseline(path):
    """-> set of grandfathered fingerprints (empty for a missing or
    empty file)."""
    if path is None or not os.path.exists(path):
        return set()
    with open(path, encoding="utf-8") as f:
        doc = json.load(f)
    if not isinstance(doc, dict) or not isinstance(
            doc.get("findings", []), list):
        raise ValueError(
            f"malformed baseline {path!r}: expected "
            '{"version": 1, "findings": [fingerprints...]}')
    return set(doc.get("findings", []))


def write_baseline(path, findings):
    """Persist ``findings`` as the new grandfathered set."""
    doc = {"version": 1,
           "findings": sorted({f.fingerprint for f in findings})}
    parent = os.path.dirname(os.path.abspath(path))
    os.makedirs(parent, exist_ok=True)
    with open(path, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=1)
        f.write("\n")


def apply_baseline(findings, fingerprints):
    """Mark findings whose fingerprint is grandfathered; -> the list of
    findings that still FAIL (not baselined)."""
    fresh = []
    for f in findings:
        if f.fingerprint in fingerprints:
            f.baselined = True
        else:
            fresh.append(f)
    return fresh
