"""dklint pass 1 — registry consistency.

Every runtime registry the framework keeps is mirrored by a source
invariant this pass enforces, WITHOUT importing the analyzed code (the
registries are extracted from the AST, so fixture trees lint exactly
like the real one):

- ``faults.KNOWN_POINTS``  <->  every ``fault_point("name")`` call site
  (dynamic-name sites declare their names via
  ``# dklint: fault-points=a,b``), in BOTH directions: an unlisted call
  site is invisible to chaos mode, a dead registry row arms a point
  that never fires.
- ``utils/knobs.py``  <->  every ``DK_*`` environment read.  Reading
  ``os.environ`` with a ``DK_*`` literal anywhere else is a finding;
  so is passing an unregistered name to ``knobs.raw``/``knobs.get``.
  The README knob tables are checked against the registry both ways.
- ``events.KNOWN_EVENTS``  <->  every ``emit("kind")`` call site, and
  the README event-schema table (marked
  ``<!-- dklint: events-table -->``) both ways.
- ``metrics.KNOWN_METRICS``  <->  every ``counter``/``gauge``/
  ``histogram`` name (kind included; dynamic families annotate their
  registered pattern), pairwise collision-freedom of the registered
  names after Prometheus sanitization, and the README metrics table
  (``<!-- dklint: metrics-table -->``) both ways.
- ``spans.KNOWN_SPANS``  <->  every ``span("name")`` /
  ``span_at("name", ...)`` call site (wildcard entries match via
  fnmatch; dynamic names annotate ``# dklint: spans=<pattern>``) —
  the span vocabulary the report, the Perfetto export and operator
  tooling attribute against is registry-closed like the others.
- ``slo.KNOWN_SLOS``  <->  the README SLO objective table (marked
  ``<!-- dklint: slos-table -->``) both ways — an objective nobody
  documented cannot page anyone usefully.
"""

from __future__ import annotations

import ast
import fnmatch
import re

from dist_keras_tpu.analysis.core import Finding, rules_table

_METRIC_KINDS = ("counter", "gauge", "histogram")
_DK_RE = re.compile(r"DK_[A-Z0-9_]+")
_BACKTICK_RE = re.compile(r"`([^`]+)`")
# prometheus.metric_name's sanitization, mirrored (a unit test pins the
# two implementations together)
_PROM_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")


def prom_name(name, kind):
    n = _PROM_NAME_RE.sub("_", str(name))
    if n and n[0].isdigit():
        n = "_" + n
    return "dk_" + n + ("_total" if kind == "counter" else "")


def _str_const(node):
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _call_name(func):
    """'f' for ``f(...)``, 'a.f' resolved to ('a', 'f') for
    ``a.f(...)`` — returns (base_or_None, attr)."""
    if isinstance(func, ast.Name):
        return None, func.id
    if isinstance(func, ast.Attribute):
        base = func.value.id if isinstance(func.value, ast.Name) else None
        return base, func.attr
    return None, None


# -- registry extraction (AST only) ------------------------------------

def _extract_tuple_assign(sf, target_name):
    """-> (values, lineno) for ``TARGET = ("a", "b", ...)``, else None."""
    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.Assign):
            continue
        names = [t.id for t in node.targets if isinstance(t, ast.Name)]
        if target_name not in names:
            continue
        if isinstance(node.value, (ast.Tuple, ast.List)):
            values = [_str_const(e) for e in node.value.elts]
            if all(v is not None for v in values):
                return values, node.lineno
    return None


def _extract_dict_assign(sf, target_name):
    """-> ({key: value}, lineno) for ``TARGET = {"k": "v", ...}``."""
    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.Assign):
            continue
        names = [t.id for t in node.targets if isinstance(t, ast.Name)]
        if target_name not in names:
            continue
        if isinstance(node.value, ast.Dict):
            out = {}
            for k, v in zip(node.value.keys, node.value.values):
                ks, vs = _str_const(k), _str_const(v)
                if ks is None or vs is None:
                    return None
                out[ks] = vs
            return out, node.lineno
    return None


def _extract_registries(project):
    regs = {"faults": None, "events": None, "metrics": None,
            "knobs": None, "spans": None, "slos": None}
    for sf in project.files:
        if regs["faults"] is None:
            found = _extract_tuple_assign(sf, "KNOWN_POINTS")
            if found:
                regs["faults"] = (found[0], sf, found[1])
        if regs["events"] is None:
            found = _extract_tuple_assign(sf, "KNOWN_EVENTS")
            if found:
                regs["events"] = (found[0], sf, found[1])
        if regs["spans"] is None:
            found = _extract_tuple_assign(sf, "KNOWN_SPANS")
            if found:
                regs["spans"] = (found[0], sf, found[1])
        if regs["metrics"] is None:
            found = _extract_dict_assign(sf, "KNOWN_METRICS")
            if found:
                regs["metrics"] = (found[0], sf, found[1])
        if regs["slos"] is None:
            found = _extract_dict_assign(sf, "KNOWN_SLOS")
            if found:
                regs["slos"] = (found[0], sf, found[1])
        if sf.rel.endswith("knobs.py"):
            knob_names = []
            for node in ast.walk(sf.tree):
                if not isinstance(node, ast.Call):
                    continue
                _, attr = _call_name(node.func)
                if attr in ("_register", "register") and node.args:
                    name = _str_const(node.args[0])
                    if name is not None and name.startswith("DK_"):
                        knob_names.append((name, node.lineno, node))
            if knob_names and regs["knobs"] is None:
                regs["knobs"] = (knob_names, sf)
    return regs


# -- environ access detection ------------------------------------------

def _is_environ(node):
    """``os.environ`` (or a bare ``environ`` import)."""
    if isinstance(node, ast.Attribute) and node.attr == "environ":
        return isinstance(node.value, ast.Name) \
            and node.value.id == "os"
    return isinstance(node, ast.Name) and node.id == "environ"


def _environ_read_name(node):
    """The DK_* literal read by this node, or None.

    Forms: ``os.environ.get("DK_X", ...)``, ``os.getenv("DK_X")``,
    ``os.environ["DK_X"]`` (Load), ``"DK_X" in os.environ`` —
    setdefault/pop count as reads too (they return the value)."""
    if isinstance(node, ast.Call):
        base, attr = _call_name(node.func)
        if attr in ("get", "setdefault", "pop") \
                and isinstance(node.func, ast.Attribute) \
                and _is_environ(node.func.value) and node.args:
            return _str_const(node.args[0])
        if base == "os" and attr == "getenv" and node.args:
            return _str_const(node.args[0])
    if isinstance(node, ast.Subscript) \
            and isinstance(node.ctx, ast.Load) \
            and _is_environ(node.value):
        return _str_const(node.slice)
    if isinstance(node, ast.Compare) and len(node.ops) == 1 \
            and isinstance(node.ops[0], (ast.In, ast.NotIn)) \
            and _is_environ(node.comparators[0]):
        return _str_const(node.left)
    return None


# -- README table parsing ----------------------------------------------

def _table_rows(readme):
    """Every markdown table row line -> (lineno, text)."""
    return [(i, line) for i, line in
            enumerate(readme.split("\n"), start=1)
            if line.lstrip().startswith("|")]


def _marked_table_tokens(readme, marker):
    """Backticked first-column tokens of the table following
    ``<!-- dklint: MARKER -->`` -> {token: lineno}, or None when the
    marker is absent.  Built on the same table walk as the strict
    row comparison so the two doc-sync paths cannot drift."""
    rows = _marked_table_data_lines(readme, marker)
    if rows is None:
        return None
    tokens = {}
    for lineno, row in rows:
        cells = row.split("|")
        first = cells[1] if len(cells) > 1 else ""
        for tok in _BACKTICK_RE.findall(first):
            tokens.setdefault(tok.strip(), lineno)
    return tokens


def _knob_table_rows(knob_reg):
    """Reconstruct ``knobs.doc_table()``'s data rows from the AST of
    the ``_register`` calls (all-literal by construction), or None when
    any piece is not statically resolvable.  A unit test pins this
    mirror to the real ``doc_table()`` output."""
    rows = []
    for name, _lineno, call in knob_reg[0]:
        kwargs = {kw.arg: kw.value for kw in call.keywords}
        try:
            default = ast.literal_eval(call.args[1])
        except (ValueError, IndexError):
            return None
        parse = call.args[2] if len(call.args) > 2 else None
        kind_node = kwargs.get("kind")
        if kind_node is not None:
            kind = _str_const(kind_node)
        elif isinstance(parse, ast.Name):
            kind = parse.id
        else:
            kind = None
        doc_node = call.args[3] if len(call.args) > 3 \
            else kwargs.get("doc")
        doc = _str_const(doc_node) if doc_node is not None else None
        if kind is None or doc is None:
            return None
        if default is None:
            default_s = "—"
        elif default == "":
            default_s = '`""`'
        else:
            default_s = f"`{default}`"
        doc = " ".join(doc.split())
        rows.append(f"| `{name}` | {kind} | {default_s} | {doc} |")
    return rows


def _marked_table_data_lines(readme, marker):
    """The data rows (lineno, text) of the table after the marker —
    header and |---| separator skipped — or None when absent."""
    lines = readme.split("\n")
    start = None
    for i, line in enumerate(lines):
        if f"dklint: {marker}" in line:
            start = i + 1
            break
    if start is None:
        return None
    rows, in_table, seen_header = [], False, False
    for i in range(start, len(lines)):
        line = lines[i]
        if line.lstrip().startswith("|"):
            in_table = True
            cells = line.split("|")
            first = cells[1] if len(cells) > 1 else ""
            if set(first.strip()) <= set("-: "):
                continue
            if not seen_header:
                seen_header = True  # the header row
                continue
            rows.append((i + 1, line.strip()))
        elif in_table:
            break
    return rows


# -- the pass ----------------------------------------------------------

def run(project):
    findings = []
    regs = _extract_registries(project)
    fault_reg = regs["faults"]
    event_reg = regs["events"]
    metric_reg = regs["metrics"]
    knob_reg = regs["knobs"]
    span_reg = regs["spans"]

    fault_points = set(fault_reg[0]) if fault_reg else None
    event_names = set(event_reg[0]) if event_reg else None
    metric_names = dict(metric_reg[0]) if metric_reg else None
    metric_patterns = ({n: k for n, k in metric_names.items()
                        if "*" in n} if metric_names else {})
    knob_names = ({entry[0] for entry in knob_reg[0]} if knob_reg
                  else None)
    span_names = set(span_reg[0]) if span_reg else None
    span_patterns = ([n for n in span_names if "*" in n]
                     if span_names else [])

    def span_known(name):
        return (name in span_names
                or any(fnmatch.fnmatchcase(name, p)
                       for p in span_patterns))

    used_fault_points = set()

    def emit_finding(rule, sf, lineno, message, key=None):
        if not sf.waived(rule, lineno):
            findings.append(Finding(rule, sf.rel, lineno, message,
                                    key=key or sf.line_text(lineno)))

    for sf in project.files:
        defines_fault_point = any(
            isinstance(n, ast.FunctionDef) and n.name == "fault_point"
            for n in ast.walk(sf.tree))
        defines_span = any(
            isinstance(n, ast.FunctionDef) and n.name == "span"
            for n in ast.walk(sf.tree))
        for node in ast.walk(sf.tree):
            if not isinstance(node, (ast.Call, ast.Subscript,
                                     ast.Compare)):
                continue
            # DK_* environ reads outside knobs.py
            dk = _environ_read_name(node)
            if dk and dk.startswith("DK_") \
                    and not sf.rel.endswith("knobs.py"):
                emit_finding(
                    "knob-read", sf, node.lineno,
                    f"{dk} read bypasses utils/knobs.py — register "
                    "the knob and resolve through knobs.raw/get",
                    key=f"knob-read:{dk}")
            if not isinstance(node, ast.Call):
                continue
            base, attr = _call_name(node.func)

            # knobs.raw / knobs.get with a literal name
            if base == "knobs" and attr in ("raw", "get") and node.args:
                name = _str_const(node.args[0])
                if name and knob_names is not None \
                        and name not in knob_names:
                    emit_finding(
                        "knob-unregistered", sf, node.lineno,
                        f"knobs.{attr}({name!r}) but {name} is not "
                        "registered in utils/knobs.py",
                        key=f"knob-unregistered:{name}")

            # fault_point call sites (not the definition module's def)
            if attr == "fault_point" and not defines_fault_point:
                name = _str_const(node.args[0]) if node.args else None
                if name is not None:
                    used_fault_points.add(name)
                    if fault_points is not None \
                            and name not in fault_points:
                        emit_finding(
                            "fault-point-unknown", sf, node.lineno,
                            f"fault_point({name!r}) is not listed in "
                            "faults.KNOWN_POINTS — chaos mode can "
                            "never arm it",
                            key=f"fault-point:{name}")
                else:
                    declared = sf.annotation("fault-points",
                                             node.lineno)
                    if declared is None:
                        emit_finding(
                            "fault-point-dynamic", sf, node.lineno,
                            "fault_point with a computed name needs "
                            "`# dklint: fault-points=a,b` declaring "
                            "the names this site can take")
                    else:
                        for name in declared:
                            used_fault_points.add(name)
                            if fault_points is not None \
                                    and name not in fault_points:
                                emit_finding(
                                    "fault-point-unknown", sf,
                                    node.lineno,
                                    f"annotated fault point {name!r} "
                                    "is not in faults.KNOWN_POINTS",
                                    key=f"fault-point:{name}")

            # emit("kind") call sites
            if attr == "emit" and event_names is not None:
                kind = _str_const(node.args[0]) if node.args else None
                if node.args and kind is not None:
                    if kind not in event_names:
                        emit_finding(
                            "event-unregistered", sf, node.lineno,
                            f"emit({kind!r}) is not in "
                            "events.KNOWN_EVENTS",
                            key=f"event:{kind}")
                elif node.args:
                    declared = sf.annotation("events", node.lineno)
                    if declared is None:
                        emit_finding(
                            "event-dynamic", sf, node.lineno,
                            "emit with a computed kind needs "
                            "`# dklint: events=a,b`")
                    else:
                        for kind in declared:
                            if kind not in event_names:
                                emit_finding(
                                    "event-unregistered", sf,
                                    node.lineno,
                                    f"annotated event {kind!r} is not "
                                    "in events.KNOWN_EVENTS",
                                    key=f"event:{kind}")

            # counter/gauge/histogram names
            if attr in _METRIC_KINDS and metric_names is not None \
                    and node.args:
                name = _str_const(node.args[0])
                if name is not None:
                    kind = metric_names.get(name)
                    if kind is None:
                        kind = next(
                            (k for p, k in metric_patterns.items()
                             if fnmatch.fnmatchcase(name, p)), None)
                    if kind is None:
                        emit_finding(
                            "metric-unregistered", sf, node.lineno,
                            f"metric {name!r} is not in "
                            "metrics.KNOWN_METRICS",
                            key=f"metric:{name}")
                    elif kind != attr:
                        emit_finding(
                            "metric-unregistered", sf, node.lineno,
                            f"metric {name!r} is registered as a "
                            f"{kind}, not a {attr}",
                            key=f"metric-kind:{name}")
                else:
                    declared = sf.annotation("metrics", node.lineno)
                    if declared is None:
                        emit_finding(
                            "metric-dynamic", sf, node.lineno,
                            f"{attr} with a computed name needs "
                            "`# dklint: metrics=<registered name or "
                            "pattern>`")
                    else:
                        for pat in declared:
                            kind = metric_names.get(pat)
                            if kind is None:
                                emit_finding(
                                    "metric-unregistered", sf,
                                    node.lineno,
                                    f"annotated metric {pat!r} is not "
                                    "a registered KNOWN_METRICS entry",
                                    key=f"metric:{pat}")
                            elif kind != attr:
                                emit_finding(
                                    "metric-unregistered", sf,
                                    node.lineno,
                                    f"annotated metric {pat!r} is "
                                    f"registered as a {kind}, not a "
                                    f"{attr}",
                                    key=f"metric-kind:{pat}")

            # span("name") / span_at("name", ...) call sites — the
            # span vocabulary is registry-closed like events/metrics
            # (the defining module's own internals are exempt)
            if attr in ("span", "span_at") and span_names is not None \
                    and not defines_span and node.args:
                name = _str_const(node.args[0])
                if name is not None:
                    if not span_known(name):
                        emit_finding(
                            "span-unregistered", sf, node.lineno,
                            f"span {name!r} is not in "
                            "spans.KNOWN_SPANS",
                            key=f"span:{name}")
                else:
                    declared = sf.annotation("spans", node.lineno)
                    if declared is None:
                        emit_finding(
                            "span-dynamic", sf, node.lineno,
                            "span with a computed name needs "
                            "`# dklint: spans=<registered name or "
                            "pattern>`")
                    else:
                        for pat in declared:
                            if pat not in span_names:
                                emit_finding(
                                    "span-unregistered", sf,
                                    node.lineno,
                                    f"annotated span {pat!r} is not a "
                                    "registered KNOWN_SPANS entry",
                                    key=f"span:{pat}")

    # registry -> call-site direction for fault points
    if fault_reg is not None:
        values, sf, lineno = fault_reg
        for name in values:
            if name not in used_fault_points \
                    and not sf.waived("fault-point-unused", lineno):
                findings.append(Finding(
                    "fault-point-unused", sf.rel, lineno,
                    f"KNOWN_POINTS entry {name!r} has no fault_point "
                    "call site (dead registry row)",
                    key=f"fault-point-unused:{name}"))

    # collision-freedom of registered metric names after sanitization
    if metric_reg is not None:
        names, sf, lineno = metric_reg
        seen = {}
        for name, kind in names.items():
            if "*" in name:
                continue
            pn = prom_name(name, kind)
            if pn in seen:
                findings.append(Finding(
                    "metric-collision", sf.rel, lineno,
                    f"metrics {seen[pn]!r} and {name!r} both render "
                    f"as Prometheus series {pn!r}",
                    key=f"metric-collision:{pn}"))
            else:
                seen[pn] = name

    findings += _check_readme(project, knob_reg, event_reg, metric_reg,
                              regs["slos"])
    return findings


def _check_readme(project, knob_reg, event_reg, metric_reg,
                  slo_reg=None):
    findings = []
    readme = project.readme
    if readme is None:
        return findings
    rel = project.readme_path or "README.md"

    # knobs <-> any table row mentioning a DK_* name
    if knob_reg is not None:
        registered = {entry[0] for entry in knob_reg[0]}
        documented = {}
        for lineno, row in _table_rows(readme):
            for m in _DK_RE.finditer(row):
                tok = m.group().rstrip("_")
                if row[m.end():m.end() + 1] == "*":
                    continue  # a DK_FOO_* wildcard, not a knob name
                documented.setdefault(tok, lineno)
        sf_knobs = knob_reg[1]
        for name, lineno, _node in knob_reg[0]:
            if name not in documented:
                findings.append(Finding(
                    "knob-undocumented", sf_knobs.rel, lineno,
                    f"registered knob {name} appears in no README "
                    "table row", key=f"knob-doc:{name}"))
        for name, lineno in sorted(documented.items()):
            if name not in registered:
                findings.append(Finding(
                    "knob-doc-drift", rel, lineno,
                    f"README table documents {name} but "
                    "utils/knobs.py does not register it",
                    key=f"knob-doc-drift:{name}"))
        # strict sync of the GENERATED consolidated table: when the
        # `<!-- dklint: knobs-table -->` marker is present, every row
        # (kind, default, doc — not just the name) must match the
        # registry exactly, in registry order
        expected = _knob_table_rows(knob_reg)
        actual = _marked_table_data_lines(readme, "knobs-table")
        if expected is not None and actual is not None:
            actual_rows = [row for _, row in actual]
            if actual_rows != expected:
                missing = [r for r in expected
                           if r not in actual_rows]
                extra = [(ln, r) for ln, r in actual
                         if r not in expected]
                for row in missing:
                    name = row.split("`")[1]
                    findings.append(Finding(
                        "knob-doc-drift", rel,
                        actual[0][0] if actual else 1,
                        f"consolidated knob table is out of sync "
                        f"with utils/knobs.py for {name}: expected "
                        f"row {row!r} (regenerate with "
                        "`python -m dist_keras_tpu.analysis "
                        "--knob-table`)",
                        key=f"knob-table-sync:{name}"))
                for ln, row in extra:
                    findings.append(Finding(
                        "knob-doc-drift", rel, ln,
                        f"consolidated knob table row {row!r} does "
                        "not match any registry entry (regenerate "
                        "with --knob-table)",
                        key=f"knob-table-extra:{row}"))
                if not missing and not extra:
                    findings.append(Finding(
                        "knob-doc-drift", rel, actual[0][0],
                        "consolidated knob table rows are out of "
                        "ORDER vs the registry (regenerate with "
                        "--knob-table)", key="knob-table-order"))

    # events <-> the marked event-schema table
    if event_reg is not None:
        names, sf_events, reg_line = event_reg
        tokens = _marked_table_tokens(readme, "events-table")
        if tokens is None:
            findings.append(Finding(
                "event-undocumented", rel, 1,
                "README has no `<!-- dklint: events-table -->` marker "
                "before the event-schema table",
                key="events-table-marker"))
        else:
            for name in names:
                if name not in tokens:
                    findings.append(Finding(
                        "event-undocumented", sf_events.rel, reg_line,
                        f"event {name!r} has no row in the README "
                        "event-schema table", key=f"event-doc:{name}"))
            for tok, lineno in sorted(tokens.items()):
                if re.fullmatch(r"[a-z0-9_]+", tok) \
                        and tok not in names:
                    findings.append(Finding(
                        "event-doc-drift", rel, lineno,
                        f"README event-schema table names {tok!r} "
                        "which is not in events.KNOWN_EVENTS",
                        key=f"event-doc-drift:{tok}"))

    # the analyzer's OWN rule table <-> core.RULES (marked, strict,
    # generated by --rules-table — the five concurrency rules can never
    # drift from the docs any more than the knobs/events/metrics can)
    expected = rules_table().splitlines()[2:]
    actual = _marked_table_data_lines(readme, "rules-table")
    if actual is None:
        findings.append(Finding(
            "rule-undocumented", rel, 1,
            "README has no `<!-- dklint: rules-table -->` marker "
            "before the static-analysis rule table",
            key="rules-table-marker"))
    else:
        actual_rows = [row for _, row in actual]
        if actual_rows != expected:
            missing = [r for r in expected if r not in actual_rows]
            extra = [(ln, r) for ln, r in actual
                     if r not in expected]
            for row in missing:
                name = row.split("`")[1]
                findings.append(Finding(
                    "rule-undocumented", rel,
                    actual[0][0] if actual else 1,
                    f"README rules table is missing/stale for rule "
                    f"{name!r}: expected row {row!r} (regenerate with "
                    "`python -m dist_keras_tpu.analysis "
                    "--rules-table`)", key=f"rule-doc:{name}"))
            for ln, row in extra:
                findings.append(Finding(
                    "rule-doc-drift", rel, ln,
                    f"README rules table row {row!r} matches no rule "
                    "in core.RULES (regenerate with --rules-table)",
                    key=f"rule-doc-drift:{row}"))
            if not missing and not extra:
                findings.append(Finding(
                    "rule-doc-drift", rel, actual[0][0],
                    "README rules table rows are out of ORDER vs "
                    "core.RULES (regenerate with --rules-table)",
                    key="rules-table-order"))

    # metrics <-> the marked metrics table
    if metric_reg is not None:
        names, sf_metrics, reg_line = metric_reg
        tokens = _marked_table_tokens(readme, "metrics-table")
        if tokens is None:
            findings.append(Finding(
                "metric-undocumented", rel, 1,
                "README has no `<!-- dklint: metrics-table -->` "
                "marker before the metrics table",
                key="metrics-table-marker"))
        else:
            for name in names:
                if name not in tokens:
                    findings.append(Finding(
                        "metric-undocumented", sf_metrics.rel,
                        reg_line,
                        f"metric {name!r} has no row in the README "
                        "metrics table", key=f"metric-doc:{name}"))
            for tok, lineno in sorted(tokens.items()):
                if re.fullmatch(r"[a-z0-9_.*]+", tok) \
                        and tok not in names:
                    findings.append(Finding(
                        "metric-doc-drift", rel, lineno,
                        f"README metrics table names {tok!r} which is "
                        "not in metrics.KNOWN_METRICS",
                        key=f"metric-doc-drift:{tok}"))

    # SLO objectives <-> the marked SLO table (both ways, like events)
    if slo_reg is not None:
        names, sf_slos, reg_line = slo_reg
        tokens = _marked_table_tokens(readme, "slos-table")
        if tokens is None:
            findings.append(Finding(
                "slo-undocumented", rel, 1,
                "README has no `<!-- dklint: slos-table -->` marker "
                "before the SLO objective table",
                key="slos-table-marker"))
        else:
            for name in names:
                if name not in tokens:
                    findings.append(Finding(
                        "slo-undocumented", sf_slos.rel, reg_line,
                        f"objective {name!r} has no row in the README "
                        "SLO table", key=f"slo-doc:{name}"))
            for tok, lineno in sorted(tokens.items()):
                if re.fullmatch(r"[a-z0-9_]+", tok) \
                        and tok not in names:
                    findings.append(Finding(
                        "slo-doc-drift", rel, lineno,
                        f"README SLO table names {tok!r} which is not "
                        "in slo.KNOWN_SLOS",
                        key=f"slo-doc-drift:{tok}"))
    return findings
