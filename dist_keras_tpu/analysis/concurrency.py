"""dklint pass 4 — concurrency invariants.

Rounds 12-14 grew a real multi-threaded runtime — the async checkpoint
writer, the serving batcher + replica workers, the heartbeat/deadline
threads, the sampler/exporter plane — and its safety arguments lived
only as CHANGES.md prose and regression tests.  This pass turns them
into source invariants, with the same never-imports-the-tree design as
the other passes (registries are extracted from the AST, so fixture
trees lint exactly like the real package):

1. **Thread-root inventory** (``thread-root-unknown`` /
   ``thread-root-unused``).  Every ``threading.Thread(target=...)`` /
   ``threading.Timer`` / ``signal.signal`` registration site must
   resolve to a named root in ``analysis/threads.py``
   ``KNOWN_THREAD_ROOTS`` — the checker's ground truth for *which
   functions execute off the main thread*.  Dynamic sites (a variable
   handler, an inherited ``serve_forever``) annotate
   ``# dklint: thread-root=<name>``.  Registry values: ``"rel:Qual"``
   (must match a resolved site), ``"~rel:Qual"`` / ``"~rel:Class.*"``
   (a framework-dispatched root with no visible registration site —
   e.g. per-request HTTP handler threads; validated to exist, seeds
   reachability), or ``"external"`` (a restored foreign handler; used
   only via annotations).

2. **Lock-order graph** (``lock-order-cycle``).  Registered locks are
   the ``threading.Lock/RLock/Condition`` constructor assignments the
   AST shows (``self._x = threading.Lock()`` / module-level
   ``_lock = ...``).  The pass builds the acquires-while-holding graph:
   lexical ``with lock:`` nesting plus ``.acquire()`` reachability
   through the cross-module call-graph walker (same resolution rules as
   the round-12 signal-safety pass: ``self.m()``, same-module calls by
   name, ``from pkg import mod`` / ``import pkg.mod as m`` bindings
   into analyzed files).  ``LOCK_ORDER`` in ``analysis/threads.py``
   declares the intended orderings once as asserted edges; any cycle
   through observed + declared edges is a potential deadlock.
   Re-entrant locks (RLock, Condition — whose default inner lock is an
   RLock) may self-nest; a plain ``Lock`` self-edge is a length-1
   cycle.

3. **Shared-state audit** (``unguarded-shared-write``).  An instance
   attribute written from >= 2 distinct thread roots (the main thread
   counts as one) must have every write guarded by a common registered
   lock, be a sync primitive (Event/Condition/queue...), or carry a
   waiver naming the safety argument — this mechanically re-derives the
   "reference assignment is atomic" claims scattered through
   CHANGES.md.  ``__init__`` writes are pre-thread by construction and
   exempt; a helper that is *always called* with a lock held inherits
   that lock (intersection over its call sites, to a fixpoint).

4. **Bounded-wait enforcement** (``unbounded-wait``).  ``.join()``,
   ``Condition.wait()`` / ``wait_for()``, ``Event.wait()``,
   ``lock.acquire()`` and ``future.result()`` without a
   timeout/deadline argument are findings — the "a wedged writer costs
   one deadline, never a hang" contract as lint.  (Static check: a
   *passed* timeout variable that is None at runtime still satisfies
   it; the rule catches the overwhelmingly common omission.)

5. **Blocking-under-lock** (``blocking-under-lock``).  No
   ``time.sleep``, subprocess, socket/HTTP or ``fault_point`` call
   (an armed chaos ``delay`` IS a sleep) while holding a registered
   lock — lexically or through the call graph — because every other
   acquirer stalls behind it.

Resolution is deliberately best-effort static: calls through object
attributes other than ``self`` (``self._reg.inc()``) do not resolve,
so the graphs under-approximate — a finding is real, absence of one is
not a proof.  The registry + waivers carry the rest of the argument.
"""

from __future__ import annotations

import ast

from dist_keras_tpu.analysis.core import Finding, import_bindings

_LOCK_CTORS = {"Lock", "RLock", "Condition"}
_REENTRANT_CTORS = {"RLock", "Condition"}
_SYNC_CTORS = _LOCK_CTORS | {
    "Event", "Semaphore", "BoundedSemaphore", "Barrier",
    "Queue", "SimpleQueue", "LifoQueue", "PriorityQueue"}
_SYNC_MODULES = {"threading", "queue"}
_BLOCKING_BASES = {"subprocess", "socket", "requests"}


def _str_const(node):
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _call_name(func):
    if isinstance(func, ast.Name):
        return None, func.id
    if isinstance(func, ast.Attribute):
        base = func.value.id if isinstance(func.value, ast.Name) else None
        return base, func.attr
    return None, None


def _kw(node, name):
    for kw in node.keywords:
        if kw.arg == name:
            return kw.value
    return None


# -- registry extraction ------------------------------------------------

def _extract_thread_registry(project):
    """-> (roots, order): ``KNOWN_THREAD_ROOTS`` as
    ``({name: value}, sf, lineno)`` and ``LOCK_ORDER`` as
    ``([(before, after), ...], sf, lineno)`` — either None when the
    tree does not declare it (fixture trees without a registry skip the
    inventory rules, like the other passes)."""
    roots = order = None
    for sf in project.files:
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Assign):
                continue
            names = [t.id for t in node.targets
                     if isinstance(t, ast.Name)]
            if "KNOWN_THREAD_ROOTS" in names and roots is None \
                    and isinstance(node.value, ast.Dict):
                out = {}
                for k, v in zip(node.value.keys, node.value.values):
                    ks, vs = _str_const(k), _str_const(v)
                    if ks is None or vs is None:
                        out = None
                        break
                    out[ks] = vs
                if out is not None:
                    roots = (out, sf, node.lineno)
            if "LOCK_ORDER" in names and order is None \
                    and isinstance(node.value, (ast.Tuple, ast.List)):
                pairs = []
                for e in node.value.elts:
                    if isinstance(e, (ast.Tuple, ast.List)) \
                            and len(e.elts) == 2:
                        a, b = _str_const(e.elts[0]), \
                            _str_const(e.elts[1])
                        if a is None or b is None:
                            pairs = None
                            break
                        pairs.append((a, b))
                    else:
                        pairs = None
                        break
                if pairs is not None:
                    order = (pairs, sf, node.lineno)
    return roots, order


# -- per-file index -----------------------------------------------------

class _FileIndex:
    """Functions (by dotted qualname), import bindings, registered
    locks/sync attrs, and thread/signal registration sites of one
    module."""

    def __init__(self, sf):
        self.sf = sf
        self.functions = {}    # qual -> def node ("Class.m", "f.inner")
        self.func_class = {}   # qual -> innermost enclosing class name
        self.locks = {}        # (cls_or_None, attr) -> reentrant bool
        self.sync_attrs = set()  # (cls, attr) assigned a sync primitive
        self.thread_sites = []   # (call node, cls, enclosing qual, kind)
        # local name -> binding, via the shared core.import_bindings
        # (one extraction for both cross-module walkers)
        self.imports = import_bindings(sf.tree)
        self._build(sf.tree, None, "")

    def _sync_ctor(self, value):
        """The constructor name if ``value`` builds a lock/sync
        primitive (``threading.Lock()``, ``queue.Queue()``, or a bare
        imported name), else None."""
        if not isinstance(value, ast.Call):
            return None
        base, attr = _call_name(value.func)
        if attr not in _SYNC_CTORS:
            return None
        if base in _SYNC_MODULES:
            return attr
        if base is None and isinstance(value.func, ast.Name):
            bound = self.imports.get(attr)
            if isinstance(bound, tuple) and bound[0] in _SYNC_MODULES:
                return attr
        return None

    def _build(self, node, cls, prefix):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = prefix + child.name
                self.functions[qual] = child
                self.func_class[qual] = cls
                self._build(child, cls, qual + ".")
            elif isinstance(child, ast.ClassDef):
                self._build(child, child.name, prefix + child.name + ".")
            else:
                if isinstance(child, ast.Assign):
                    ctor = self._sync_ctor(child.value)
                    if ctor is not None:
                        for t in child.targets:
                            if isinstance(t, ast.Attribute) \
                                    and isinstance(t.value, ast.Name) \
                                    and t.value.id == "self" \
                                    and cls is not None:
                                self.sync_attrs.add((cls, t.attr))
                                if ctor in _LOCK_CTORS:
                                    self.locks[(cls, t.attr)] = \
                                        ctor in _REENTRANT_CTORS
                            elif isinstance(t, ast.Name) and cls is None \
                                    and not prefix:
                                if ctor in _LOCK_CTORS:
                                    self.locks[(None, t.id)] = \
                                        ctor in _REENTRANT_CTORS
                if isinstance(child, ast.Call):
                    self._note_site(child, cls, prefix)
                self._build(child, cls, prefix)

    def _note_site(self, node, cls, prefix):
        base, attr = _call_name(node.func)
        kind = None
        if attr in ("Thread", "Timer"):
            bound = self.imports.get(attr)
            if base == "threading" or (
                    base is None and isinstance(bound, tuple)
                    and bound[0] == "threading"):
                kind = attr
        elif attr == "signal" and base == "signal" \
                and len(node.args) >= 2:
            kind = "signal"
        if kind is not None:
            qual = prefix[:-1] if prefix.endswith(".") else prefix
            self.thread_sites.append((node, cls, qual, kind))

    def lock_of(self, expr, cls):
        """-> the registered lock key ``(cls_or_None, attr)`` this
        expression names, or None."""
        if isinstance(expr, ast.Attribute) \
                and isinstance(expr.value, ast.Name) \
                and expr.value.id == "self" and cls is not None:
            key = (cls, expr.attr)
            return key if key in self.locks else None
        if isinstance(expr, ast.Name):
            key = (None, expr.id)
            return key if key in self.locks else None
        return None


def _lock_name(lock_id):
    """Display name of a global lock id ``(rel, cls, attr)``."""
    rel, cls, attr = lock_id
    return f"{rel}:{cls}.{attr}" if cls else f"{rel}:{attr}"


def _resolve_call(index, caller_qual, cls, func, by_basename):
    """Resolve a call expression to ``(other_index, qual)`` or None —
    the round-12 walker's rules, extended with nested-scope and
    ``self.method`` resolution."""
    if isinstance(func, ast.Name):
        name = func.id
        parts = caller_qual.split(".") if caller_qual else []
        for i in range(len(parts), -1, -1):
            q = ".".join(parts[:i] + [name])
            if q in index.functions:
                return index, q
        bound = index.imports.get(name)
        if isinstance(bound, tuple):
            other = by_basename.get(bound[0].split(".")[-1] + ".py")
            if other is not None and bound[1] in other.functions:
                return other, bound[1]
        return None
    if isinstance(func, ast.Attribute):
        attr = func.attr
        base = func.value
        if isinstance(base, ast.Name):
            if base.id == "self" and cls is not None:
                q = f"{cls}.{attr}"
                if q in index.functions:
                    return index, q
                return None
            bound = index.imports.get(base.id)
            target = None
            if isinstance(bound, str):
                target = bound.split(".")[-1] + ".py"
            elif isinstance(bound, tuple):
                target = bound[1] + ".py"
            other = by_basename.get(target) if target else None
            if other is not None and attr in other.functions:
                return other, attr
    return None


# -- per-function summaries ---------------------------------------------

class _FnSummary:
    __slots__ = ("acquires", "calls", "blocking", "writes", "waits")

    def __init__(self):
        self.acquires = []   # (lock_id, lineno, held_tuple)
        self.calls = []      # ((rel, qual), lineno, held_frozenset)
        self.blocking = []   # (lineno, description, held_frozenset)
        self.writes = []     # (attr, lineno, held_frozenset)
        self.waits = []      # (lineno, description) — unbounded sites


def _queueish_name(expr):
    """Receiver-name heuristic for ``.get()``: a queue-shaped name
    (``inbox``, ``_queue``...) — dict/env ``.get`` always passes a
    key, so only the zero-arg form even reaches this check."""
    if isinstance(expr, ast.Attribute):
        name = expr.attr
    elif isinstance(expr, ast.Name):
        name = expr.id
    else:
        return False
    name = name.lower()
    return "queue" in name or "inbox" in name


def _wait_finding(node, base, attr, lockish, queueish):
    """-> description when this call is an unbounded cross-thread wait."""
    has_timeout_kw = any(
        kw.arg in ("timeout", "timeout_s", "deadline_s")
        for kw in node.keywords)
    if attr == "join" and not node.args and not node.keywords:
        return ".join() without a timeout"
    if attr == "wait" and not node.args and not has_timeout_kw:
        return ".wait() without a timeout"
    if attr == "wait_for" and len(node.args) < 2 and not has_timeout_kw:
        return ".wait_for(predicate) without a timeout"
    if attr == "result" and not node.args and not has_timeout_kw:
        return ".result() without a timeout"
    if attr == "acquire" and lockish and not node.args \
            and not has_timeout_kw:
        return ".acquire() without a timeout"
    if attr == "get" and queueish and not node.args \
            and not has_timeout_kw:
        return "queue .get() without a timeout"
    return None


def _lockish_name(expr):
    """Name-based lock heuristic for ``.acquire()`` receivers that are
    not registered locks (a parameter, a foreign object)."""
    if isinstance(expr, ast.Attribute):
        name = expr.attr
    elif isinstance(expr, ast.Name):
        name = expr.id
    else:
        return False
    name = name.lower()
    return "lock" in name or "cond" in name or "sem" in name


def _blocking_desc(node, index):
    """-> description when this call blocks (sleep/subprocess/socket/
    HTTP/fault_point), else None."""
    base, attr = _call_name(node.func)
    if attr == "fault_point":
        return "fault_point(...) (a chaos delay is a sleep)"
    if base == "time" and attr == "sleep":
        return "time.sleep(...)"
    if base in _BLOCKING_BASES:
        return f"{base}.{attr}(...)"
    if attr in ("urlopen", "getaddrinfo", "create_connection"):
        return f".{attr}(...)"
    if base is None and isinstance(node.func, ast.Name):
        bound = index.imports.get(node.func.id)
        if isinstance(bound, tuple) and bound[1] == "fault_point":
            return "fault_point(...) (a chaos delay is a sleep)"
        if isinstance(bound, tuple) and bound[0] in _BLOCKING_BASES:
            return f"{bound[0]}.{bound[1]}(...)"
    return None


def _scan(index, qual, by_basename):
    """Walk one function body tracking the lexically held registered
    locks -> :class:`_FnSummary`."""
    cls = index.func_class.get(qual)
    rel = index.sf.rel
    s = _FnSummary()

    def visit(node, held):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            return  # separate summary; a closure runs later, locks free
        if isinstance(node, (ast.With, ast.AsyncWith)):
            new = held
            for item in node.items:
                visit(item.context_expr, new)
                lk = index.lock_of(item.context_expr, cls)
                if lk is not None:
                    gid = (rel,) + lk
                    s.acquires.append((gid, node.lineno, new))
                    new = new + (gid,)
            for b in node.body:
                visit(b, new)
            return
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            for t in targets:
                if isinstance(t, ast.Attribute) \
                        and isinstance(t.value, ast.Name) \
                        and t.value.id == "self" and cls is not None:
                    s.writes.append((t.attr, node.lineno,
                                     frozenset(held)))
        if isinstance(node, ast.Call):
            base, attr = _call_name(node.func)
            receiver = (node.func.value
                        if isinstance(node.func, ast.Attribute)
                        else None)
            lk = (index.lock_of(receiver, cls)
                  if receiver is not None else None)
            if attr == "acquire" and lk is not None:
                s.acquires.append(((rel,) + lk, node.lineno, held))
            if attr in ("join", "wait", "wait_for", "result",
                        "acquire", "get"):
                lockish = lk is not None or (
                    receiver is not None and _lockish_name(receiver))
                queueish = (receiver is not None
                            and _queueish_name(receiver))
                desc = _wait_finding(node, base, attr, lockish,
                                     queueish)
                if desc is not None:
                    s.waits.append((node.lineno, desc))
            desc = _blocking_desc(node, index)
            if desc is not None:
                s.blocking.append((node.lineno, desc, frozenset(held)))
            resolved = _resolve_call(index, qual, cls, node.func,
                                     by_basename)
            if resolved is not None:
                other, oq = resolved
                s.calls.append(((other.sf.rel, oq), node.lineno,
                                frozenset(held)))
        for child in ast.iter_child_nodes(node):
            visit(child, held)

    fn = index.functions[qual]
    for stmt in fn.body:
        visit(stmt, ())
    return s


# -- thread-root site resolution ----------------------------------------

def _resolve_target(index, target, cls, qual):
    """Resolve a Thread ``target=`` / signal handler expression to a
    ``(rel, qual)`` function in this file, or None."""
    if isinstance(target, ast.Name):
        parts = qual.split(".") if qual else []
        for i in range(len(parts), -1, -1):
            q = ".".join(parts[:i] + [target.id])
            if q in index.functions:
                return index.sf.rel, q
        return None
    if isinstance(target, ast.Attribute) \
            and isinstance(target.value, ast.Name) \
            and target.value.id == "self" and cls is not None:
        q = f"{cls}.{target.attr}"
        if q in index.functions:
            return index.sf.rel, q
    return None


def _site_target(node, kind):
    """The target/handler expression of a registration site, or the
    string ``"skip"`` when the site registers nothing to track
    (``signal.signal(sig, SIG_DFL/SIG_IGN)``)."""
    if kind == "signal":
        h = node.args[1]
        if isinstance(h, ast.Attribute) \
                and h.attr in ("SIG_DFL", "SIG_IGN"):
            return "skip"
        return h
    target = _kw(node, "target" if kind == "Thread" else "function")
    if target is not None:
        return target
    if kind == "Timer" and len(node.args) >= 2:
        return node.args[1]
    if kind == "Thread" and len(node.args) >= 2:
        return node.args[1]
    return None


def _closure(seeds, summaries):
    seen = set(seeds)
    stack = list(seen)
    while stack:
        f = stack.pop()
        summ = summaries.get(f)
        if summ is None:
            continue
        for callee, _, _ in summ.calls:
            if callee not in seen:
                seen.add(callee)
                stack.append(callee)
    return seen


# -- the pass -----------------------------------------------------------

def run(project):
    findings = []
    reg, order_reg = _extract_thread_registry(project)

    indexes = {}
    by_basename = {}
    for sf in project.files:
        idx = _FileIndex(sf)
        indexes[sf.rel] = idx
        base = sf.rel.rsplit("/", 1)[-1]
        if base != "__init__.py":
            by_basename.setdefault(base, idx)

    summaries = {}
    for rel, idx in indexes.items():
        for qual in idx.functions:
            summaries[(rel, qual)] = _scan(idx, qual, by_basename)

    def flag(rule, sf, lineno, message, key):
        if not sf.waived(rule, lineno):
            findings.append(Finding(rule, sf.rel, lineno, message,
                                    key=key))

    # ---- 1. thread-root inventory + root seeds -------------------------
    reg_map = reg[0] if reg else {}
    reg_by_value = {v: k for k, v in reg_map.items()}
    used_keys = set()
    root_seeds = {}  # root display name -> set of (rel, qual) seeds

    for rel, idx in indexes.items():
        sf = idx.sf
        for node, cls, qual, kind in idx.thread_sites:
            target = _site_target(node, kind)
            if target == "skip":
                continue
            resolved = (None if target is None
                        else _resolve_target(idx, target, cls, qual))
            if resolved is not None:
                value = f"{resolved[0]}:{resolved[1]}"
                name = reg_by_value.get(value)
                if name is not None:
                    used_keys.add(name)
                elif reg is not None:
                    declared = sf.annotation("thread-root", node.lineno)
                    if declared and all(d in reg_map for d in declared):
                        used_keys.update(declared)
                        name = declared[0]
                    else:
                        flag("thread-root-unknown", sf, node.lineno,
                             f"{kind} registration targets {value!r} "
                             "which is not a named root in "
                             "KNOWN_THREAD_ROOTS",
                             key=f"thread-root:{value}")
                root_seeds.setdefault(name or value,
                                      set()).add(resolved)
            else:
                declared = sf.annotation("thread-root", node.lineno)
                if declared is None:
                    if reg is not None:
                        flag("thread-root-unknown", sf, node.lineno,
                             f"{kind} registration with a computed "
                             "target needs `# dklint: "
                             "thread-root=<name>` naming a "
                             "KNOWN_THREAD_ROOTS entry",
                             key="thread-root-dynamic:"
                                 f"{sf.line_text(node.lineno)}")
                    continue
                for name in declared:
                    if reg is not None and name not in reg_map:
                        flag("thread-root-unknown", sf, node.lineno,
                             f"annotated thread root {name!r} is not "
                             "in KNOWN_THREAD_ROOTS",
                             key=f"thread-root:{name}")
                    else:
                        used_keys.add(name)

    # ~declared roots (framework-dispatched, no registration site)
    if reg is not None:
        reg_sf, reg_line = reg[1], reg[2]
        for name, value in reg_map.items():
            if value == "external":
                continue
            if not value.startswith("~"):
                continue
            loc = value[1:]
            rel, _, q = loc.partition(":")
            idx = indexes.get(rel)
            seeds = set()
            if idx is not None:
                if q.endswith(".*"):
                    prefix = q[:-1]  # "Class."
                    seeds = {(rel, fq) for fq in idx.functions
                             if fq.startswith(prefix)}
                elif q in idx.functions:
                    seeds = {(rel, q)}
            if not seeds:
                if not reg_sf.waived("thread-root-unused", reg_line):
                    findings.append(Finding(
                        "thread-root-unused", reg_sf.rel, reg_line,
                        f"declared root {name!r} -> {value!r} resolves "
                        "to no function in the analyzed tree",
                        key=f"thread-root-unused:{name}"))
            else:
                used_keys.add(name)
                root_seeds.setdefault(name, set()).update(seeds)
        for name, value in reg_map.items():
            if name in used_keys or value.startswith("~"):
                continue
            if not reg_sf.waived("thread-root-unused", reg_line):
                findings.append(Finding(
                    "thread-root-unused", reg_sf.rel, reg_line,
                    f"KNOWN_THREAD_ROOTS entry {name!r} -> {value!r} "
                    "matches no registration site or annotation (dead "
                    "registry row)", key=f"thread-root-unused:{name}"))

    # ---- reachability: which functions run under which roots -----------
    root_reach = {name: _closure(seeds, summaries)
                  for name, seeds in root_seeds.items()}
    off_main = set()
    for reach in root_reach.values():
        off_main |= reach
    main_seeds = [f for f in summaries if f not in off_main]
    main_reach = _closure(main_seeds, summaries)

    def roots_of(f):
        roots = {name for name, reach in root_reach.items()
                 if f in reach}
        if f in main_reach or not roots:
            roots.add("main")
        return roots

    # ---- held-at-every-call-site fixpoint ------------------------------
    callers = {}
    for f, summ in summaries.items():
        for callee, _, held in summ.calls:
            callers.setdefault(callee, []).append((f, held))
    held_env = {f: None for f in summaries}  # None = TOP (unknown)
    for _ in range(30):
        changed = False
        for f in summaries:
            cl = callers.get(f)
            if not cl:
                new = frozenset()
            else:
                acc = None
                for caller, held in cl:
                    ce = held_env.get(caller)
                    if ce is None and not held:
                        continue  # TOP caller adds no constraint
                    site = set(held) | set(ce or ())
                    acc = site if acc is None else (acc & site)
                new = None if acc is None else frozenset(acc)
            if new != held_env[f]:
                held_env[f] = new
                changed = True
        if not changed:
            break

    def env_of(f):
        e = held_env.get(f)
        return e if e is not None else frozenset()

    # ---- 3. shared-state audit -----------------------------------------
    writes = {}
    for (rel, qual), summ in summaries.items():
        cls = indexes[rel].func_class.get(qual)
        if cls is None:
            continue
        fname = qual.split(".")[-1] if "." in qual else qual
        # writes inside __init__ (or nested defs of it) happen before
        # any thread this object starts exists
        in_init = "__init__" in qual.split(".")
        for attr, lineno, held in summ.writes:
            writes.setdefault((rel, cls, attr), []).append(
                ((rel, qual), fname, lineno, held, in_init))
    for (rel, cls, attr), sites in sorted(writes.items()):
        idx = indexes[rel]
        if (cls, attr) in idx.sync_attrs:
            continue
        live = [s for s in sites if not s[4]]
        if not live:
            continue
        all_roots = set()
        for f, _, _, _, _ in live:
            all_roots |= roots_of(f)
        if len(all_roots) < 2:
            continue
        effective = [frozenset(h) | env_of(f)
                     for f, _, _, h, _ in live]
        if frozenset.intersection(*effective):
            continue  # every write guarded by a common lock
        # flag the bare writes when some exist (the actionable sites);
        # when every write IS locked but by different locks, flag all
        unguarded = [s for s, eff in zip(live, effective) if not eff]
        flag_sites = unguarded or live
        for f, _, lineno, held, _ in flag_sites:
            eff = frozenset(held) | env_of(f)
            locks = (", ".join(sorted(_lock_name(g) for g in eff))
                     or "no lock")
            flag("unguarded-shared-write", idx.sf, lineno,
                 f"self.{attr} is written from threads "
                 f"{sorted(all_roots)} but this write holds {locks} "
                 "(no common lock across all write sites) — guard it, "
                 "make it a sync primitive, or waive with the safety "
                 "argument",
                 key=f"unguarded-shared-write:{cls}.{attr}:"
                     f"{idx.sf.line_text(lineno)}")

    # ---- 4. bounded-wait ------------------------------------------------
    for (rel, qual), summ in summaries.items():
        sf = indexes[rel].sf
        for lineno, desc in summ.waits:
            flag("unbounded-wait", sf, lineno,
                 f"{desc} can hang forever on a wedged peer thread — "
                 "pass a timeout/deadline or waive with the reason the "
                 "wait is bounded elsewhere",
                 key=f"unbounded-wait:{qual}:{sf.line_text(lineno)}")

    # ---- 2. lock-order graph -------------------------------------------
    all_acquires = {f: {g for g, _, _ in summ.acquires}
                    for f, summ in summaries.items()}
    for _ in range(30):
        changed = False
        for f, summ in summaries.items():
            acc = all_acquires[f]
            before = len(acc)
            for callee, _, _ in summ.calls:
                acc |= all_acquires.get(callee, set())
            if len(acc) != before:
                changed = True
        if not changed:
            break

    edges = {}  # (A_name, B_name) -> (sf, lineno) first observed

    def add_edge(a, b, sf, lineno, reentrant_ok):
        if a == b and reentrant_ok:
            return
        an, bn = _lock_name(a), _lock_name(b)
        edges.setdefault((an, bn), (sf, lineno))

    for (rel, qual), summ in summaries.items():
        idx = indexes[rel]
        for gid, lineno, held in summ.acquires:
            re_ok = idx.locks.get(gid[1:], False)
            for h in held:
                add_edge(h, gid, idx.sf, lineno, re_ok and h == gid)
        for callee, lineno, held in summ.calls:
            if not held:
                continue
            for gid in all_acquires.get(callee, ()):
                c_rel = gid[0]
                re_ok = indexes[c_rel].locks.get(gid[1:], False)
                for h in held:
                    add_edge(h, gid, idx.sf, lineno,
                             re_ok and h == gid)

    def _known_lock(name):
        rel, _, rest = name.partition(":")
        idx = indexes.get(rel)
        if idx is None:
            return False
        cls, _, attr = rest.rpartition(".")
        return (cls or None, attr or rest) in idx.locks

    graph = {}
    for (a, b), site in edges.items():
        graph.setdefault(a, set()).add(b)
    if order_reg is not None:
        for a, b in order_reg[0]:
            # a declaration that names no registered lock declares
            # nothing — it would rot silently, like a stale waiver
            for name in (a, b):
                if not _known_lock(name):
                    flag("lock-order-cycle", order_reg[1],
                         order_reg[2],
                         f"LOCK_ORDER declares {name!r} which matches "
                         "no registered lock in the analyzed tree",
                         key=f"lock-order-unknown:{name}")
            graph.setdefault(a, set()).add(b)

    for cycle in _find_cycles(graph):
        members = set(cycle)
        observed = sorted(
            ((sf, lineno, a, b) for (a, b), (sf, lineno)
             in edges.items() if a in members and b in members),
            key=lambda t: (t[0].rel, t[1]))
        if any(sf.waived("lock-order-cycle", lineno)
               for sf, lineno, _, _ in observed):
            continue
        if observed:
            sf, lineno = observed[0][0], observed[0][1]
        elif order_reg is not None:
            sf, lineno = order_reg[1], order_reg[2]
        else:  # pragma: no cover - cycle needs at least one edge
            continue
        findings.append(Finding(
            "lock-order-cycle", sf.rel, lineno,
            "potential deadlock: locks acquired in a cycle "
            f"({' -> '.join(cycle + [cycle[0]])}) — fix the order or "
            "declare the intended one in LOCK_ORDER",
            key="lock-order-cycle:" + ",".join(sorted(members))))

    # ---- 5. blocking-under-lock ----------------------------------------
    blocks = {f: (summ.blocking[0][1] if summ.blocking else None)
              for f, summ in summaries.items()}
    for _ in range(30):
        changed = False
        for f, summ in summaries.items():
            if blocks[f] is not None:
                continue
            for callee, _, _ in summ.calls:
                via = blocks.get(callee)
                if via is not None:
                    blocks[f] = f"{via} via {callee[1]}()"
                    changed = True
                    break
        if not changed:
            break

    for (rel, qual), summ in summaries.items():
        sf = indexes[rel].sf
        for lineno, desc, held in summ.blocking:
            if held:
                locks = ", ".join(sorted(_lock_name(g) for g in held))
                flag("blocking-under-lock", sf, lineno,
                     f"{desc} while holding {locks} — every other "
                     "acquirer stalls behind it",
                     key=f"blocking-under-lock:{qual}:"
                         f"{sf.line_text(lineno)}")
        for callee, lineno, held in summ.calls:
            if not held:
                continue
            via = blocks.get(callee)
            if via is None:
                continue
            locks = ", ".join(sorted(_lock_name(g) for g in held))
            flag("blocking-under-lock", sf, lineno,
                 f"{via} via {callee[1]}() while holding {locks} — "
                 "every other acquirer stalls behind it",
                 key=f"blocking-under-lock:{qual}:"
                     f"{sf.line_text(lineno)}")

    return findings


def _find_cycles(graph):
    """-> list of cycles (each a list of node names) — one per strongly
    connected component with >= 2 nodes, plus self-loops.  Iterative
    Tarjan (the tree is small, but recursion depth must not depend on
    it)."""
    index = {}
    low = {}
    on_stack = set()
    stack = []
    sccs = []
    counter = [0]

    for start in sorted(graph):
        if start in index:
            continue
        work = [(start, iter(sorted(graph.get(start, ()))))]
        index[start] = low[start] = counter[0]
        counter[0] += 1
        stack.append(start)
        on_stack.add(start)
        while work:
            node, it = work[-1]
            advanced = False
            for nxt in it:
                if nxt not in index:
                    index[nxt] = low[nxt] = counter[0]
                    counter[0] += 1
                    stack.append(nxt)
                    on_stack.add(nxt)
                    work.append((nxt, iter(sorted(graph.get(nxt,
                                                            ())))))
                    advanced = True
                    break
                elif nxt in on_stack:
                    low[node] = min(low[node], index[nxt])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                comp = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comp.append(w)
                    if w == node:
                        break
                if len(comp) > 1 or node in graph.get(node, ()):
                    sccs.append(sorted(comp))
    return sccs
