"""Windowed-commit machinery + the async optimizer family.

The reference's asynchronous parameter-server optimizers (workers.py:~230-600
+ parameter_servers.py:~200-330) share one skeleton: train locally for
``communication_window`` batches, then exchange an update with the center
variable.  On lockstep SPMD hardware the exchange compiles to one collective:

- DOWNPOUR  (workers.py:~230): commit the accumulated weight delta; pull.
  -> center += psum(local - center); local = center.
- ADAG      (workers.py:~300): DOWNPOUR with the delta normalised by the
  window length before commit.
  -> center += psum((local - center) / W).
- AEASGD    (workers.py:~370): elastic averaging; every tau steps the worker
  moves toward the center by E = alpha*(theta_i - center) and commits E.
  -> E_i = alpha*(local - center); local -= E_i; center += psum(E_i).
- EAMSGD    (workers.py:~450): AEASGD + Nesterov momentum on the local
  update (handled by wrapping the worker optimizer with optax.trace).

Mechanism-vs-behavior note (SURVEY.md §7 "hard parts"): in the reference
these commits are *asynchronous* and interleave arbitrarily; under SPMD all
workers commit at the same step, which reproduces the communication pattern
and the update algebra but with zero staleness.  DynSGD, whose whole point is
staleness, gets a genuinely staggered emulation in ``dynsgd.py``.

Everything here runs inside one jitted ``shard_map``: outer ``lax.scan`` over
windows, inner ``lax.scan`` over the window's batches, one pytree collective
per window riding ICI.
"""

from __future__ import annotations

import warnings

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import PartitionSpec as P

from dist_keras_tpu.parallel.collectives import tree_psum, tree_pvary
from dist_keras_tpu.parallel.mesh import WORKER_AXIS
from dist_keras_tpu.comm import backend as comm
from dist_keras_tpu.trainers.base import DistributedTrainer
from dist_keras_tpu.trainers.step import make_model_step
from dist_keras_tpu.utils.pytree import (
    tree_add,
    tree_merge_floats,
    tree_scale,
    tree_sub,
)
from dist_keras_tpu.utils.sync import drain

try:
    from jax import shard_map
except ImportError:  # older jax
    from jax.experimental.shard_map import shard_map


class AsynchronousDistributedTrainer(DistributedTrainer):
    """Base of the windowed family (trainers.py:~420).

    ``parallelism_factor`` (trainers.py:~310) is accepted for parity but is
    a deliberate no-op: the reference oversubscribes Spark partitions so a
    straggling executor can be load-balanced, a failure mode lockstep SPMD
    does not have — every worker is one mesh slot and ``worker_shards``
    already deals all rows evenly across workers.
    """

    def __init__(self, keras_model, num_workers=2, communication_window=5,
                 parallelism_factor=1, **kw):
        super().__init__(keras_model, num_workers=num_workers, **kw)
        self.communication_window = int(communication_window)
        self.parallelism_factor = int(parallelism_factor)

    def _cache_extras(self):
        # the per-chunk epoch count is appended via _compiled(extra_key=)
        return super()._cache_extras() + (self.communication_window,)

    # --- strategy hooks -------------------------------------------------
    def wrap_optimizer(self, tx):
        return tx

    def merge(self, center, local):
        """(center, local) -> (center', local'), called once per window with
        the worker axis bound."""
        raise NotImplementedError

    # --- shared training loop ------------------------------------------
    def train(self, dataset, shuffle=False):
        """Epochs run as an outer ``lax.scan`` over device-resident shard
        tensors (one H2D transfer).  With no hooks requested the whole
        num_epoch run is ONE dispatch; ``checkpoint_every``/``callbacks``
        chunk the dispatch at epoch boundaries, with all worker state
        (local replicas, optimizer state) carried across chunks — exactly
        as a long-lived reference worker's state persists
        (workers.py:~150) — so training is resumable mid-run."""
        import time as _time

        model, loss_fn, tx = self._resolve()
        tx = self.wrap_optimizer(tx)
        if shuffle:
            dataset = dataset.shuffle(seed=self.seed)
        xs, ys = self._shards(dataset)  # (workers, steps, batch, ...)

        W = min(self.communication_window, xs.shape[1])
        windows = xs.shape[1] // W
        # Whole windows only, cut per epoch (remainder dropped every epoch,
        # like the reference's fixed mini-batching) — warn so silent data
        # loss / window shrinkage is visible.
        if W < self.communication_window:
            warnings.warn(
                f"communication_window={self.communication_window} > "
                f"{xs.shape[1]} steps per worker per epoch; effective "
                f"window shrunk to {W}", stacklevel=2)
        dropped = xs.shape[1] - windows * W
        if dropped:
            warnings.warn(
                f"dropping {dropped} trailing step(s) per worker per epoch "
                f"(not a whole communication window)", stacklevel=2)
        # leading axis is LOCAL workers (== num_workers single-process;
        # this host's slice when multi-host, see base._shards)
        xs = xs[:, :windows * W].reshape(
            xs.shape[0], windows, W, *xs.shape[2:])
        ys = ys[:, :windows * W].reshape(
            ys.shape[0], windows, W, *ys.shape[2:])

        mesh = self.mesh
        merge = self.merge
        step, opt_init = make_model_step(
            model, loss_fn, tx, self.compute_dtype)

        def build_chunk(E):
            def body(center, local, opt_state, xs, ys, key, epoch0):
                xs, ys = xs[0], ys[0]  # (windows, W, batch, ...)
                widx = jax.lax.axis_index(WORKER_AXIS)
                # carry state arrives stacked (1, ...) per worker shard
                local = jax.tree.map(lambda t: t[0], local)
                opt_state = jax.tree.map(lambda t: t[0], opt_state)

                def window(carry, batch):
                    center, local, opt_state, rng = carry
                    xw, yw = batch
                    (local, opt_state, rng), losses = jax.lax.scan(
                        step, (local, opt_state, rng), (xw, yw))
                    new_center, new_local = merge(center, local)
                    # integer leaves (Keras seed-generator counters) are
                    # RNG state, not weights: exempt from merge algebra
                    center = tree_merge_floats(new_center, center)
                    local = tree_merge_floats(new_local, local)
                    # merges that reset local to the (replicated) center
                    # must hand back a varying-typed local for next window
                    local = tree_pvary(local)
                    return (center, local, opt_state, rng), losses

                def epoch(carry, e):
                    center, local, opt_state = carry
                    rng = tree_pvary(jax.random.fold_in(
                        jax.random.fold_in(key, e), widx))
                    (center, local, opt_state, _), losses = jax.lax.scan(
                        window, (center, local, opt_state, rng), (xs, ys))
                    return (center, local, opt_state), losses

                (center, local, opt_state), losses = jax.lax.scan(
                    epoch, (center, local, opt_state),
                    jnp.arange(E) + epoch0)
                stack = lambda t: t[None]  # noqa: E731
                return (center, jax.tree.map(stack, local),
                        jax.tree.map(stack, opt_state), losses[None])

            return jax.jit(shard_map(
                body, mesh=mesh,
                in_specs=(P(), P(WORKER_AXIS), P(WORKER_AXIS),
                          P(WORKER_AXIS), P(WORKER_AXIS), P(), P()),
                out_specs=(P(), P(WORKER_AXIS), P(WORKER_AXIS),
                           P(WORKER_AXIS)),
            ))

        # initial carry (stacked per worker on the leading axis)
        center = model.params
        local = self._stack_workers(center)
        opt_state = self._stack_workers(opt_init(center))
        template = {"center": center, "local": local,
                    "opt_state": opt_state}
        start_epoch, restored = self._maybe_resume(template)
        if restored is not None:
            center = restored["center"]
            local = restored["local"]
            opt_state = restored["opt_state"]

        xs = self._to_device(xs)
        ys = self._to_device(ys)
        drain(xs, ys)  # data distribution completes OUTSIDE the clock
        key = jax.random.PRNGKey(self.seed)
        samples_per_epoch = self.num_workers * windows * W * self.batch_size

        self.record_training_start()
        all_losses = []
        epochs_done = start_epoch
        for E in self._chunk_plan(start_epoch):
            fn = self._compiled(lambda: build_chunk(E), extra_key=(E,))
            t0 = _time.time()
            center, local, opt_state, losses = fn(
                center, local, opt_state, xs, ys, key,
                jnp.int32(epochs_done))
            drain(center)  # block_until_ready lies through the tunnel
            dt = _time.time() - t0
            epochs_done += E
            losses = np.asarray(comm.fetch_global(losses))  # (workers, E, windows, W)
            all_losses.append(losses)
            self._emit_epoch_end(epochs_done, losses, dt,
                                 samples_per_epoch * E)
            self._maybe_checkpoint(
                epochs_done,
                lambda: {"center": center, "local": local,
                         "opt_state": opt_state})
        self.record_training_end()

        history = (np.concatenate(all_losses, axis=1).tolist()
                   if all_losses else [])
        # history: (workers, epochs, windows, W)
        return self._finalize(center, history)


class DOWNPOUR(AsynchronousDistributedTrainer):
    """trainers.py:~470 / workers.py:~230."""

    def __init__(self, keras_model, communication_window=5, **kw):
        super().__init__(keras_model,
                         communication_window=communication_window, **kw)

    def merge(self, center, local):
        delta = tree_sub(local, center)
        center = tree_add(center, tree_psum(delta))
        return center, center


class ADAG(AsynchronousDistributedTrainer):
    """Accumulated-gradient normalisation (trainers.py:~530,
    workers.py:~300): the window's accumulated delta is divided by the
    window length before the commit."""

    def __init__(self, keras_model, communication_window=12, **kw):
        super().__init__(keras_model,
                         communication_window=communication_window, **kw)

    def merge(self, center, local):
        delta = tree_scale(tree_sub(local, center),
                           1.0 / self.communication_window)
        center = tree_add(center, tree_psum(delta))
        return center, center


class AEASGD(AsynchronousDistributedTrainer):
    """Asynchronous elastic averaging SGD (trainers.py:~590,
    workers.py:~370). alpha = learning_rate * rho."""

    def __init__(self, keras_model, communication_window=32, rho=5.0,
                 learning_rate=0.1, **kw):
        super().__init__(keras_model,
                         communication_window=communication_window, **kw)
        self.rho = float(rho)
        self.learning_rate = float(learning_rate)

    def _cache_extras(self):
        return super()._cache_extras() + (self.rho, self.learning_rate)

    def merge(self, center, local):
        alpha = self.learning_rate * self.rho
        elastic = tree_scale(tree_sub(local, center), alpha)
        local = tree_sub(local, elastic)
        center = tree_add(center, tree_psum(elastic))
        return center, local


class EAMSGD(AEASGD):
    """AEASGD + Nesterov momentum on the local update (trainers.py:~650,
    workers.py:~450): the worker optimizer's updates go through a Nesterov
    momentum trace."""

    def __init__(self, keras_model, momentum=0.9, **kw):
        super().__init__(keras_model, **kw)
        self.momentum = float(momentum)

    def _cache_extras(self):
        return super()._cache_extras() + (self.momentum,)

    def wrap_optimizer(self, tx):
        return optax.chain(
            tx, optax.trace(decay=self.momentum, nesterov=True))
