"""Windowed-commit machinery + the async optimizer family.

The reference's asynchronous parameter-server optimizers (workers.py:~230-600
+ parameter_servers.py:~200-330) share one skeleton: train locally for
``communication_window`` batches, then exchange an update with the center
variable.  On lockstep SPMD hardware the exchange compiles to one collective:

- DOWNPOUR  (workers.py:~230): commit the accumulated weight delta; pull.
  -> center += psum(local - center); local = center.
- ADAG      (workers.py:~300): DOWNPOUR with the delta normalised by the
  window length before commit.
  -> center += psum((local - center) / W).
- AEASGD    (workers.py:~370): elastic averaging; every tau steps the worker
  moves toward the center by E = alpha*(theta_i - center) and commits E.
  -> E_i = alpha*(local - center); local -= E_i; center += psum(E_i).
- EAMSGD    (workers.py:~450): AEASGD + Nesterov momentum on the local
  update (handled by wrapping the worker optimizer with optax.trace).

Mechanism-vs-behavior note (SURVEY.md §7 "hard parts"): in the reference
these commits are *asynchronous* and interleave arbitrarily; under SPMD all
workers commit at the same step, which reproduces the communication pattern
and the update algebra but with zero staleness.  DynSGD, whose whole point is
staleness, gets a genuinely staggered emulation in ``dynsgd.py``.

Everything here runs inside one jitted ``shard_map``: outer ``lax.scan`` over
windows, inner ``lax.scan`` over the window's batches, one pytree collective
per window riding ICI.
"""

from __future__ import annotations

import warnings

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import PartitionSpec as P

from dist_keras_tpu.parallel.collectives import (
    AsyncMerge,
    tree_psum,
    tree_pvary,
)
from dist_keras_tpu.parallel.mesh import WORKER_AXIS
from dist_keras_tpu.comm import backend as comm
from dist_keras_tpu.trainers.base import DistributedTrainer
from dist_keras_tpu.trainers.chunking import init_streaming, run_chunked
from dist_keras_tpu.utils import knobs
from dist_keras_tpu.utils.pytree import (
    tree_add,
    tree_merge_floats,
    tree_scale,
    tree_sub,
    tree_zeros_like,
)

try:
    from jax import shard_map
except ImportError:  # older jax
    from jax.experimental.shard_map import shard_map


class AsynchronousDistributedTrainer(DistributedTrainer):
    """Base of the windowed family (trainers.py:~420).

    ``parallelism_factor`` (trainers.py:~310) is accepted for parity but is
    a deliberate no-op: the reference oversubscribes Spark partitions so a
    straggling executor can be load-balanced, a failure mode lockstep SPMD
    does not have — every worker is one mesh slot and ``worker_shards``
    already deals all rows evenly across workers.
    """

    def __init__(self, keras_model, num_workers=2, communication_window=5,
                 parallelism_factor=1, checkpoint_every_windows=None,
                 stream_chunk_windows=None, max_resident_bytes=None,
                 comm_overlap=None, **kw):
        super().__init__(keras_model, num_workers=num_workers, **kw)
        self.communication_window = int(communication_window)
        self.parallelism_factor = int(parallelism_factor)
        # overlapped window collectives (round 19): None defers to the
        # DK_COMM_OVERLAP knob at train() time (launcher-export wins),
        # an explicit bool pins it per trainer
        self.comm_overlap = comm_overlap
        self._overlap = False  # resolved per train() call
        # window-granular checkpoint cadence: a preemption then loses at
        # most ``checkpoint_every_windows`` communication windows, not a
        # whole epoch (the reference's big-DataFrame case,
        # trainers.py:~360, can make one epoch arbitrarily long)
        self.checkpoint_every_windows = (
            int(checkpoint_every_windows) if checkpoint_every_windows
            else None)
        if self.checkpoint_every_windows and not self.checkpoint_dir:
            raise ValueError(
                "checkpoint_every_windows requires checkpoint_dir")
        # ---- streaming input pipeline (the reference's partition-iterator
        # property, workers.py:~60: an epoch never has to fit on-device).
        # stream_chunk_windows=C streams the data C windows per dispatch
        # through a double-buffered ChunkFeed (<= 2 chunks ever resident);
        # max_resident_bytes=B auto-enables streaming whenever the epoch
        # tensor would exceed B bytes of device memory, sizing C so two
        # in-flight chunks fit inside B.  Default (both None) keeps the
        # round-1 whole-run-resident fast path.
        init_streaming(self, stream_chunk_windows, max_resident_bytes)

    def _cache_extras(self):
        # the per-chunk epoch count is appended via _compiled(extra_key=)
        # (the overlap flag changes the scan carry STRUCTURE, so it must
        # key the executable cache too)
        return super()._cache_extras() + (self.communication_window,
                                          self._overlap)

    # --- strategy hooks -------------------------------------------------
    def wrap_optimizer(self, tx):
        return tx

    def merge(self, center, local):
        """(center, local) -> (center', local'), called once per window with
        the worker axis bound.  The BLOCKED merge — kept verbatim so
        ``DK_COMM_OVERLAP=0`` compiles byte-identical window bodies to
        every round before the overlap existed."""
        raise NotImplementedError

    # --- overlap decomposition (DK_COMM_OVERLAP) ------------------------
    # The blocked ``merge`` is algebraically  commit -> psum -> apply ->
    # absorb  with the apply consumed IMMEDIATELY.  The overlapped path
    # splits those so the psum's result has no consumer until the NEXT
    # window boundary (the one-window-stale center — exactly the paper's
    # async commit model, where a worker's commit is "in flight" while
    # it already trains on): XLA is then free to run the collective
    # concurrently with window k+1's local steps, and the host-level
    # ``AsyncMerge`` flush at the end of train() plays the same trick
    # for the final pending commit.
    def commit(self, center, local):
        """The worker's window commit delta (pre-psum), computed against
        the center this window's local steps started from."""
        raise NotImplementedError(
            f"{type(self).__name__} defines no commit/absorb overlap "
            "decomposition — DK_COMM_OVERLAP needs both (or run this "
            "trainer with the blocked merge: comm_overlap=False)")

    def absorb(self, center, local, delta):
        """The worker-local post-commit update: ``center`` is the
        (one-window-stale) merged center the worker syncs to, ``delta``
        its OWN just-committed delta (pre-psum)."""
        raise NotImplementedError(
            f"{type(self).__name__} defines no commit/absorb overlap "
            "decomposition — DK_COMM_OVERLAP needs both (or run this "
            "trainer with the blocked merge: comm_overlap=False)")

    def _ckpt_cadence_windows(self, wpe):
        """Save cadence in WINDOW units — the single source both the
        chunk plan and the save decision use, so dispatch boundaries and
        checkpoint writes can never desynchronize."""
        if self.checkpoint_every_windows:
            return self.checkpoint_every_windows
        if self.checkpoint_every:
            return self.checkpoint_every * wpe
        return None

    # --- shared training loop ------------------------------------------
    def train(self, dataset, shuffle=False):
        """The whole run is one flat ``lax.scan`` over communication
        windows on device-resident shard tensors (one H2D transfer).
        With no hooks requested all ``num_epoch * windows_per_epoch``
        windows are ONE dispatch; ``checkpoint_every``/``callbacks``
        chunk at epoch boundaries and ``checkpoint_every_windows`` at
        WINDOW boundaries — mid-epoch — with all worker state (local
        replicas, optimizer state, the in-epoch rng) carried across
        chunks, so a preemption loses at most one cadence of windows.
        The reference analogue: a long-lived worker's state persists
        across its entire partition pass (workers.py:~150).

        Metrics cadence: per-epoch metrics/callbacks fire at dispatch
        boundaries whose window count is an exact epoch multiple.  With
        ``checkpoint_every_windows`` not dividing windows-per-epoch and
        no callbacks registered, several epochs can collapse into one
        metrics entry (nothing is lost — accumulators carry across and
        the final emit always fires); register any callback to force
        true epoch-boundary chunking."""
        model, loss_fn, tx = self._resolve()
        tx = self.wrap_optimizer(tx)
        # overlapped window collectives: resolved per call so a
        # launcher-exported DK_COMM_OVERLAP wins regardless of when the
        # trainer was constructed (the knobs-registry contract)
        overlap = self._overlap = bool(
            self.comm_overlap if self.comm_overlap is not None
            else knobs.get("DK_COMM_OVERLAP"))
        if shuffle:
            dataset = dataset.shuffle(seed=self.seed)
        xs, ys = self._shards(dataset)  # (workers, steps, batch, ...)

        W = min(self.communication_window, xs.shape[1])
        wpe = xs.shape[1] // W  # windows per epoch
        # Whole windows only, cut per epoch (remainder dropped every epoch,
        # like the reference's fixed mini-batching) — warn so silent data
        # loss / window shrinkage is visible.
        if W < self.communication_window:
            warnings.warn(
                f"communication_window={self.communication_window} > "
                f"{xs.shape[1]} steps per worker per epoch; effective "
                f"window shrunk to {W}", stacklevel=2)
        dropped = xs.shape[1] - wpe * W
        if dropped:
            warnings.warn(
                f"dropping {dropped} trailing step(s) per worker per epoch "
                f"(not a whole communication window)", stacklevel=2)
        # leading axis is LOCAL workers (== num_workers single-process;
        # this host's slice when multi-host, see base._shards)
        xs = xs[:, :wpe * W].reshape(xs.shape[0], wpe, W, *xs.shape[2:])
        ys = ys[:, :wpe * W].reshape(ys.shape[0], wpe, W, *ys.shape[2:])
        total_w = self.num_epoch * wpe

        mesh = self.mesh
        merge = self.merge
        step, opt_init = self._make_step(model, loss_fn, tx)

        def build_chunk(K, streamed=False):
            """K-window dispatch.  Resident mode: the whole (wpe, W, ...)
            epoch tensor is an argument and windows are selected by
            dynamic index modulo wpe (data reused across epochs inside
            one dispatch).  Streaming mode: ONLY the chunk's (K, W, ...)
            slice arrives and the scan consumes it directly — identical
            window algebra, so the two paths are bit-equal on the same
            data (asserted in tests/test_streaming_feed.py).

            Under ``overlap`` (DK_COMM_OVERLAP) the carry grows a
            replicated ``pending`` leaf set — the previous window's
            psum'd commit, applied ONE window late.  The psum issued at
            boundary k has no consumer until boundary k+1, so it
            carries no data dependency into window k+1's local steps
            and the compiler overlaps the collective with them; the
            algebra is the paper's async model (every worker trains on
            a center missing exactly the cluster's last window of
            commits).  ``pending`` rides the scan carry, the chunk
            carry AND the checkpoint state, so the staleness semantics
            are chunk-plan-invariant (gates.py --speed-only pins a
            per-window-dispatched run bit-equal to the fused one)."""
            def window(carry, g, xw, yw, widx, key):
                if overlap:
                    center, pending, local, opt_state, rng = carry
                else:
                    center, local, opt_state, rng = carry
                e, wi = g // wpe, g % wpe
                # the epoch's rng stream starts at its first window
                # and is CARRIED through the rest (and across chunk
                # boundaries via the checkpointed rng), so a
                # mid-epoch resume replays the identical stream
                fresh = tree_pvary(jax.random.fold_in(
                    jax.random.fold_in(key, e), widx))
                rng = jnp.where(wi == 0, fresh, rng)
                (local, opt_state, rng), losses = jax.lax.scan(
                    step, (local, opt_state, rng), (xw, yw))
                if overlap:
                    # deferred merge: commit this window's delta, apply
                    # the PREVIOUS window's summed commit, hand the new
                    # psum to the next boundary.  Integer leaves (Keras
                    # seed-generator counters) are RNG state, not
                    # weights: exempt everywhere, like the blocked path.
                    delta = self.commit(center, local)
                    center = tree_merge_floats(
                        tree_add(center, pending), center)
                    local = tree_merge_floats(
                        self.absorb(center, local, delta), local)
                    local = tree_pvary(local)
                    pending = tree_merge_floats(tree_psum(delta),
                                                pending)
                    return (center, pending, local, opt_state,
                            rng), losses
                new_center, new_local = merge(center, local)
                # integer leaves (Keras seed-generator counters) are
                # RNG state, not weights: exempt from merge algebra
                center = tree_merge_floats(new_center, center)
                local = tree_merge_floats(new_local, local)
                # merges that reset local to the (replicated) center
                # must hand back a varying-typed local for next window
                local = tree_pvary(local)
                return (center, local, opt_state, rng), losses

            def body(*args):
                if overlap:
                    (center, pending, local, opt_state, rng, xs, ys,
                     key, g0) = args
                else:
                    center, local, opt_state, rng, xs, ys, key, g0 = args
                xs, ys = xs[0], ys[0]  # (wpe | K, W, batch, ...)
                widx = jax.lax.axis_index(WORKER_AXIS)
                # carry state arrives stacked (1, ...) per worker shard
                local = jax.tree.map(lambda t: t[0], local)
                opt_state = jax.tree.map(lambda t: t[0], opt_state)
                rng = rng[0]

                carry = ((center, pending, local, opt_state, rng)
                         if overlap else (center, local, opt_state, rng))
                if streamed:
                    carry, losses = jax.lax.scan(
                        lambda c, inp: window(c, *inp, widx, key), carry,
                        (jnp.arange(K) + g0, xs, ys))
                else:
                    def indexed(c, g):
                        wi = g % wpe
                        xw = jax.lax.dynamic_index_in_dim(
                            xs, wi, 0, keepdims=False)
                        yw = jax.lax.dynamic_index_in_dim(
                            ys, wi, 0, keepdims=False)
                        return window(c, g, xw, yw, widx, key)

                    carry, losses = jax.lax.scan(
                        indexed, carry, jnp.arange(K) + g0)
                stack = lambda t: t[None]  # noqa: E731
                if overlap:
                    center, pending, local, opt_state, rng = carry
                    return (center, pending, jax.tree.map(stack, local),
                            jax.tree.map(stack, opt_state), rng[None],
                            losses[None])
                center, local, opt_state, rng = carry
                return (center, jax.tree.map(stack, local),
                        jax.tree.map(stack, opt_state), rng[None],
                        losses[None])  # losses: (1, K, W)

            rep = (P(),) if overlap else ()  # pending: replicated
            return jax.jit(shard_map(
                body, mesh=mesh,
                in_specs=(P(), *rep, P(WORKER_AXIS), P(WORKER_AXIS),
                          P(WORKER_AXIS), P(WORKER_AXIS), P(WORKER_AXIS),
                          P(), P()),
                out_specs=(P(), *rep, P(WORKER_AXIS), P(WORKER_AXIS),
                           P(WORKER_AXIS), P(WORKER_AXIS)),
            ))

        # initial carry (stacked per worker on the leading axis)
        center = model.params
        local = self._stack_workers(center)
        opt_state = self._stack_workers(opt_init(center))
        rng = self._stack_workers(jnp.zeros((2,), jnp.uint32))
        # the overlap carry: the previous window's psum'd commit, not
        # yet applied (zeros before the first boundary — nothing is in
        # flight at window 0)
        pending = tree_zeros_like(center) if overlap else None
        template = {"center": center, "local": local,
                    "opt_state": opt_state, "rng": rng}
        if overlap:
            template["pending"] = pending
        start_w, restored = self._maybe_resume(
            template,
            incompatible_hint=(
                "if this checkpoint predates window-granular training "
                "state (round 2: no 'rng' leaf, step counted epochs not "
                "windows), restart training or point checkpoint_dir at "
                "a fresh directory; if it carries a 'pending' leaf the "
                "run was overlapped — resume with DK_COMM_OVERLAP=1"))
        if restored is not None:
            if "rng" not in restored:
                raise ValueError(
                    "checkpoint predates window-granular training state "
                    "(no 'rng' leaf; its step counts epochs, not "
                    "windows) — restart training or point "
                    "checkpoint_dir at a fresh directory")
            if "pending" in restored and not overlap:
                raise ValueError(
                    "checkpoint carries an in-flight overlapped window "
                    "commit (a 'pending' leaf: it was written under "
                    "DK_COMM_OVERLAP=1) — resume with DK_COMM_OVERLAP=1 "
                    "so the commit lands, or restart from a fresh "
                    "checkpoint_dir")
            center = restored["center"]
            local = restored["local"]
            opt_state = restored["opt_state"]
            rng = restored["rng"]
            if overlap:
                # a blocked-era checkpoint resumes into an overlapped
                # run with nothing in flight — semantically the run's
                # first boundary simply applies a zero commit
                pending = restored.get("pending", pending)

        key = jax.random.PRNGKey(self.seed)

        def dispatch(i, K, windows_done, data):
            nonlocal center, pending, local, opt_state, rng
            if self._streamed:
                fn = self._compiled(lambda: build_chunk(K, streamed=True),
                                    extra_key=("stream", K, wpe))
            else:
                fn = self._compiled(lambda: build_chunk(K),
                                    extra_key=(K, wpe))
            if overlap:
                center, pending, local, opt_state, rng, losses = fn(
                    center, pending, local, opt_state, rng, *data, key,
                    jnp.int32(windows_done))
            else:
                center, local, opt_state, rng, losses = fn(
                    center, local, opt_state, rng, *data, key,
                    jnp.int32(windows_done))
            return losses

        def state_fn():
            state = {"center": center, "local": local,
                     "opt_state": opt_state, "rng": rng}
            if overlap:
                state["pending"] = pending
            return state

        carry_leaves = ((center, pending, local, opt_state, rng)
                        if overlap else (center, local, opt_state, rng))
        # history entries are (workers, K, W) per chunk; run_chunked
        # reshapes whole-epoch runs to the round-2 get_history contract
        # (workers, epochs, windows, W) — a run RESUMED mid-epoch stays
        # (workers, windows, W)
        history = run_chunked(
            self, xs, ys, start=start_w, total=total_w, per_epoch=wpe,
            stream_units=self.stream_chunk_windows,
            cadence=self._ckpt_cadence_windows(wpe),
            samples_per_unit=self.num_workers * W * self.batch_size,
            dispatch=dispatch, sync_ref=lambda: center,
            state_fn=state_fn,
            carry_leaves=carry_leaves,
            fetch_global=comm.fetch_global)
        if overlap:
            # flush the LAST window's in-flight commit so the returned
            # center includes every worker's final delta — the host-
            # level half of the double buffer (AsyncMerge: async submit,
            # deferred block_until_ready; here the wait is immediate
            # because training is over, but the enqueue/blocking walls
            # still land in the comm_overlap/comm_blocked split)
            flush = AsyncMerge(
                lambda c, p: tree_merge_floats(tree_add(c, p), c))
            # dklint: ignore[unbounded-wait] block_until_ready on the
            # just-dispatched flush (an XLA program, which terminates),
            # not a thread/event wait
            center = flush.submit(center, pending).wait()
        return self._finalize(center, history)


class DOWNPOUR(AsynchronousDistributedTrainer):
    """trainers.py:~470 / workers.py:~230."""

    def __init__(self, keras_model, communication_window=5, **kw):
        super().__init__(keras_model,
                         communication_window=communication_window, **kw)

    def merge(self, center, local):
        delta = tree_sub(local, center)
        center = tree_add(center, tree_psum(delta))
        return center, center

    def commit(self, center, local):
        return tree_sub(local, center)

    def absorb(self, center, local, delta):
        # DOWNPOUR pulls the center after its commit; overlapped, the
        # pulled center is one window stale (the commit is in flight)
        return center


class ADAG(AsynchronousDistributedTrainer):
    """Accumulated-gradient normalisation (trainers.py:~530,
    workers.py:~300): the window's accumulated delta is divided by the
    window length before the commit."""

    def __init__(self, keras_model, communication_window=12, **kw):
        super().__init__(keras_model,
                         communication_window=communication_window, **kw)

    def merge(self, center, local):
        delta = tree_scale(tree_sub(local, center),
                           1.0 / self.communication_window)
        center = tree_add(center, tree_psum(delta))
        return center, center

    def commit(self, center, local):
        return tree_scale(tree_sub(local, center),
                          1.0 / self.communication_window)

    def absorb(self, center, local, delta):
        return center


class AEASGD(AsynchronousDistributedTrainer):
    """Asynchronous elastic averaging SGD (trainers.py:~590,
    workers.py:~370). alpha = learning_rate * rho."""

    def __init__(self, keras_model, communication_window=32, rho=5.0,
                 learning_rate=0.1, **kw):
        super().__init__(keras_model,
                         communication_window=communication_window, **kw)
        self.rho = float(rho)
        self.learning_rate = float(learning_rate)

    def _cache_extras(self):
        return super()._cache_extras() + (self.rho, self.learning_rate)

    def merge(self, center, local):
        alpha = self.learning_rate * self.rho
        elastic = tree_scale(tree_sub(local, center), alpha)
        local = tree_sub(local, elastic)
        center = tree_add(center, tree_psum(elastic))
        return center, local

    def commit(self, center, local):
        alpha = self.learning_rate * self.rho
        return tree_scale(tree_sub(local, center), alpha)

    def absorb(self, center, local, delta):
        # the elastic force moves the worker toward the center it
        # MEASURED against (one window stale under overlap); the
        # worker keeps its own replica, unlike the pull-based family
        return tree_sub(local, delta)


class EAMSGD(AEASGD):
    """AEASGD + Nesterov momentum on the local update (trainers.py:~650,
    workers.py:~450): the worker optimizer's updates go through a Nesterov
    momentum trace."""

    def __init__(self, keras_model, momentum=0.9, **kw):
        super().__init__(keras_model, **kw)
        self.momentum = float(momentum)

    def _cache_extras(self):
        return super()._cache_extras() + (self.momentum,)

    def wrap_optimizer(self, tx):
        return optax.chain(
            tx, optax.trace(decay=self.momentum, nesterov=True))
