"""Windowed-commit machinery + the async optimizer family.

The reference's asynchronous parameter-server optimizers (workers.py:~230-600
+ parameter_servers.py:~200-330) share one skeleton: train locally for
``communication_window`` batches, then exchange an update with the center
variable.  On lockstep SPMD hardware the exchange compiles to one collective:

- DOWNPOUR  (workers.py:~230): commit the accumulated weight delta; pull.
  -> center += psum(local - center); local = center.
- ADAG      (workers.py:~300): DOWNPOUR with the delta normalised by the
  window length before commit.
  -> center += psum((local - center) / W).
- AEASGD    (workers.py:~370): elastic averaging; every tau steps the worker
  moves toward the center by E = alpha*(theta_i - center) and commits E.
  -> E_i = alpha*(local - center); local -= E_i; center += psum(E_i).
- EAMSGD    (workers.py:~450): AEASGD + Nesterov momentum on the local
  update (handled by wrapping the worker optimizer with optax.trace).

Mechanism-vs-behavior note (SURVEY.md §7 "hard parts"): in the reference
these commits are *asynchronous* and interleave arbitrarily; under SPMD all
workers commit at the same step, which reproduces the communication pattern
and the update algebra but with zero staleness.  DynSGD, whose whole point is
staleness, gets a genuinely staggered emulation in ``dynsgd.py``.

Everything here runs inside one jitted ``shard_map``: outer ``lax.scan`` over
windows, inner ``lax.scan`` over the window's batches, one pytree collective
per window riding ICI.
"""

from __future__ import annotations

import warnings

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import PartitionSpec as P

from dist_keras_tpu.parallel.collectives import tree_psum, tree_pvary
from dist_keras_tpu.parallel.mesh import WORKER_AXIS
from dist_keras_tpu.comm import backend as comm
from dist_keras_tpu.trainers.base import DistributedTrainer
from dist_keras_tpu.trainers.step import make_model_step
from dist_keras_tpu.utils.pytree import (
    tree_add,
    tree_merge_floats,
    tree_scale,
    tree_sub,
)
from dist_keras_tpu.utils.sync import drain

try:
    from jax import shard_map
except ImportError:  # older jax
    from jax.experimental.shard_map import shard_map


class AsynchronousDistributedTrainer(DistributedTrainer):
    """Base of the windowed family (trainers.py:~420).

    ``parallelism_factor`` (trainers.py:~310) is accepted for parity but is
    a deliberate no-op: the reference oversubscribes Spark partitions so a
    straggling executor can be load-balanced, a failure mode lockstep SPMD
    does not have — every worker is one mesh slot and ``worker_shards``
    already deals all rows evenly across workers.
    """

    def __init__(self, keras_model, num_workers=2, communication_window=5,
                 parallelism_factor=1, checkpoint_every_windows=None,
                 stream_chunk_windows=None, max_resident_bytes=None, **kw):
        super().__init__(keras_model, num_workers=num_workers, **kw)
        self.communication_window = int(communication_window)
        self.parallelism_factor = int(parallelism_factor)
        # window-granular checkpoint cadence: a preemption then loses at
        # most ``checkpoint_every_windows`` communication windows, not a
        # whole epoch (the reference's big-DataFrame case,
        # trainers.py:~360, can make one epoch arbitrarily long)
        self.checkpoint_every_windows = (
            int(checkpoint_every_windows) if checkpoint_every_windows
            else None)
        if self.checkpoint_every_windows and not self.checkpoint_dir:
            raise ValueError(
                "checkpoint_every_windows requires checkpoint_dir")
        # ---- streaming input pipeline (the reference's partition-iterator
        # property, workers.py:~60: an epoch never has to fit on-device).
        # stream_chunk_windows=C streams the data C windows per dispatch
        # through a double-buffered ChunkFeed (<= 2 chunks ever resident);
        # max_resident_bytes=B auto-enables streaming whenever the epoch
        # tensor would exceed B bytes of device memory, sizing C so two
        # in-flight chunks fit inside B.  Default (both None) keeps the
        # round-1 whole-run-resident fast path.
        self.stream_chunk_windows = (int(stream_chunk_windows)
                                     if stream_chunk_windows else None)
        if self.stream_chunk_windows is not None \
                and self.stream_chunk_windows < 1:
            raise ValueError(
                f"stream_chunk_windows={stream_chunk_windows} must be >= 1")
        self.max_resident_bytes = (int(max_resident_bytes)
                                   if max_resident_bytes else None)
        if self.max_resident_bytes is not None and self.max_resident_bytes < 1:
            raise ValueError(
                f"max_resident_bytes={max_resident_bytes} must be >= 1")
        self._streamed = False  # set by train(); introspectable by tests

    def _cache_extras(self):
        # the per-chunk epoch count is appended via _compiled(extra_key=)
        return super()._cache_extras() + (self.communication_window,)

    # --- strategy hooks -------------------------------------------------
    def wrap_optimizer(self, tx):
        return tx

    def merge(self, center, local):
        """(center, local) -> (center', local'), called once per window with
        the worker axis bound."""
        raise NotImplementedError

    def _window_chunk_plan(self, start_w, total_w, wpe, data_chunk=None):
        """Chunk sizes in WINDOW units: the dispatch breaks at the union
        of epoch boundaries (when callbacks need on_epoch_end at real
        epoch ends) and checkpoint-cadence boundaries (counted from the
        resume point, possibly mid-epoch).  No hooks = one dispatch (the
        round-1 perf path).

        ``data_chunk=C`` (streaming mode) additionally cuts at every
        epoch boundary and every C-th window *within* each epoch
        (aligned to the epoch start, NOT the resume point, so a resumed
        run reuses the identical chunk grid): each dispatch's data is
        then one contiguous epoch-relative slice of <= C windows, the
        unit the ChunkFeed transfers."""
        remaining = total_w - start_w
        if remaining <= 0:
            return []
        bounds = {total_w}
        if self.callbacks:
            first = (start_w // wpe + 1) * wpe
            bounds |= set(range(first, total_w, wpe))
        cadence = self._ckpt_cadence_windows(wpe)
        if cadence:
            bounds |= set(range(start_w + cadence, total_w, cadence))
        if data_chunk:
            # k=0 of the grid below lands on every epoch boundary too
            first_epoch = start_w // wpe
            for e in range(first_epoch, -(-total_w // wpe)):
                bounds |= {e * wpe + k for k in range(0, wpe, data_chunk)
                           if start_w < e * wpe + k}
        cuts = sorted(b for b in bounds if start_w < b <= total_w)
        out, prev = [], start_w
        for b in cuts:
            out.append(b - prev)
            prev = b
        return out

    def _ckpt_cadence_windows(self, wpe):
        """Save cadence in WINDOW units — the single source both the
        chunk plan and the save decision use, so dispatch boundaries and
        checkpoint writes can never desynchronize."""
        if self.checkpoint_every_windows:
            return self.checkpoint_every_windows
        if self.checkpoint_every:
            return self.checkpoint_every * wpe
        return None

    def _ckpt_due_windows(self, windows_done, total_w):
        """True when a save is owed at this window count — the dispatch
        loop's sync-boundary predicate (a due save forces the pipeline
        flush that makes the state fetchable)."""
        if self._checkpointer_or_none() is None:
            return False
        last = getattr(self, "_last_ckpt_epoch", 0)  # in window units here
        cadence = (self._ckpt_cadence_windows(self._wpe)
                   or self.num_epoch * self._wpe)
        return windows_done - last >= cadence or windows_done >= total_w

    def _maybe_checkpoint_windows(self, windows_done, total_w, state_fn):
        if self._ckpt_due_windows(windows_done, total_w):
            self._checkpointer_or_none().save(windows_done, state_fn())
            self._last_ckpt_epoch = windows_done

    # --- shared training loop ------------------------------------------
    def train(self, dataset, shuffle=False):
        """The whole run is one flat ``lax.scan`` over communication
        windows on device-resident shard tensors (one H2D transfer).
        With no hooks requested all ``num_epoch * windows_per_epoch``
        windows are ONE dispatch; ``checkpoint_every``/``callbacks``
        chunk at epoch boundaries and ``checkpoint_every_windows`` at
        WINDOW boundaries — mid-epoch — with all worker state (local
        replicas, optimizer state, the in-epoch rng) carried across
        chunks, so a preemption loses at most one cadence of windows.
        The reference analogue: a long-lived worker's state persists
        across its entire partition pass (workers.py:~150)."""
        import time as _time

        model, loss_fn, tx = self._resolve()
        tx = self.wrap_optimizer(tx)
        if shuffle:
            dataset = dataset.shuffle(seed=self.seed)
        xs, ys = self._shards(dataset)  # (workers, steps, batch, ...)

        W = min(self.communication_window, xs.shape[1])
        wpe = xs.shape[1] // W  # windows per epoch
        # Whole windows only, cut per epoch (remainder dropped every epoch,
        # like the reference's fixed mini-batching) — warn so silent data
        # loss / window shrinkage is visible.
        if W < self.communication_window:
            warnings.warn(
                f"communication_window={self.communication_window} > "
                f"{xs.shape[1]} steps per worker per epoch; effective "
                f"window shrunk to {W}", stacklevel=2)
        dropped = xs.shape[1] - wpe * W
        if dropped:
            warnings.warn(
                f"dropping {dropped} trailing step(s) per worker per epoch "
                f"(not a whole communication window)", stacklevel=2)
        # leading axis is LOCAL workers (== num_workers single-process;
        # this host's slice when multi-host, see base._shards)
        xs = xs[:, :wpe * W].reshape(xs.shape[0], wpe, W, *xs.shape[2:])
        ys = ys[:, :wpe * W].reshape(ys.shape[0], wpe, W, *ys.shape[2:])
        self._wpe = wpe
        total_w = self.num_epoch * wpe

        mesh = self.mesh
        merge = self.merge
        step, opt_init = make_model_step(
            model, loss_fn, tx, self.compute_dtype)

        def build_chunk(K, streamed=False):
            """K-window dispatch.  Resident mode: the whole (wpe, W, ...)
            epoch tensor is an argument and windows are selected by
            dynamic index modulo wpe (data reused across epochs inside
            one dispatch).  Streaming mode: ONLY the chunk's (K, W, ...)
            slice arrives and the scan consumes it directly — identical
            window algebra, so the two paths are bit-equal on the same
            data (asserted in tests/test_streaming_feed.py)."""
            def body(center, local, opt_state, rng, xs, ys, key, g0):
                xs, ys = xs[0], ys[0]  # (wpe | K, W, batch, ...)
                widx = jax.lax.axis_index(WORKER_AXIS)
                # carry state arrives stacked (1, ...) per worker shard
                local = jax.tree.map(lambda t: t[0], local)
                opt_state = jax.tree.map(lambda t: t[0], opt_state)
                rng = rng[0]

                def window(carry, g, xw, yw):
                    center, local, opt_state, rng = carry
                    e, wi = g // wpe, g % wpe
                    # the epoch's rng stream starts at its first window
                    # and is CARRIED through the rest (and across chunk
                    # boundaries via the checkpointed rng), so a
                    # mid-epoch resume replays the identical stream
                    fresh = tree_pvary(jax.random.fold_in(
                        jax.random.fold_in(key, e), widx))
                    rng = jnp.where(wi == 0, fresh, rng)
                    (local, opt_state, rng), losses = jax.lax.scan(
                        step, (local, opt_state, rng), (xw, yw))
                    new_center, new_local = merge(center, local)
                    # integer leaves (Keras seed-generator counters) are
                    # RNG state, not weights: exempt from merge algebra
                    center = tree_merge_floats(new_center, center)
                    local = tree_merge_floats(new_local, local)
                    # merges that reset local to the (replicated) center
                    # must hand back a varying-typed local for next window
                    local = tree_pvary(local)
                    return (center, local, opt_state, rng), losses

                carry = (center, local, opt_state, rng)
                if streamed:
                    carry, losses = jax.lax.scan(
                        lambda c, inp: window(c, *inp), carry,
                        (jnp.arange(K) + g0, xs, ys))
                else:
                    def indexed(c, g):
                        wi = g % wpe
                        xw = jax.lax.dynamic_index_in_dim(
                            xs, wi, 0, keepdims=False)
                        yw = jax.lax.dynamic_index_in_dim(
                            ys, wi, 0, keepdims=False)
                        return window(c, g, xw, yw)

                    carry, losses = jax.lax.scan(
                        indexed, carry, jnp.arange(K) + g0)
                center, local, opt_state, rng = carry
                stack = lambda t: t[None]  # noqa: E731
                return (center, jax.tree.map(stack, local),
                        jax.tree.map(stack, opt_state), rng[None],
                        losses[None])  # losses: (1, K, W)

            return jax.jit(shard_map(
                body, mesh=mesh,
                in_specs=(P(), P(WORKER_AXIS), P(WORKER_AXIS),
                          P(WORKER_AXIS), P(WORKER_AXIS), P(WORKER_AXIS),
                          P(), P()),
                out_specs=(P(), P(WORKER_AXIS), P(WORKER_AXIS),
                           P(WORKER_AXIS), P(WORKER_AXIS)),
            ))

        # initial carry (stacked per worker on the leading axis)
        center = model.params
        local = self._stack_workers(center)
        opt_state = self._stack_workers(opt_init(center))
        rng = self._stack_workers(jnp.zeros((2,), jnp.uint32))
        template = {"center": center, "local": local,
                    "opt_state": opt_state, "rng": rng}
        start_w, restored = self._maybe_resume(template)
        if restored is not None:
            if "rng" not in restored:
                raise ValueError(
                    "checkpoint predates window-granular training state "
                    "(no 'rng' leaf; its step counts epochs, not "
                    "windows) — restart training or point "
                    "checkpoint_dir at a fresh directory")
            center = restored["center"]
            local = restored["local"]
            opt_state = restored["opt_state"]
            rng = restored["rng"]

        # ---- streaming decision: per-DEVICE residency is the HBM
        # constraint (each device holds its own worker's epoch shard)
        stream_C = self.stream_chunk_windows
        per_device_epoch_bytes = (xs.nbytes + ys.nbytes) // max(
            1, xs.shape[0])
        if (stream_C is None and self.max_resident_bytes
                and per_device_epoch_bytes > self.max_resident_bytes):
            per_window = max(1, per_device_epoch_bytes // wpe)
            # two chunks in flight (executing + prefetched) must fit
            stream_C = max(1, self.max_resident_bytes // (2 * per_window))
        if stream_C:
            stream_C = max(1, min(int(stream_C), wpe))
        self._streamed = bool(stream_C)

        plan = self._window_chunk_plan(start_w, total_w, wpe,
                                       data_chunk=stream_C)
        if stream_C:
            from dist_keras_tpu.data.feed import ChunkFeed

            w, spans = start_w, []
            for K in plan:
                spans.append((w % wpe, K))  # epoch-relative slice
                w += K
            feed = ChunkFeed(spans, self._put_worker_chunk, xs, ys)
            self._last_feed = feed  # test introspection
            # chunk 0's transfer and the carry state land OUTSIDE the
            # clock, like the resident path's one-shot H2D; chunks 1..
            # transfer inside it, overlapped under the running dispatch
            # (plan may be empty: resume of an already-finished run)
            first = feed.get(0) if plan else ()
            drain(center, local, opt_state, rng, *first)
        else:
            xs = self._to_device(xs)
            ys = self._to_device(ys)
            # data AND carry-state distribution completes OUTSIDE the
            # clock (the stacked local/opt_state device_puts are async
            # too)
            drain(xs, ys, center, local, opt_state, rng)
        key = jax.random.PRNGKey(self.seed)
        samples_per_window = self.num_workers * W * self.batch_size

        self.record_training_start()
        all_losses = []
        windows_done = start_w
        # metrics/callbacks fire at EPOCH boundaries only (integer epoch
        # numbers, like every other trainer); chunks ending mid-epoch
        # accumulate into the next boundary's emit
        acc_losses, acc_dt, acc_samples = [], 0.0, 0
        # Streamed chunks PIPELINE: losses of chunk i are fetched only
        # when (a) a second chunk is already in flight (depth-2 bound so
        # the feed's two-buffer residency guarantee holds) or (b) a sync
        # boundary (epoch end / checkpoint due / final chunk) arrives.
        # Non-boundary chunks thus cost no tunnel round trip — the sync
        # cadence is per-epoch, not per-chunk.  Resident-mode chunks end
        # only at boundaries, so its behavior is exactly the round-3 loop.
        pending = []  # [(chunk_idx, device losses)]

        def _retire_one():
            j, lj = pending.pop(0)
            arr = np.asarray(comm.fetch_global(lj))  # blocks until j done
            if stream_C:
                feed.release(j)
            all_losses.append(arr)
            acc_losses.append(arr)

        t_mark = _time.time()
        try:
            for i, K in enumerate(plan):
                if stream_C:
                    fn = self._compiled(
                        lambda: build_chunk(K, streamed=True),
                        extra_key=("stream", K, wpe))
                    data = feed.get(i)
                else:
                    fn = self._compiled(lambda: build_chunk(K),
                                        extra_key=(K, wpe))
                    data = (xs, ys)
                center, local, opt_state, rng, losses = fn(
                    center, local, opt_state, rng, *data, key,
                    jnp.int32(windows_done))
                pending.append((i, losses))
                windows_done += K
                if stream_C:
                    # retire the previous chunk BEFORE prefetching the
                    # next: at most two chunks' data is ever
                    # device-resident, and the i+1 transfer still
                    # overlaps chunk i's execution
                    while len(pending) > 1:
                        _retire_one()
                    feed.prefetch(i + 1)
                boundary = (windows_done % wpe == 0
                            or i == len(plan) - 1
                            or self._ckpt_due_windows(windows_done,
                                                      total_w))
                acc_samples += samples_per_window * K
                if not boundary:
                    continue
                drain(center)  # block_until_ready lies via the tunnel
                acc_dt += _time.time() - t_mark
                # host-side work below (loss fetches, checkpoint I/O,
                # user callbacks) stays OUTSIDE the clock, as round 3
                while pending:
                    _retire_one()
                # save BEFORE user callbacks run: a callback that dies
                # (preemption simulation) must not lose the chunk
                self._maybe_checkpoint_windows(
                    windows_done, total_w,
                    lambda: {"center": center, "local": local,
                             "opt_state": opt_state, "rng": rng})
                if windows_done % wpe == 0:
                    self._emit_epoch_end(windows_done // wpe,
                                         np.concatenate(acc_losses,
                                                        axis=1),
                                         acc_dt, acc_samples)
                    acc_losses, acc_dt, acc_samples = [], 0.0, 0
                t_mark = _time.time()
        finally:
            # exception-safe (a raising user callback must not leave the
            # feed pinning the host epoch tensors for the trainer's life)
            if stream_C:
                feed.close()  # keeps stats, frees data references
        self.record_training_end()

        if all_losses:
            flat = np.concatenate(all_losses, axis=1)  # (workers, tw, W)
            # (workers, epochs, windows, W) for runs that executed whole
            # epochs — the standard case, and the round-2 get_history
            # contract.  A run RESUMED mid-epoch executed a partial first
            # epoch, so its own history stays (workers, windows, W); see
            # Trainer.get_history.
            if flat.shape[1] % wpe == 0:
                flat = flat.reshape(flat.shape[0], -1, wpe, W)
            history = flat.tolist()
        else:
            history = []
        return self._finalize(center, history)


class DOWNPOUR(AsynchronousDistributedTrainer):
    """trainers.py:~470 / workers.py:~230."""

    def __init__(self, keras_model, communication_window=5, **kw):
        super().__init__(keras_model,
                         communication_window=communication_window, **kw)

    def merge(self, center, local):
        delta = tree_sub(local, center)
        center = tree_add(center, tree_psum(delta))
        return center, center


class ADAG(AsynchronousDistributedTrainer):
    """Accumulated-gradient normalisation (trainers.py:~530,
    workers.py:~300): the window's accumulated delta is divided by the
    window length before the commit."""

    def __init__(self, keras_model, communication_window=12, **kw):
        super().__init__(keras_model,
                         communication_window=communication_window, **kw)

    def merge(self, center, local):
        delta = tree_scale(tree_sub(local, center),
                           1.0 / self.communication_window)
        center = tree_add(center, tree_psum(delta))
        return center, center


class AEASGD(AsynchronousDistributedTrainer):
    """Asynchronous elastic averaging SGD (trainers.py:~590,
    workers.py:~370). alpha = learning_rate * rho."""

    def __init__(self, keras_model, communication_window=32, rho=5.0,
                 learning_rate=0.1, **kw):
        super().__init__(keras_model,
                         communication_window=communication_window, **kw)
        self.rho = float(rho)
        self.learning_rate = float(learning_rate)

    def _cache_extras(self):
        return super()._cache_extras() + (self.rho, self.learning_rate)

    def merge(self, center, local):
        alpha = self.learning_rate * self.rho
        elastic = tree_scale(tree_sub(local, center), alpha)
        local = tree_sub(local, elastic)
        center = tree_add(center, tree_psum(elastic))
        return center, local


class EAMSGD(AEASGD):
    """AEASGD + Nesterov momentum on the local update (trainers.py:~650,
    workers.py:~450): the worker optimizer's updates go through a Nesterov
    momentum trace."""

    def __init__(self, keras_model, momentum=0.9, **kw):
        super().__init__(keras_model, **kw)
        self.momentum = float(momentum)

    def _cache_extras(self):
        return super()._cache_extras() + (self.momentum,)

    def wrap_optimizer(self, tx):
        return optax.chain(
            tx, optax.trace(decay=self.momentum, nesterov=True))
