"""SingleTrainer — parity with ``distkeras/trainers.py:~100``.

Reference path: coalesce the DataFrame to one partition and run a plain
epochs x train_on_batch loop in one Spark task (SURVEY.md §3.1).  TPU-native:
the run is a flat ``lax.scan`` over GLOBAL steps under ``jit`` — one
dispatch when no hooks are requested — driven through the shared
``ChunkRunner`` (``trainers/chunking.py``), which as of round 4 gives the
single-worker path the same streaming feed as the distributed family:
``stream_chunk_steps=C`` (or ``max_resident_bytes=B``) feeds C steps per
dispatch through the double-buffered ChunkFeed, so a dataset larger than
device memory trains at resident-speed parity; ``data_dtype=None`` ships
uint8 batches cast on-device.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from dist_keras_tpu.trainers.base import Trainer


class SingleTrainer(Trainer):
    def __init__(self, keras_model, stream_chunk_steps=None,
                 max_resident_bytes=None, **kw):
        super().__init__(keras_model, **kw)
        from dist_keras_tpu.trainers.chunking import init_streaming

        init_streaming(self, stream_chunk_steps, max_resident_bytes,
                       name="stream_chunk_steps")

    # single-device transfer primitives with the ChunkFeed's
    # (leading-dummy-axis, slice-axis-1) calling convention
    def _put_worker_chunk(self, *arrays):
        return tuple(jax.device_put(np.ascontiguousarray(a[0]))
                     for a in arrays)

    def _to_device(self, x):
        return jnp.asarray(x[0])

    def train(self, dataset, shuffle=False):
        model, loss_fn, tx = self._resolve()
        if shuffle:
            dataset = dataset.shuffle(seed=self.seed)
        xb, yb = dataset.batches(
            self.batch_size, self.features_col, self.label_col,
            dtype=self.data_dtype)
        spb = xb.shape[0]  # steps per epoch
        total_t = self.num_epoch * spb

        step, opt_init = self._make_step(model, loss_fn, tx)
        params = model.params
        opt_state = opt_init(params)
        rng = jax.random.PRNGKey(self.seed)

        # t_units marks the checkpoint's step counter as STEP-granular
        # (round 3 counted epochs); restoring an old checkpoint fails the
        # template match and surfaces the actionable hint below
        template = {"params": params, "opt_state": opt_state, "rng": rng,
                    "t_units": jnp.zeros((), jnp.int32)}
        start_t, restored = self._maybe_resume(
            template,
            incompatible_hint=(
                "if this checkpoint predates step-granular SingleTrainer "
                "state (round 3: no 't_units' leaf, step counted epochs "
                "not steps), restart training or point checkpoint_dir "
                "at a fresh directory"))
        if restored is not None:
            if "t_units" not in restored:
                # pickle-fallback checkpoints restore without a template
                # match, so the orbax-path structure error can't fire
                raise ValueError(
                    "checkpoint predates step-granular SingleTrainer "
                    "state (no 't_units' leaf; its step counts epochs, "
                    "not steps) — restart training or point "
                    "checkpoint_dir at a fresh directory")
            params = restored["params"]
            opt_state = restored["opt_state"]
            rng = jnp.asarray(restored["rng"])

        def build_chunk(T, streamed=False):
            # the rng chain is CONTINUOUS across epochs (the round-1..3
            # behavior: one PRNG stream for the whole run), so a flat
            # step scan needs no per-epoch reseeding
            @jax.jit
            def run(params, opt_state, rng, xs, ys, t0):
                if streamed:
                    (params, opt_state, rng), ls = jax.lax.scan(
                        step, (params, opt_state, rng), (xs, ys))
                else:
                    def indexed(c, t):
                        si = t % spb
                        x = jax.lax.dynamic_index_in_dim(
                            xs, si, 0, keepdims=False)
                        y = jax.lax.dynamic_index_in_dim(
                            ys, si, 0, keepdims=False)
                        return step(c, (x, y))

                    (params, opt_state, rng), ls = jax.lax.scan(
                        indexed, (params, opt_state, rng),
                        jnp.arange(T) + t0)
                return params, opt_state, rng, ls[None]  # (1, T)

            return run

        def dispatch(i, T, steps_done, data):
            nonlocal params, opt_state, rng
            streamed = self._streamed
            fn = self._compiled(
                lambda: build_chunk(T, streamed=streamed),
                extra_key=("sstream", T, spb) if streamed
                else ("single", T, spb))
            params, opt_state, rng, losses = fn(
                params, opt_state, rng, *data, jnp.int32(steps_done))
            return losses

        cadence = (self.checkpoint_every * spb
                   if self.checkpoint_every else None)
        # dummy leading axis: the shared feed slices axis 1
        history = _run_single(
            self, xb[None], yb[None], start=start_t, total=total_t,
            per_epoch=spb, stream_units=self.stream_chunk_steps,
            cadence=cadence, samples_per_unit=self.batch_size,
            dispatch=dispatch,
            sync_ref=lambda: params,
            state_fn=lambda: {"params": params, "opt_state": opt_state,
                              "rng": rng,
                              "t_units": jnp.zeros((), jnp.int32)},
            carry_leaves=(params, opt_state))
        return self._finalize(params, history)


def _run_single(trainer, xs, ys, **kw):
    """run_chunked with SingleTrainer's flat (steps,) history contract."""
    from dist_keras_tpu.trainers.chunking import run_chunked

    history = run_chunked(trainer, xs, ys, fetch_global=lambda x: x, **kw)
    return np.asarray(history).reshape(-1).tolist() if history else []
