"""SingleTrainer — parity with ``distkeras/trainers.py:~100``.

Reference path: coalesce the DataFrame to one partition and run a plain
epochs x train_on_batch loop in one Spark task (SURVEY.md §3.1).  TPU-native:
the whole epoch is ONE jitted ``lax.scan`` over pre-batched device arrays;
the Python epoch loop re-enters the same compiled computation.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from dist_keras_tpu.trainers.base import Trainer
from dist_keras_tpu.trainers.step import make_model_step, scan_epoch


class SingleTrainer(Trainer):
    def train(self, dataset, shuffle=False):
        model, loss_fn, tx = self._resolve()
        if shuffle:
            dataset = dataset.shuffle(seed=self.seed)
        xb, yb = dataset.batches(
            self.batch_size, self.features_col, self.label_col)

        step, opt_init = make_model_step(
            model, loss_fn, tx, self.compute_dtype)
        params = model.params
        opt_state = opt_init(params)
        rng = jax.random.PRNGKey(self.seed)

        def build():
            @jax.jit
            def run_epoch(params, opt_state, rng, xb, yb):
                return scan_epoch(step, params, opt_state, rng, xb, yb)

            return run_epoch

        run_epoch = self._compiled(build)

        xb = jnp.asarray(xb)
        yb = jnp.asarray(yb)

        self.record_training_start()
        losses = []
        for _ in range(self.num_epoch):
            params, opt_state, rng, ls = run_epoch(
                params, opt_state, rng, xb, yb)
            losses.append(np.asarray(ls))
        jax.block_until_ready(params)
        self.record_training_end()

        return self._finalize(params, np.concatenate(losses).tolist())
