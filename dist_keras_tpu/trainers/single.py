"""SingleTrainer — parity with ``distkeras/trainers.py:~100``.

Reference path: coalesce the DataFrame to one partition and run a plain
epochs x train_on_batch loop in one Spark task (SURVEY.md §3.1).  TPU-native:
the whole epoch is ONE jitted ``lax.scan`` over pre-batched device arrays;
the Python epoch loop re-enters the same compiled computation.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from dist_keras_tpu.trainers.base import Trainer
from dist_keras_tpu.trainers.step import make_model_step, scan_epoch
from dist_keras_tpu.utils.sync import drain


class SingleTrainer(Trainer):
    def train(self, dataset, shuffle=False):
        import time as _time

        model, loss_fn, tx = self._resolve()
        if shuffle:
            dataset = dataset.shuffle(seed=self.seed)
        xb, yb = dataset.batches(
            self.batch_size, self.features_col, self.label_col,
            dtype=self.data_dtype)

        step, opt_init = make_model_step(
            model, loss_fn, tx, self.compute_dtype)
        params = model.params
        opt_state = opt_init(params)
        rng = jax.random.PRNGKey(self.seed)

        start_epoch, restored = self._maybe_resume(
            {"params": params, "opt_state": opt_state, "rng": rng})
        if restored is not None:
            params = restored["params"]
            opt_state = restored["opt_state"]
            rng = jnp.asarray(restored["rng"])

        def build_chunk(E):
            # E epochs inside ONE dispatch (outer scan over epochs, inner
            # scan over batches) — the same whole-run-compiled shape as
            # the distributed trainers; per-epoch host dispatch capped
            # SingleTrainer at ~90k samples/s on a v5e
            @jax.jit
            def run(params, opt_state, rng, xb, yb):
                def epoch(carry, _):
                    params, opt_state, rng = carry
                    params, opt_state, rng, ls = scan_epoch(
                        step, params, opt_state, rng, xb, yb)
                    return (params, opt_state, rng), ls

                (params, opt_state, rng), ls = jax.lax.scan(
                    epoch, (params, opt_state, rng), None, length=E)
                return params, opt_state, rng, ls  # ls: (E, steps)

            return run

        xb = jnp.asarray(xb)
        yb = jnp.asarray(yb)
        # data AND carry-state distribution completes OUTSIDE the clock
        drain(xb, yb, params, opt_state)
        samples_per_epoch = xb.shape[0] * self.batch_size

        self.record_training_start()
        losses = []
        epochs_done = start_epoch
        for E in self._chunk_plan(start_epoch):
            run = self._compiled(lambda: build_chunk(E), extra_key=(E,))
            t0 = _time.time()
            params, opt_state, rng, ls = run(
                params, opt_state, rng, xb, yb)
            drain(params)  # block_until_ready lies through the tunnel
            dt = _time.time() - t0
            epochs_done += E
            ls = np.asarray(ls)  # (E, steps)
            losses.append(ls.reshape(-1))
            self._emit_epoch_end(epochs_done, ls, dt,
                                 samples_per_epoch * E)
            self._maybe_checkpoint(
                epochs_done,
                lambda: {"params": params, "opt_state": opt_state,
                         "rng": rng})
        self.record_training_end()

        history = (np.concatenate(losses).tolist() if losses else [])
        return self._finalize(params, history)
