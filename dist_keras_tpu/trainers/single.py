"""SingleTrainer — parity with ``distkeras/trainers.py:~100``.

Reference path: coalesce the DataFrame to one partition and run a plain
epochs x train_on_batch loop in one Spark task (SURVEY.md §3.1).  TPU-native:
the whole epoch is ONE jitted ``lax.scan`` over pre-batched device arrays;
the Python epoch loop re-enters the same compiled computation.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from dist_keras_tpu.trainers.base import Trainer
from dist_keras_tpu.trainers.step import make_model_step, scan_epoch


class SingleTrainer(Trainer):
    def train(self, dataset, shuffle=False):
        import time as _time

        model, loss_fn, tx = self._resolve()
        if shuffle:
            dataset = dataset.shuffle(seed=self.seed)
        xb, yb = dataset.batches(
            self.batch_size, self.features_col, self.label_col)

        step, opt_init = make_model_step(
            model, loss_fn, tx, self.compute_dtype)
        params = model.params
        opt_state = opt_init(params)
        rng = jax.random.PRNGKey(self.seed)

        start_epoch, restored = self._maybe_resume(
            {"params": params, "opt_state": opt_state, "rng": rng})
        if restored is not None:
            params = restored["params"]
            opt_state = restored["opt_state"]
            rng = jnp.asarray(restored["rng"])

        def build():
            @jax.jit
            def run_epoch(params, opt_state, rng, xb, yb):
                return scan_epoch(step, params, opt_state, rng, xb, yb)

            return run_epoch

        run_epoch = self._compiled(build)

        xb = jnp.asarray(xb)
        yb = jnp.asarray(yb)
        samples_per_epoch = xb.shape[0] * self.batch_size

        self.record_training_start()
        losses = []
        for e in range(start_epoch, self.num_epoch):
            t0 = _time.time()
            params, opt_state, rng, ls = run_epoch(
                params, opt_state, rng, xb, yb)
            jax.block_until_ready(params)
            dt = _time.time() - t0
            ls = np.asarray(ls)
            losses.append(ls)
            self._emit_epoch_end(e + 1, ls, dt, samples_per_epoch)
            self._maybe_checkpoint(
                e + 1, lambda: {"params": params, "opt_state": opt_state,
                                "rng": rng})
        self.record_training_end()

        history = (np.concatenate(losses).tolist() if losses else [])
        return self._finalize(params, history)
