"""AveragingTrainer + EnsembleTrainer.

- ``AveragingTrainer`` (trainers.py:~160): per epoch, every worker trains a
  full pass over its shard, then weights are averaged.  The reference
  collects weight lists to the driver and numpy-means them
  (trainers.py:~190); here the merge is one fused ``lax.pmean`` over the ICI
  mesh inside the compiled epoch loop — no host round-trip at all.

- ``EnsembleTrainer`` (trainers.py:~230): N independent models trained in
  parallel (one per mesh slot), no merge; returns the list of models.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from dist_keras_tpu.parallel.collectives import tree_pmean_sync, tree_pvary
from dist_keras_tpu.parallel.mesh import WORKER_AXIS
from dist_keras_tpu.trainers.base import DistributedTrainer
from dist_keras_tpu.trainers.step import make_model_step

try:
    from jax import shard_map
except ImportError:  # older jax
    from jax.experimental.shard_map import shard_map


class AveragingTrainer(DistributedTrainer):
    def _cache_extras(self):
        # the epoch count is the outer scan length -> part of the trace
        return super()._cache_extras() + (self.num_epoch,)

    def train(self, dataset, shuffle=False):
        model, loss_fn, tx = self._resolve()
        if shuffle:
            dataset = dataset.shuffle(seed=self.seed)
        xs, ys = self._shards(dataset)  # (workers, steps, batch, ...)
        mesh = self.mesh
        num_epoch = self.num_epoch

        def build():
            step, opt_init = make_model_step(
                model, loss_fn, tx, self.compute_dtype)

            def body(params, xs, ys, rng):
                xs, ys = xs[0], ys[0]  # shard -> local (steps, batch, ...)
                rng = jax.random.fold_in(
                    rng, jax.lax.axis_index(WORKER_AXIS))

                def epoch(carry, _):
                    params, rng = carry
                    # Local copies must be explicitly worker-varying, else
                    # the backward pass psums gradients globally (see
                    # tree_pvary).
                    local = tree_pvary(params)
                    # Fresh worker optimizer each epoch, as the reference
                    # recompiles the model per epoch (trainers.py:~170).
                    opt_state = opt_init(local)
                    (local, _, rng), losses = jax.lax.scan(
                        step, (local, opt_state, rng), (xs, ys))
                    # pmean float weights; pmax integer leaves (lockstep
                    # seed counters) back to an axis-invariant type for
                    # the replicated epoch carry
                    params = tree_pmean_sync(local)
                    return (params, rng), losses

                (params, _), losses = jax.lax.scan(
                    epoch, (params, rng), None, length=num_epoch)
                return params, losses[None]  # losses: (1, epochs, steps)

            return jax.jit(shard_map(
                body, mesh=mesh,
                in_specs=(P(), P(WORKER_AXIS), P(WORKER_AXIS), P()),
                out_specs=(P(), P(WORKER_AXIS)),
            ))

        fn = self._compiled(build)

        self.record_training_start()
        params, losses = fn(model.params, jnp.asarray(xs), jnp.asarray(ys),
                            jax.random.PRNGKey(self.seed))
        jax.block_until_ready(params)
        self.record_training_end()

        # history: per-worker per-epoch per-step losses
        return self._finalize(params, np.asarray(losses).tolist())


class EnsembleTrainer(DistributedTrainer):
    """Trains ``num_models`` independent replicas; returns a list of models
    (majority voting at predict time is up to the user, as upstream)."""

    def __init__(self, keras_model, num_models=2, **kw):
        kw.setdefault("num_workers", num_models)
        super().__init__(keras_model, **kw)
        self.num_models = int(num_models)

    def _cache_extras(self):
        # the epoch count is the outer scan length -> part of the trace
        return super()._cache_extras() + (self.num_epoch,)

    def train(self, dataset, shuffle=False):
        model, loss_fn, tx = self._resolve()
        if shuffle:
            dataset = dataset.shuffle(seed=self.seed)
        xs, ys = self._shards(dataset)
        mesh = self.mesh
        num_epoch = self.num_epoch

        def build():
            step, opt_init = make_model_step(
                model, loss_fn, tx, self.compute_dtype)

            def body(params, xs, ys, rng):
                xs, ys = xs[0], ys[0]
                rng = jax.random.fold_in(
                    rng, jax.lax.axis_index(WORKER_AXIS))
                params = tree_pvary(params)  # independent replicas
                opt_state = opt_init(params)

                def epoch(carry, _):
                    params, opt_state, rng = carry
                    (params, opt_state, rng), losses = jax.lax.scan(
                        step, (params, opt_state, rng), (xs, ys))
                    return (params, opt_state, rng), losses

                (params, _, _), losses = jax.lax.scan(
                    epoch, (params, opt_state, rng), None, length=num_epoch)
                stacked = jax.tree.map(lambda x: x[None], params)
                return stacked, losses[None]

            return jax.jit(shard_map(
                body, mesh=mesh,
                in_specs=(P(), P(WORKER_AXIS), P(WORKER_AXIS), P()),
                out_specs=(P(WORKER_AXIS), P(WORKER_AXIS)),
            ))

        fn = self._compiled(build)

        self.record_training_start()
        stacked, losses = fn(model.params, jnp.asarray(xs), jnp.asarray(ys),
                             jax.random.PRNGKey(self.seed))
        jax.block_until_ready(stacked)
        self.record_training_end()
        self.history = np.asarray(losses).tolist()

        models = []
        for i in range(self.num_models):
            m = self._fresh_model()
            m.set_params(jax.tree.map(lambda x: np.asarray(x[i]), stacked))
            models.append(m)
        return models
