"""AveragingTrainer + EnsembleTrainer.

- ``AveragingTrainer`` (trainers.py:~160): per epoch, every worker trains a
  full pass over its shard, then weights are averaged.  The reference
  collects weight lists to the driver and numpy-means them
  (trainers.py:~190); here the merge is one fused ``lax.pmean`` over the ICI
  mesh inside the compiled epoch loop — no host round-trip at all.

- ``EnsembleTrainer`` (trainers.py:~230): N independent models trained in
  parallel (one per mesh slot), no merge; returns the list of models.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from dist_keras_tpu.parallel.collectives import tree_pmean_sync, tree_pvary
from dist_keras_tpu.parallel.mesh import WORKER_AXIS
from dist_keras_tpu.comm import backend as comm
from dist_keras_tpu.trainers.base import DistributedTrainer
from dist_keras_tpu.trainers.chunking import (
    reject_stale_checkpoint,
    run_chunked,
    scan_units,
)
from dist_keras_tpu.utils.sync import drain

try:
    from jax import shard_map
except ImportError:  # older jax
    from jax.experimental.shard_map import shard_map


class AveragingTrainer(DistributedTrainer):
    """Per-epoch weight averaging (trainers.py:~160).

    Round 4: the run is a flat scan over GLOBAL steps through the shared
    ``ChunkRunner`` — per-worker local state is re-initialized at each
    epoch's first step and ``pmean``-merged at its last (identical math
    to the round-3 per-epoch scan), which buys the same streaming feed as
    the rest of the family (``stream_chunk_steps`` counts chunks in
    STEPS here; ``max_resident_bytes`` auto-switches)."""

    def __init__(self, keras_model, stream_chunk_steps=None,
                 max_resident_bytes=None, **kw):
        super().__init__(keras_model, **kw)
        from dist_keras_tpu.trainers.chunking import init_streaming

        init_streaming(self, stream_chunk_steps, max_resident_bytes,
                       name="stream_chunk_steps")

    def train(self, dataset, shuffle=False):
        model, loss_fn, tx = self._resolve()
        if shuffle:
            dataset = dataset.shuffle(seed=self.seed)
        xs, ys = self._shards(dataset)  # (workers, steps, batch, ...)
        spe = xs.shape[1]
        total_t = self.num_epoch * spe
        mesh = self.mesh
        step, opt_init = self._make_step(model, loss_fn, tx)
        key = jax.random.PRNGKey(self.seed)

        def build_chunk(T, streamed=False):
            def body(params, local, opt_state, rng, xs, ys, key, t0):
                xs, ys = xs[0], ys[0]
                widx = jax.lax.axis_index(WORKER_AXIS)
                local = jax.tree.map(lambda a: a[0], local)
                opt_state = jax.tree.map(lambda a: a[0], opt_state)
                rng = rng[0]

                def one_step(carry, inp):
                    params, local, opt_state, rng = carry
                    t, x, y = inp
                    e, si = t // spe, t % spe
                    # epoch start: fresh local replica from the merged
                    # params, fresh worker optimizer (the reference
                    # recompiles per epoch, trainers.py:~170), fresh
                    # per-epoch rng — all carried thereafter so chunk
                    # boundaries at ANY step preserve the epoch math.
                    # si is worker-UNIFORM (derived from the replicated
                    # t), so lax.cond keeps the reset/merge work — incl.
                    # the cross-worker pmean — off the per-step hot path
                    # (a per-step where-form would all-reduce the full
                    # parameter tree EVERY step).
                    def reset(_):
                        fresh = tree_pvary(jax.random.fold_in(
                            jax.random.fold_in(key, e), widx))
                        pv = tree_pvary(params)
                        # pvary the fresh opt state too: its integer
                        # count leaf inits invariant, but the carried
                        # state is worker-sharded (varying) — cond
                        # branches must agree
                        return pv, tree_pvary(opt_init(pv)), fresh

                    local, opt_state, rng = jax.lax.cond(
                        si == 0, reset,
                        lambda _: (local, opt_state, rng), None)
                    (local, opt_state, rng), loss = step(
                        (local, opt_state, rng), (x, y))
                    # epoch end: pmean float weights; pmax integer
                    # leaves (lockstep seed counters) back to an
                    # axis-invariant type for the replicated carry
                    params = jax.lax.cond(
                        si == spe - 1,
                        lambda l: tree_pmean_sync(l),
                        lambda l: params, local)
                    return (params, local, opt_state, rng), loss

                (params, local, opt_state, rng), losses = scan_units(
                    one_step, (params, local, opt_state, rng),
                    xs, ys, T, t0, spe, streamed)
                stack = lambda t_: t_[None]  # noqa: E731
                return (params, jax.tree.map(stack, local),
                        jax.tree.map(stack, opt_state), rng[None],
                        losses[None])

            return jax.jit(shard_map(
                body, mesh=mesh,
                in_specs=(P(), P(WORKER_AXIS), P(WORKER_AXIS),
                          P(WORKER_AXIS), P(WORKER_AXIS), P(WORKER_AXIS),
                          P(), P()),
                out_specs=(P(), P(WORKER_AXIS), P(WORKER_AXIS),
                           P(WORKER_AXIS), P(WORKER_AXIS)),
            ))

        params = model.params
        local = self._stack_workers(params)
        opt_state = self._stack_workers(opt_init(params))
        rng = self._stack_workers(jnp.zeros((2,), jnp.uint32))
        template = {"params": params, "local": local,
                    "opt_state": opt_state, "rng": rng}
        start_t, restored = self._maybe_resume(
            template,
            incompatible_hint=(
                "if this checkpoint predates step-granular "
                "AveragingTrainer state (round 3: params only, step "
                "counted epochs not steps), restart training or point "
                "checkpoint_dir at a fresh directory"))
        reject_stale_checkpoint(
            restored, "local", "AveragingTrainer",
            "params only; its step counts epochs, not steps")
        if restored is not None:
            params = restored["params"]
            local = restored["local"]
            opt_state = restored["opt_state"]
            rng = restored["rng"]

        def dispatch(i, T, steps_done, data):
            nonlocal params, local, opt_state, rng
            streamed = self._streamed
            fn = self._compiled(
                lambda: build_chunk(T, streamed=streamed),
                extra_key=("stream", T, spe) if streamed else (T, spe))
            params, local, opt_state, rng, losses = fn(
                params, local, opt_state, rng, *data, key,
                jnp.int32(steps_done))
            return losses

        cadence = (self.checkpoint_every * spe
                   if self.checkpoint_every else None)
        history = run_chunked(
            self, xs, ys, start=start_t, total=total_t, per_epoch=spe,
            stream_units=self.stream_chunk_steps, cadence=cadence,
            samples_per_unit=self.num_workers * self.batch_size,
            dispatch=dispatch, sync_ref=lambda: params,
            state_fn=lambda: {"params": params, "local": local,
                              "opt_state": opt_state, "rng": rng},
            carry_leaves=(params, local, opt_state, rng),
            fetch_global=comm.fetch_global)
        return self._finalize(params, history)


class EnsembleTrainer(DistributedTrainer):
    """Trains ``num_models`` independent replicas; returns a list of models
    (majority voting at predict time is up to the user, as upstream).

    ``num_models`` may exceed the device count (the reference trains any
    N over however many executors Spark has): models are laid out
    ``(mesh slots, models_per_slot)`` and each slot ``vmap``s its
    replicas — one compiled program regardless of the ratio.

    Round 5: the run is a flat scan over GLOBAL steps through the shared
    ``ChunkRunner`` — each step ``vmap``s the model step across the
    slot's replicas and the per-model per-epoch rng is re-derived at
    each epoch's first step (identical math to the round-4 nested
    epoch scan), which buys the ensemble the same streaming feed as
    every other trainer (``stream_chunk_steps`` counts chunks in STEPS;
    ``max_resident_bytes`` auto-switches): the last resident-only
    trainer is gone — an ensemble whose data exceeds HBM streams
    through the two-buffer ChunkFeed like the rest of the family
    (reference property: an epoch never has to fit in executor memory,
    workers.py:~60).

    ``get_history()`` shape contract (mirrors the windowed family's
    convention, see ``Trainer.get_history``): a run whose executed span
    covers WHOLE epochs returns ``(num_models, epochs,
    steps_per_epoch)``; a run RESUMED mid-epoch (its partial first epoch
    breaks the alignment) returns the flat ``(num_models, steps_run)``
    layout instead.  Callers that index history per epoch should check
    ``ndim``/the middle axis, or keep ``checkpoint_every`` in whole
    epochs so every resume stays epoch-aligned.  The flat layout is
    deliberate: padding the partial epoch would fabricate loss values,
    and splitting it would misalign epoch indices against an
    uninterrupted run's."""

    def __init__(self, keras_model, num_models=2, stream_chunk_steps=None,
                 max_resident_bytes=None, **kw):
        from dist_keras_tpu.parallel.mesh import num_available_devices
        from dist_keras_tpu.trainers.chunking import init_streaming

        self.num_models = int(num_models)
        slots = kw.pop("num_workers", None)
        if slots is None:
            # device count must come AFTER multi-host bring-up (querying
            # devices initializes the backend; see base.mesh ordering)
            comm.initialize()
            slots = min(self.num_models, num_available_devices())
        if self.num_models % slots:
            raise ValueError(
                f"num_models={num_models} must divide evenly over "
                f"{slots} mesh slots (pad num_models or pass "
                "num_workers=<divisor>)")
        super().__init__(keras_model, num_workers=slots, **kw)
        self.models_per_slot = self.num_models // slots
        init_streaming(self, stream_chunk_steps, max_resident_bytes,
                       name="stream_chunk_steps")

    def _cache_extras(self):
        # slots alone no longer distinguishes configs: equal slot counts
        # with different num_models bake different mps into the body
        return super()._cache_extras() + (self.num_models,)

    def train(self, dataset, shuffle=False):
        model, loss_fn, tx = self._resolve()
        if shuffle:
            dataset = dataset.shuffle(seed=self.seed)
        # one data shard per MODEL (reference: one partition per model);
        # leading axis regrouped (slots, steps, models_per_slot, ...) —
        # steps on axis 1 so the ChunkFeed's axis-1 spans slice the scan
        # axis while mps rides inside each chunk's put.  Multi-host:
        # host h owns mesh slots [lo, hi), hence global model ids
        # [lo*mps, hi*mps) — slice exactly those models' rows so the
        # concatenation over hosts equals the single-host deal.
        mps = self.models_per_slot
        mesh = self.mesh  # prime the mesh (and multi-host bring-up)
        if comm.is_multi_host():
            lo, hi = self._local_worker_range()
            model_range = (lo * mps, hi * mps)
        else:
            model_range = None
        xs, ys = dataset.worker_shards(
            self.num_models, self.batch_size,
            features_col=self.features_col, label_col=self.label_col,
            worker_range=model_range, dtype=self.data_dtype)

        def _regroup(a):
            # -1, not self.num_workers: on multi-host only this host's
            # models are materialized (leading dim = LOCAL slot count)
            a = a.reshape(-1, mps, *a.shape[1:])
            return np.ascontiguousarray(
                a.transpose(0, 2, 1, *range(3, a.ndim)))

        xs, ys = _regroup(xs), _regroup(ys)  # (slots, steps, mps, ...)
        spe = xs.shape[1]
        total_t = self.num_epoch * spe
        step, opt_init = self._make_step(model, loss_fn, tx)
        key = jax.random.PRNGKey(self.seed)

        def build_chunk(T, streamed=False):
            def body(params, opt_state, rng, xs, ys, key, t0):
                # carry arrives stacked (1, mps, ...) per slot
                xs, ys = xs[0], ys[0]
                params = jax.tree.map(lambda t: t[0], params)
                opt_state = jax.tree.map(lambda t: t[0], opt_state)
                rng = rng[0]
                slot = jax.lax.axis_index(WORKER_AXIS)
                midx = slot * mps + jnp.arange(mps)  # global model ids

                def one_step(carry, inp):
                    params, opt_state, rng = carry
                    t, x, y = inp  # x, y: (mps, batch, ...)
                    e, si = t // spe, t % spe

                    # epoch start: fresh per-model per-epoch rng —
                    # identical derivation to the round-4 nested epoch
                    # scan (fold_in(fold_in(key, model_id), epoch)), so
                    # chunk boundaries at ANY step preserve the epoch
                    # math.  si is worker-UNIFORM (derived from the
                    # replicated t): lax.cond keeps the re-derivation
                    # off the per-step hot path.
                    def reset(_):
                        return jax.vmap(lambda mi: tree_pvary(
                            jax.random.fold_in(
                                jax.random.fold_in(key, mi), e)))(midx)

                    rng = jax.lax.cond(si == 0, reset,
                                       lambda _: rng, None)

                    def per_model(p, o, r, xm, ym):
                        (p, o, r), loss = step((p, o, r), (xm, ym))
                        return p, o, r, loss

                    params, opt_state, rng, loss = jax.vmap(per_model)(
                        params, opt_state, rng, x, y)
                    return (params, opt_state, rng), loss

                (params, opt_state, rng), losses = scan_units(
                    one_step, (params, opt_state, rng),
                    xs, ys, T, t0, spe, streamed)
                stack = lambda t_: t_[None]  # noqa: E731
                # losses: (T, mps) -> (1, T, mps): run_chunked's unit
                # axis is 1, models ride behind it
                return (jax.tree.map(stack, params),
                        jax.tree.map(stack, opt_state), rng[None],
                        losses[None])

            return jax.jit(shard_map(
                body, mesh=mesh,
                in_specs=(P(WORKER_AXIS), P(WORKER_AXIS), P(WORKER_AXIS),
                          P(WORKER_AXIS), P(WORKER_AXIS), P(), P()),
                out_specs=(P(WORKER_AXIS), P(WORKER_AXIS), P(WORKER_AXIS),
                           P(WORKER_AXIS)),
            ))

        stacked = self._stack_workers(model.params, inner=(mps,))
        opt_state = self._stack_workers(opt_init(model.params),
                                        inner=(mps,))
        rng = self._stack_workers(jnp.zeros((2,), jnp.uint32),
                                  inner=(mps,))
        template = {"params": stacked, "opt_state": opt_state, "rng": rng}
        hint = ("if this checkpoint predates step-granular "
                "EnsembleTrainer state (round 4: no rng leaf, step "
                "counted epochs not steps), restart training or point "
                "checkpoint_dir at a fresh directory")
        start_t, restored = self._maybe_resume(
            template, incompatible_hint=hint)
        reject_stale_checkpoint(
            restored, "rng", "EnsembleTrainer",
            "no rng leaf; its step counts epochs, not steps")
        if restored is not None:
            stacked = restored["params"]
            opt_state = restored["opt_state"]
            rng = restored["rng"]

        def dispatch(i, T, steps_done, data):
            nonlocal stacked, opt_state, rng
            streamed = self._streamed
            fn = self._compiled(
                lambda: build_chunk(T, streamed=streamed),
                extra_key=("stream", T, spe) if streamed else (T, spe))
            stacked, opt_state, rng, losses = fn(
                stacked, opt_state, rng, *data, key,
                jnp.int32(steps_done))
            return losses

        cadence = (self.checkpoint_every * spe
                   if self.checkpoint_every else None)
        hist = run_chunked(
            self, xs, ys, start=start_t, total=total_t, per_epoch=spe,
            stream_units=self.stream_chunk_steps, cadence=cadence,
            samples_per_unit=self.num_models * self.batch_size,
            dispatch=dispatch, sync_ref=lambda: stacked,
            state_fn=lambda: {"params": stacked, "opt_state": opt_state,
                              "rng": rng},
            carry_leaves=(stacked, opt_state, rng),
            fetch_global=comm.fetch_global)
        # (slots, epochs, spe, mps) -> (num_models, epochs, spe); a
        # mid-epoch resume's partial run stays flat (slots, T, mps) ->
        # (num_models, T), mirroring the windowed family's convention
        arr = np.asarray(hist)
        if arr.ndim == 4:
            arr = arr.transpose(0, 3, 1, 2).reshape(
                self.num_models, arr.shape[1], arr.shape[2])
        elif arr.ndim == 3:
            arr = arr.transpose(0, 2, 1).reshape(self.num_models, -1)
        self.history = arr.tolist()

        # one device->host transfer for the whole ensemble, then slice
        # (fetch_global: multi-host gathers every host's slots so ALL
        # hosts hold all models, matching the driver-side collect of the
        # reference; np.asarray alone cannot read non-addressable shards)
        host = jax.tree.map(
            lambda x: np.asarray(x).reshape(
                self.num_models, *x.shape[2:]),
            comm.fetch_global(stacked))
        models = []
        for i in range(self.num_models):
            m = self._fresh_model()
            m.set_params(jax.tree.map(lambda x: x[i], host))
            models.append(m)
        return models
