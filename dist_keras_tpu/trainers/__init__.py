from dist_keras_tpu.trainers.averaging import AveragingTrainer, EnsembleTrainer
from dist_keras_tpu.trainers.base import DistributedTrainer, Trainer
from dist_keras_tpu.trainers.dynsgd import DynSGD
from dist_keras_tpu.trainers.single import SingleTrainer
from dist_keras_tpu.trainers.windowed import (
    ADAG,
    AEASGD,
    DOWNPOUR,
    EAMSGD,
    AsynchronousDistributedTrainer,
)

__all__ = [
    "Trainer", "DistributedTrainer", "AsynchronousDistributedTrainer",
    "SingleTrainer", "AveragingTrainer", "EnsembleTrainer",
    "DOWNPOUR", "ADAG", "AEASGD", "EAMSGD", "DynSGD",
]
