"""Train-step machinery shared by every trainer.

The reference's hot loop is ``model.train_on_batch`` inside a Spark task
(``distkeras/workers.py:~60-115``).  Here the equivalent is a pure jitted
step over a params pytree, and an epoch is one ``lax.scan`` over a
``(steps, batch, ...)`` tensor — a single XLA computation per epoch, with
the batch loop compiled (no per-batch Python, no recompiles, MXU stays hot).

Mixed precision: ``compute_dtype=jnp.bfloat16`` casts parameters and inputs
for the forward/backward while the master params and optimizer state stay
float32 (loss is always reduced in f32).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import optax

from dist_keras_tpu.utils.pytree import tree_cast


def make_loss_fn(apply_fn, loss_fn, compute_dtype=None, training=True):
    """-> loss(params, x, y, rng) -> scalar f32."""

    def loss_of(params, x, y, rng=None):
        if compute_dtype is not None:
            params = tree_cast(params, compute_dtype)
            x = x.astype(compute_dtype)
        elif not jnp.issubdtype(x.dtype, jnp.floating):
            # cast-late input pipeline (data_dtype=None ships uint8):
            # the cast happens here, on-device, not on the host
            x = x.astype(jnp.float32)
        preds = apply_fn(params, x, training=training, rng=rng)
        return loss_fn(preds.astype(jnp.float32), y.astype(jnp.float32))

    return loss_of


def make_sgd_step(apply_fn, loss_fn, tx, compute_dtype=None, training=True):
    """-> step((params, opt_state, rng), (x, y)) -> (carry, loss).

    Shaped for ``lax.scan``: one local optimizer update per mini-batch,
    the train_on_batch equivalent (workers.py:~115).
    """
    loss_of = make_loss_fn(apply_fn, loss_fn, compute_dtype, training)
    grad_fn = jax.value_and_grad(loss_of)

    def step(carry, batch):
        params, opt_state, rng = carry
        x, y = batch
        rng, sub = jax.random.split(rng)
        loss, grads = grad_fn(params, x, y, sub)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return (params, opt_state, rng), loss

    return step


def make_model_step(model, loss_fn, tx, compute_dtype=None, training=True):
    """-> (step, opt_init) for a model object.

    For stateless models this is exactly ``make_sgd_step(model.apply, ...)``
    with ``opt_init = tx.init``.  For models with running state (BatchNorm
    moving stats, Keras seed generators — anything ``model.has_state()``
    reports), the step threads the aux-state channel:

    - gradients are taken w.r.t. the *trainable* split only, so integer
      state leaves (Keras seed generators) never hit ``jax.grad`` and the
      optimizer never decays moving statistics;
    - the state split is replaced each step by the values
      ``model.apply_with_state`` returns (momentum-blended batch stats,
      advanced seed state);
    - ``opt_init(params)`` builds optimizer state over the trainable split
      only — trainers must use it instead of raw ``tx.init``.

    The carried params pytree keeps its full structure (state leaves
    included), so trainer merge algebra (psum deltas, elastic averaging,
    pmean) treats moving stats like any other weight — the reference
    behaves identically, since Keras ``get_weights`` includes them.
    """
    has_state = getattr(model, "has_state", None)
    if has_state is None or not model.has_state():
        step = make_sgd_step(model.apply, loss_fn, tx, compute_dtype,
                             training)
        return step, tx.init

    cast = getattr(model, "cast_params", None) or (
        lambda p, d: tree_cast(p, d))

    def loss_of(trainable, state, x, y, rng=None):
        params = model.join_state(trainable, state)
        if compute_dtype is not None:
            params = cast(params, compute_dtype)
            x = x.astype(compute_dtype)
        elif not jnp.issubdtype(x.dtype, jnp.floating):
            x = x.astype(jnp.float32)  # cast-late uint8 feed (see above)
        preds, new_state = model.apply_with_state(
            params, x, training=training, rng=rng)
        loss = loss_fn(preds.astype(jnp.float32), y.astype(jnp.float32))
        return loss, new_state

    grad_fn = jax.value_and_grad(loss_of, has_aux=True)

    def step(carry, batch):
        params, opt_state, rng = carry
        x, y = batch
        rng, sub = jax.random.split(rng)
        trainable, state = model.split_state(params)
        (loss, new_state), grads = grad_fn(trainable, state, x, y, sub)
        updates, opt_state = tx.update(grads, opt_state, trainable)
        trainable = optax.apply_updates(trainable, updates)
        params = model.join_state(trainable, new_state)
        return (params, opt_state, rng), loss

    def opt_init(params):
        return tx.init(model.split_state(params)[0])

    return step, opt_init
