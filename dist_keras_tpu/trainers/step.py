"""Train-step machinery shared by every trainer.

The reference's hot loop is ``model.train_on_batch`` inside a Spark task
(``distkeras/workers.py:~60-115``).  Here the equivalent is a pure jitted
step over a params pytree, and an epoch is one ``lax.scan`` over a
``(steps, batch, ...)`` tensor — a single XLA computation per epoch, with
the batch loop compiled (no per-batch Python, no recompiles, MXU stays hot).

Mixed precision: ``compute_dtype=jnp.bfloat16`` casts parameters and inputs
for the forward/backward while the master params and optimizer state stay
float32 (loss is always reduced in f32).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import optax

from dist_keras_tpu.utils.pytree import tree_cast


def make_loss_fn(apply_fn, loss_fn, compute_dtype=None, training=True):
    """-> loss(params, x, y, rng) -> scalar f32."""

    def loss_of(params, x, y, rng=None):
        if compute_dtype is not None:
            params = tree_cast(params, compute_dtype)
            x = x.astype(compute_dtype)
        preds = apply_fn(params, x, training=training, rng=rng)
        return loss_fn(preds.astype(jnp.float32), y.astype(jnp.float32))

    return loss_of


def make_sgd_step(apply_fn, loss_fn, tx, compute_dtype=None, training=True):
    """-> step((params, opt_state, rng), (x, y)) -> (carry, loss).

    Shaped for ``lax.scan``: one local optimizer update per mini-batch,
    the train_on_batch equivalent (workers.py:~115).
    """
    loss_of = make_loss_fn(apply_fn, loss_fn, compute_dtype, training)
    grad_fn = jax.value_and_grad(loss_of)

    def step(carry, batch):
        params, opt_state, rng = carry
        x, y = batch
        rng, sub = jax.random.split(rng)
        loss, grads = grad_fn(params, x, y, sub)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return (params, opt_state, rng), loss

    return step


def scan_epoch(step, params, opt_state, rng, xb, yb):
    """Run ``step`` over every batch with lax.scan.

    xb/yb: (steps, batch, ...). Returns (params, opt_state, rng, losses).
    """
    (params, opt_state, rng), losses = jax.lax.scan(
        step, (params, opt_state, rng), (xb, yb))
    return params, opt_state, rng, losses
