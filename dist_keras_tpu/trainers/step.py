"""Train-step machinery shared by every trainer.

The reference's hot loop is ``model.train_on_batch`` inside a Spark task
(``distkeras/workers.py:~60-115``).  Here the equivalent is a pure jitted
step over a params pytree, and an epoch is one ``lax.scan`` over a
``(steps, batch, ...)`` tensor — a single XLA computation per epoch, with
the batch loop compiled (no per-batch Python, no recompiles, MXU stays hot).

Mixed precision: ``compute_dtype=jnp.bfloat16`` casts parameters and inputs
for the forward/backward while the master params and optimizer state stay
float32 (loss is always reduced in f32).

NaN guard (round 6): ``skip_nonfinite=True`` compiles a finite-check over
(loss, grads) into the step and keeps the previous params/optimizer state
when it fails — one exploding batch costs one skipped update instead of
poisoning the run.  This is the device half of ``nan_policy="skip"``
(``resilience.guards`` is the host half); it changes the traced program,
so trainers key their jit cache on it.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import optax

from dist_keras_tpu.utils.pytree import tree_cast


def make_loss_fn(apply_fn, loss_fn, compute_dtype=None, training=True):
    """-> loss(params, x, y, rng) -> scalar f32."""

    def loss_of(params, x, y, rng=None):
        if compute_dtype is not None:
            params = tree_cast(params, compute_dtype)
            x = x.astype(compute_dtype)
        elif not jnp.issubdtype(x.dtype, jnp.floating):
            # cast-late input pipeline (data_dtype=None ships uint8):
            # the cast happens here, on-device, not on the host
            x = x.astype(jnp.float32)
        preds = apply_fn(params, x, training=training, rng=rng)
        return loss_fn(preds.astype(jnp.float32), y.astype(jnp.float32))

    return loss_of


def _all_finite(loss, grads):
    """Scalar bool: loss and every float grad leaf are finite."""
    ok = jnp.isfinite(loss)
    for g in jax.tree.leaves(grads):
        if jnp.issubdtype(g.dtype, jnp.floating):
            ok = jnp.logical_and(ok, jnp.all(jnp.isfinite(g)))
    return ok


def _select(ok, new, old):
    """Pytree where(ok, new, old) — the skipped-update selector."""
    return jax.tree.map(lambda n, o: jnp.where(ok, n, o), new, old)


def make_sgd_step(apply_fn, loss_fn, tx, compute_dtype=None, training=True,
                  skip_nonfinite=False):
    """-> step((params, opt_state, rng), (x, y)) -> (carry, loss).

    Shaped for ``lax.scan``: one local optimizer update per mini-batch,
    the train_on_batch equivalent (workers.py:~115).  With
    ``skip_nonfinite`` a step whose loss or grads are NaN/Inf keeps the
    incoming params AND optimizer state (the rng still advances, so the
    schedule stays deterministic); the NaN loss is still emitted for the
    host-side counter.
    """
    loss_of = make_loss_fn(apply_fn, loss_fn, compute_dtype, training)
    grad_fn = jax.value_and_grad(loss_of)

    def step(carry, batch):
        params, opt_state, rng = carry
        x, y = batch
        rng, sub = jax.random.split(rng)
        loss, grads = grad_fn(params, x, y, sub)
        updates, new_opt = tx.update(grads, opt_state, params)
        new_params = optax.apply_updates(params, updates)
        if skip_nonfinite:
            ok = _all_finite(loss, grads)
            new_params = _select(ok, new_params, params)
            new_opt = _select(ok, new_opt, opt_state)
        return (new_params, new_opt, rng), loss

    return step


def make_model_step(model, loss_fn, tx, compute_dtype=None, training=True,
                    skip_nonfinite=False):
    """-> (step, opt_init) for a model object.

    For stateless models this is exactly ``make_sgd_step(model.apply, ...)``
    with ``opt_init = tx.init``.  For models with running state (BatchNorm
    moving stats, Keras seed generators — anything ``model.has_state()``
    reports), the step threads the aux-state channel:

    - gradients are taken w.r.t. the *trainable* split only, so integer
      state leaves (Keras seed generators) never hit ``jax.grad`` and the
      optimizer never decays moving statistics;
    - the state split is replaced each step by the values
      ``model.apply_with_state`` returns (momentum-blended batch stats,
      advanced seed state);
    - ``opt_init(params)`` builds optimizer state over the trainable split
      only — trainers must use it instead of raw ``tx.init``.

    The carried params pytree keeps its full structure (state leaves
    included), so trainer merge algebra (psum deltas, elastic averaging,
    pmean) treats moving stats like any other weight — the reference
    behaves identically, since Keras ``get_weights`` includes them.
    """
    has_state = getattr(model, "has_state", None)
    if has_state is None or not model.has_state():
        step = make_sgd_step(model.apply, loss_fn, tx, compute_dtype,
                             training, skip_nonfinite=skip_nonfinite)
        return step, tx.init

    cast = getattr(model, "cast_params", None) or (
        lambda p, d: tree_cast(p, d))

    def loss_of(trainable, state, x, y, rng=None):
        params = model.join_state(trainable, state)
        if compute_dtype is not None:
            params = cast(params, compute_dtype)
            x = x.astype(compute_dtype)
        elif not jnp.issubdtype(x.dtype, jnp.floating):
            x = x.astype(jnp.float32)  # cast-late uint8 feed (see above)
        preds, new_state = model.apply_with_state(
            params, x, training=training, rng=rng)
        loss = loss_fn(preds.astype(jnp.float32), y.astype(jnp.float32))
        return loss, new_state

    grad_fn = jax.value_and_grad(loss_of, has_aux=True)

    def step(carry, batch):
        params, opt_state, rng = carry
        x, y = batch
        rng, sub = jax.random.split(rng)
        trainable, state = model.split_state(params)
        (loss, new_state), grads = grad_fn(trainable, state, x, y, sub)
        updates, new_opt = tx.update(grads, opt_state, trainable)
        trainable = optax.apply_updates(trainable, updates)
        new_params = model.join_state(trainable, new_state)
        if skip_nonfinite:
            # a bad step keeps the previous params, running state
            # (BatchNorm stats computed from the poisoned batch) AND
            # optimizer state together
            ok = _all_finite(loss, grads)
            new_params = _select(ok, new_params, params)
            new_opt = _select(ok, new_opt, opt_state)
        return (new_params, new_opt, rng), loss

    def opt_init(params):
        return tx.init(model.split_state(params)[0])

    return step, opt_init
