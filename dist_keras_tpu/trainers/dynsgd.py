"""DynSGD — staleness-scaled updates, with *real* staleness under SPMD.

Reference semantics (workers.py:~530 + parameter_servers.py:~280): each
worker commits ``{delta, last_seen_update}`` and the PS scales the commit by
``1/(staleness+1)`` where ``staleness = num_updates - last_seen_update``.

Staleness is meaningless if all workers commit in lockstep, so a plain
windowed port would degenerate to DOWNPOUR (SURVEY.md §7 hard part #1).
Instead we *stagger* the commit schedule: worker ``i`` commits every
``communication_window`` steps at phase offset ``i*W/N``.  Commits from
different workers then land at different global steps, the center variable
moves between a worker's pull and its next commit, and the DynSGD staleness
counter measures exactly what it does in the reference — how many center
updates the worker missed.  The commit itself is a masked ``psum`` executed
every step (zero contribution from non-committing workers), so the whole
schedule stays one compiled ``lax.scan`` with no data-dependent control flow.

Like the other distributed trainers, epochs loop on the host over
device-resident data (one H2D transfer), and all per-worker state — local
replica, pulled snapshot, optimizer state, staleness counters — persists
across epochs.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from dist_keras_tpu.parallel.collectives import tree_psum, tree_pvary
from dist_keras_tpu.parallel.mesh import WORKER_AXIS
from dist_keras_tpu.comm import backend as comm
from dist_keras_tpu.trainers.base import DistributedTrainer
from dist_keras_tpu.trainers.step import make_model_step
from dist_keras_tpu.utils.pytree import tree_merge_floats, tree_zeros_like
from dist_keras_tpu.utils.sync import drain

try:
    from jax import shard_map
except ImportError:  # older jax
    from jax.experimental.shard_map import shard_map


def _make_body(step, window, num_workers, num_epochs_chunk):
    """Chunked scan body: runs ``num_epochs_chunk`` epochs from absolute
    epoch ``epoch0`` with ALL per-worker state (pulled snapshot, local
    replica, optimizer state, staleness counters) carried in/out, so the
    staggered-staleness schedule survives checkpoint/resume boundaries."""
    def body(center, pulled, local, opt_state, last_seen, global_count,
             xs, ys, key, epoch0):
        xs, ys = xs[0], ys[0]
        widx = jax.lax.axis_index(WORKER_AXIS)
        phase = (widx * window) // num_workers  # staggered commit schedule

        # per-worker carry arrives stacked (1, ...) on the worker shard
        unstack = lambda t: t[0]  # noqa: E731
        pulled = jax.tree.map(unstack, pulled)
        local = jax.tree.map(unstack, local)
        opt_state = jax.tree.map(unstack, opt_state)
        last_seen = unstack(last_seen)

        def one_step(carry, inp):
            (center, pulled, local, opt_state, rng,
             last_seen, global_count) = carry
            t, x, y = inp
            (local, opt_state, rng), loss = step(
                (local, opt_state, rng), (x, y))

            commit = ((t + 1 + phase) % window == 0)
            m = commit.astype(jnp.float32)
            staleness = (global_count - last_seen).astype(jnp.float32)
            scale = m / (staleness + 1.0)

            # integer leaves (Keras seed-generator counters) are RNG
            # state, not weights: zero contribution, never pulled
            # (tree_merge_floats implements the exemption policy)
            contribution = tree_merge_floats(
                jax.tree.map(lambda l, p: scale * (l.astype(jnp.float32)
                                                   - p.astype(jnp.float32)),
                             local, pulled),
                tree_zeros_like(local))
            center = jax.tree.map(
                lambda c, d: (c + d).astype(c.dtype), center,
                tree_psum(contribution))
            global_count = global_count + jax.lax.psum(
                commit.astype(jnp.int32), WORKER_AXIS)
            # committing workers pull the fresh center
            local = tree_merge_floats(
                jax.tree.map(lambda l, c: jnp.where(commit, c, l),
                             local, center), local)
            pulled = tree_merge_floats(
                jax.tree.map(lambda p, c: jnp.where(commit, c, p),
                             pulled, center), pulled)
            last_seen = jnp.where(commit, global_count, last_seen)
            return (center, pulled, local, opt_state, rng,
                    last_seen, global_count), loss

        steps = xs.shape[0]

        def epoch(carry, e):
            (center, pulled, local, opt_state,
             last_seen, global_count) = carry
            rng = tree_pvary(jax.random.fold_in(
                jax.random.fold_in(key, e), widx))
            ts = jnp.arange(steps) + e * steps
            state = (center, pulled, local, opt_state, rng,
                     last_seen, global_count)
            state, losses = jax.lax.scan(one_step, state, (ts, xs, ys))
            (center, pulled, local, opt_state, _,
             last_seen, global_count) = state
            return (center, pulled, local, opt_state,
                    last_seen, global_count), losses

        carry = (center, pulled, local, opt_state, last_seen, global_count)
        carry, losses = jax.lax.scan(
            epoch, carry, jnp.arange(num_epochs_chunk) + epoch0)
        (center, pulled, local, opt_state, last_seen, global_count) = carry
        stack = lambda t: t[None]  # noqa: E731
        return (center, jax.tree.map(stack, pulled),
                jax.tree.map(stack, local), jax.tree.map(stack, opt_state),
                stack(last_seen), global_count,
                losses[None])  # losses: (1, epochs, steps)

    return body


class DynSGD(DistributedTrainer):
    def __init__(self, keras_model, num_workers=2, communication_window=5,
                 **kw):
        super().__init__(keras_model, num_workers=num_workers, **kw)
        self.communication_window = int(communication_window)

    def _cache_extras(self):
        # the per-chunk epoch count is appended via _compiled(extra_key=)
        return super()._cache_extras() + (self.communication_window,)

    def train(self, dataset, shuffle=False):
        import time as _time

        model, loss_fn, tx = self._resolve()
        if shuffle:
            dataset = dataset.shuffle(seed=self.seed)
        xs, ys = self._shards(dataset)  # (workers, steps, batch, ...)
        mesh = self.mesh
        step, opt_init = make_model_step(
            model, loss_fn, tx, self.compute_dtype)

        def build_chunk(E):
            return jax.jit(shard_map(
                _make_body(step, self.communication_window,
                           self.num_workers, E),
                mesh=mesh,
                in_specs=(P(), P(WORKER_AXIS), P(WORKER_AXIS),
                          P(WORKER_AXIS), P(WORKER_AXIS), P(),
                          P(WORKER_AXIS), P(WORKER_AXIS), P(), P()),
                out_specs=(P(), P(WORKER_AXIS), P(WORKER_AXIS),
                           P(WORKER_AXIS), P(WORKER_AXIS), P(),
                           P(WORKER_AXIS)),
            ))

        center = model.params
        pulled = self._stack_workers(center)
        local = self._stack_workers(center)
        opt_state = self._stack_workers(opt_init(center))
        last_seen = jnp.zeros((self.num_workers,), jnp.int32)
        global_count = jnp.zeros((), jnp.int32)
        template = {"center": center, "pulled": pulled, "local": local,
                    "opt_state": opt_state, "last_seen": last_seen,
                    "global_count": global_count}
        start_epoch, restored = self._maybe_resume(template)
        if restored is not None:
            center = restored["center"]
            pulled = restored["pulled"]
            local = restored["local"]
            opt_state = restored["opt_state"]
            last_seen = restored["last_seen"]
            global_count = restored["global_count"]

        xs = self._to_device(xs)
        ys = self._to_device(ys)
        # data AND carry-state distribution completes OUTSIDE the clock
        drain(xs, ys, center, pulled, local, opt_state, last_seen)
        key = jax.random.PRNGKey(self.seed)
        samples_per_epoch = xs.shape[0] * xs.shape[1] * self.batch_size

        self.record_training_start()
        all_losses = []
        epochs_done = start_epoch
        for E in self._chunk_plan(start_epoch):
            fn = self._compiled(lambda: build_chunk(E), extra_key=(E,))
            t0 = _time.time()
            (center, pulled, local, opt_state, last_seen, global_count,
             losses) = fn(center, pulled, local, opt_state, last_seen,
                          global_count, xs, ys, key,
                          jnp.int32(epochs_done))
            drain(center)  # block_until_ready lies through the tunnel
            dt = _time.time() - t0
            epochs_done += E
            losses = np.asarray(comm.fetch_global(losses))  # (workers, E, steps)
            all_losses.append(losses)
            self._emit_epoch_end(epochs_done, losses, dt,
                                 samples_per_epoch * E)
            self._maybe_checkpoint(
                epochs_done,
                lambda: {"center": center, "pulled": pulled,
                         "local": local, "opt_state": opt_state,
                         "last_seen": last_seen,
                         "global_count": global_count})
        self.record_training_end()

        history = (np.concatenate(all_losses, axis=1).tolist()
                   if all_losses else [])
        # history: (workers, epochs, steps)
        return self._finalize(center, history)
