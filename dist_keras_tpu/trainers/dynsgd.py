"""DynSGD — staleness-scaled updates, with *real* staleness under SPMD.

Reference semantics (workers.py:~530 + parameter_servers.py:~280): each
worker commits ``{delta, last_seen_update}`` and the PS scales the commit by
``1/(staleness+1)`` where ``staleness = num_updates - last_seen_update``.

Staleness is meaningless if all workers commit in lockstep, so a plain
windowed port would degenerate to DOWNPOUR (SURVEY.md §7 hard part #1).
Instead we *stagger* the commit schedule: worker ``i`` commits every
``communication_window`` steps at phase offset ``i*W/N``.  Commits from
different workers then land at different global steps, the center variable
moves between a worker's pull and its next commit, and the DynSGD staleness
counter measures exactly what it does in the reference — how many center
updates the worker missed.  The commit itself is a masked ``psum`` executed
every step (zero contribution from non-committing workers), so the whole
schedule stays one compiled ``lax.scan`` with no data-dependent control flow.

Round 4: the dispatch is STEP-granular through the shared ``ChunkRunner``
(``trainers/chunking.py``), which buys DynSGD the two capabilities the
windowed family got in rounds 3-4 — ``checkpoint_every_windows`` saves
mid-epoch (the staggered schedule has the most state to lose on
preemption: pulled snapshots, staleness counters, the in-epoch rng are
all in the payload and resume bit-exactly) and
``stream_chunk_windows``/``max_resident_bytes`` stream the data through
the double-buffered ChunkFeed, so an epoch no longer has to fit in HBM
(the reference's partition-iterator property, workers.py:~60).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from dist_keras_tpu.parallel.collectives import tree_psum, tree_pvary
from dist_keras_tpu.parallel.mesh import WORKER_AXIS
from dist_keras_tpu.comm import backend as comm
from dist_keras_tpu.trainers.chunking import run_chunked
from dist_keras_tpu.trainers.windowed import AsynchronousDistributedTrainer
from dist_keras_tpu.utils.pytree import tree_merge_floats, tree_zeros_like

try:
    from jax import shard_map
except ImportError:  # older jax
    from jax.experimental.shard_map import shard_map


def _make_body(step, window, num_workers, steps_per_epoch, T, streamed):
    """Chunked scan body over a flat range of GLOBAL steps [t0, t0+T).

    All per-worker state (pulled snapshot, local replica, optimizer
    state, staleness counters, in-epoch rng) is carried in/out, so the
    staggered-staleness schedule survives chunk boundaries at ANY step —
    including mid-epoch checkpoint cuts and streaming data-chunk cuts.
    The epoch's rng stream starts at its first step (``t % spe == 0``)
    and is carried through the rest, so a mid-epoch resume replays the
    identical stream (same construction as windowed.build_chunk).
    """
    def body(center, pulled, local, opt_state, last_seen, global_count,
             rng, xs, ys, key, t0):
        xs, ys = xs[0], ys[0]  # (spe | T, batch, ...)
        widx = jax.lax.axis_index(WORKER_AXIS)
        phase = (widx * window) // num_workers  # staggered commit schedule

        # per-worker carry arrives stacked (1, ...) on the worker shard
        unstack = lambda t: t[0]  # noqa: E731
        pulled = jax.tree.map(unstack, pulled)
        local = jax.tree.map(unstack, local)
        opt_state = jax.tree.map(unstack, opt_state)
        last_seen = unstack(last_seen)
        rng = rng[0]

        def one_step(carry, inp):
            (center, pulled, local, opt_state, rng,
             last_seen, global_count) = carry
            t, x, y = inp
            e, si = t // steps_per_epoch, t % steps_per_epoch
            fresh = tree_pvary(jax.random.fold_in(
                jax.random.fold_in(key, e), widx))
            rng = jnp.where(si == 0, fresh, rng)
            (local, opt_state, rng), loss = step(
                (local, opt_state, rng), (x, y))

            commit = ((t + 1 + phase) % window == 0)
            m = commit.astype(jnp.float32)
            staleness = (global_count - last_seen).astype(jnp.float32)
            scale = m / (staleness + 1.0)

            # integer leaves (Keras seed-generator counters) are RNG
            # state, not weights: zero contribution, never pulled
            # (tree_merge_floats implements the exemption policy)
            contribution = tree_merge_floats(
                jax.tree.map(lambda l, p: scale * (l.astype(jnp.float32)
                                                   - p.astype(jnp.float32)),
                             local, pulled),
                tree_zeros_like(local))
            center = jax.tree.map(
                lambda c, d: (c + d).astype(c.dtype), center,
                tree_psum(contribution))
            global_count = global_count + jax.lax.psum(
                commit.astype(jnp.int32), WORKER_AXIS)
            # committing workers pull the fresh center
            local = tree_merge_floats(
                jax.tree.map(lambda l, c: jnp.where(commit, c, l),
                             local, center), local)
            pulled = tree_merge_floats(
                jax.tree.map(lambda p, c: jnp.where(commit, c, p),
                             pulled, center), pulled)
            last_seen = jnp.where(commit, global_count, last_seen)
            return (center, pulled, local, opt_state, rng,
                    last_seen, global_count), loss

        carry = (center, pulled, local, opt_state, rng,
                 last_seen, global_count)
        if streamed:
            carry, losses = jax.lax.scan(
                one_step, carry, (jnp.arange(T) + t0, xs, ys))
        else:
            def indexed(c, t):
                si = t % steps_per_epoch
                x = jax.lax.dynamic_index_in_dim(xs, si, 0, keepdims=False)
                y = jax.lax.dynamic_index_in_dim(ys, si, 0, keepdims=False)
                return one_step(c, (t, x, y))

            carry, losses = jax.lax.scan(
                indexed, carry, jnp.arange(T) + t0)
        (center, pulled, local, opt_state, rng,
         last_seen, global_count) = carry
        stack = lambda t: t[None]  # noqa: E731
        return (center, jax.tree.map(stack, pulled),
                jax.tree.map(stack, local), jax.tree.map(stack, opt_state),
                stack(last_seen), global_count, rng[None],
                losses[None])  # losses: (1, T)

    return body


class DynSGD(AsynchronousDistributedTrainer):
    """trainers.py:~700 / workers.py:~530; inherits the windowed family's
    checkpoint/streaming kwargs (cadences are counted in communication
    windows = ``communication_window`` steps)."""

    def merge(self, center, local):  # pragma: no cover - not windowed
        raise NotImplementedError(
            "DynSGD commits per-step with staggered phases; it does not "
            "use the windowed merge hook")

    def train(self, dataset, shuffle=False):
        model, loss_fn, tx = self._resolve()
        if shuffle:
            dataset = dataset.shuffle(seed=self.seed)
        xs, ys = self._shards(dataset)  # (workers, steps, batch, ...)
        spe = xs.shape[1]  # steps per epoch
        total_t = self.num_epoch * spe
        W = self.communication_window
        mesh = self.mesh
        step, opt_init = self._make_step(model, loss_fn, tx)
        key = jax.random.PRNGKey(self.seed)

        def build_chunk(T, streamed=False):
            body = _make_body(step, W, self.num_workers, spe, T, streamed)
            return jax.jit(shard_map(
                body, mesh=mesh,
                in_specs=(P(), P(WORKER_AXIS), P(WORKER_AXIS),
                          P(WORKER_AXIS), P(WORKER_AXIS), P(),
                          P(WORKER_AXIS), P(WORKER_AXIS), P(WORKER_AXIS),
                          P(), P()),
                out_specs=(P(), P(WORKER_AXIS), P(WORKER_AXIS),
                           P(WORKER_AXIS), P(WORKER_AXIS), P(),
                           P(WORKER_AXIS), P(WORKER_AXIS)),
            ))

        center = model.params
        pulled = self._stack_workers(center)
        local = self._stack_workers(center)
        opt_state = self._stack_workers(opt_init(center))
        last_seen = self._stack_workers(jnp.zeros((), jnp.int32))
        global_count = jnp.zeros((), jnp.int32)
        rng = self._stack_workers(jnp.zeros((2,), jnp.uint32))
        template = {"center": center, "pulled": pulled, "local": local,
                    "opt_state": opt_state, "last_seen": last_seen,
                    "global_count": global_count, "rng": rng}
        start_t, restored = self._maybe_resume(
            template,
            incompatible_hint=(
                "if this checkpoint predates step-granular DynSGD "
                "training state (round 3: no 'rng' leaf, step counted "
                "epochs not steps), restart training or point "
                "checkpoint_dir at a fresh directory"))
        if restored is not None:
            if "rng" not in restored:
                raise ValueError(
                    "checkpoint predates step-granular DynSGD training "
                    "state (no 'rng' leaf; its step counts epochs, not "
                    "steps) — restart training or point checkpoint_dir "
                    "at a fresh directory")
            center = restored["center"]
            pulled = restored["pulled"]
            local = restored["local"]
            opt_state = restored["opt_state"]
            last_seen = restored["last_seen"]
            global_count = restored["global_count"]
            rng = restored["rng"]

        def dispatch(i, T, steps_done, data):
            nonlocal center, pulled, local, opt_state, last_seen, \
                global_count, rng
            streamed = self._streamed
            fn = self._compiled(
                lambda: build_chunk(T, streamed=streamed),
                extra_key=("stream", T, spe) if streamed else (T, spe))
            (center, pulled, local, opt_state, last_seen, global_count,
             rng, losses) = fn(center, pulled, local, opt_state,
                               last_seen, global_count, rng, *data,
                               key, jnp.int32(steps_done))
            return losses

        # cadence kwargs stay in window units for API parity with the
        # family; the dispatch machinery runs in STEP units.  History
        # entries are (workers, T) per chunk; whole-epoch runs reshape
        # to (workers, epochs, steps), mid-epoch resumes stay flat.
        cadence = (self.checkpoint_every_windows * W
                   if self.checkpoint_every_windows
                   else self.checkpoint_every * spe
                   if self.checkpoint_every else None)
        history = run_chunked(
            self, xs, ys, start=start_t, total=total_t, per_epoch=spe,
            stream_units=(self.stream_chunk_windows * W
                          if self.stream_chunk_windows else None),
            cadence=cadence,
            samples_per_unit=self.num_workers * self.batch_size,
            dispatch=dispatch, sync_ref=lambda: center,
            state_fn=lambda: {"center": center, "pulled": pulled,
                              "local": local, "opt_state": opt_state,
                              "last_seen": last_seen,
                              "global_count": global_count, "rng": rng},
            carry_leaves=(center, pulled, local, opt_state, last_seen,
                          rng),
            fetch_global=comm.fetch_global)
        return self._finalize(center, history)
