"""Shared chunked-dispatch machinery for scan-based trainers.

Every distributed trainer has the same outer shape: a run of N scan units
(communication windows for the windowed family, steps for DynSGD) is cut
into dispatch chunks at the union of epoch boundaries, checkpoint-cadence
points and streaming data-chunk boundaries, then driven through a loop
that pipelines streamed chunks (depth 2, preserving the ChunkFeed's
two-buffer residency bound), syncs at boundaries, saves checkpoints
BEFORE user callbacks, and emits per-epoch metrics.  Round 3 had this
loop hand-written inside ``windowed.py``; hoisting it here lets DynSGD —
whose staggered-staleness schedule has the most state to lose on
preemption — share the identical cadence/resume/streaming semantics
instead of re-implementing (and subtly diverging from) them.

The reference analogue of the whole mechanism: a long-lived Spark worker
streams its partition through an iterator (workers.py:~60) while the
driver polls trained models per epoch (trainers.py:~360); there is no
single-dispatch fast path to preserve there because every batch is a
Python step.  Here the no-hooks case stays ONE compiled dispatch.
"""

from __future__ import annotations

import signal
import time

import numpy as np

from dist_keras_tpu.observability import events as obs_events
from dist_keras_tpu.observability import perf
from dist_keras_tpu.observability import spans as obs_spans
from dist_keras_tpu.resilience import coordination, preemption
from dist_keras_tpu.resilience.faults import fault_point
from dist_keras_tpu.resilience.guards import check_losses
from dist_keras_tpu.resilience.preemption import Preempted
from dist_keras_tpu.utils.sync import drain


def init_streaming(trainer, chunk, budget, name="stream_chunk_windows"):
    """Validate and install the streaming kwargs every streaming-capable
    trainer shares (one definition instead of a per-class copy)."""
    # None = off; anything else must be a positive int (0 raises like
    # every other out-of-range value rather than silently meaning "off")
    value = None if chunk is None else int(chunk)
    if value is not None and value < 1:
        raise ValueError(f"{name}={chunk} must be >= 1")
    setattr(trainer, name, value)
    trainer.max_resident_bytes = None if budget is None else int(budget)
    if trainer.max_resident_bytes is not None \
            and trainer.max_resident_bytes < 1:
        raise ValueError(f"max_resident_bytes={budget} must be >= 1")
    trainer._streamed = False  # set by train(); introspectable by tests


def scan_units(one_step, carry, xs, ys, T, t0, spe, streamed):
    """Scan ``one_step(carry, (t, x, y))`` over ``T`` global units
    starting at ``t0`` — the shared inner-scan shape of every flat-step
    trainer body.  Streamed mode consumes ``xs``/``ys`` directly as the
    scanned sequence (the chunk IS exactly its data, epoch-aligned by
    ``epoch_spans``); resident mode dynamically indexes the
    epoch-resident tensors at ``si = t % spe``."""
    import jax
    import jax.numpy as jnp

    ts = jnp.arange(T) + t0
    if streamed:
        return jax.lax.scan(one_step, carry, (ts, xs, ys))

    def indexed(c, t):
        si = t % spe
        x = jax.lax.dynamic_index_in_dim(xs, si, 0, keepdims=False)
        y = jax.lax.dynamic_index_in_dim(ys, si, 0, keepdims=False)
        return one_step(c, (t, x, y))

    return jax.lax.scan(indexed, carry, ts)


def reject_stale_checkpoint(restored, required_key, trainer, detail):
    """Raise the shared actionable error for a checkpoint written by a
    pre-step-granular version of ``trainer``.  Needed because
    pickle-fallback checkpoints restore without a template match, so the
    orbax-path structure error can't fire — the missing key is the only
    tell."""
    if restored is not None and required_key not in restored:
        raise ValueError(
            f"checkpoint predates step-granular {trainer} state "
            f"({detail}) — restart training or point checkpoint_dir at "
            "a fresh directory")


def chunk_plan(start, total, per_epoch, *, epoch_bounds=False,
               cadence=None, data_chunk=None):
    """Chunk sizes (in scan units) for the dispatch loop.

    - ``epoch_bounds``: cut at every epoch boundary (callbacks need
      on_epoch_end at real epoch ends).
    - ``cadence=N``: cut every N units counted from ``start`` (the
      resume point) — the checkpoint grid.
    - ``data_chunk=C``: streaming mode — cut at every epoch boundary
      AND every C-th unit within each epoch, aligned to the epoch start
      (NOT the resume point, so a resumed run reuses the identical
      chunk grid); each chunk's data is then one contiguous
      epoch-relative slice of <= C units, the ChunkFeed transfer unit.

    No hooks = one dispatch (the round-1 perf path).
    """
    remaining = total - start
    if remaining <= 0:
        return []
    bounds = {total}
    if epoch_bounds:
        first = (start // per_epoch + 1) * per_epoch
        bounds |= set(range(first, total, per_epoch))
    if cadence:
        bounds |= set(range(start + cadence, total, cadence))
    if data_chunk:
        # k=0 of the grid lands on every epoch boundary too
        for e in range(start // per_epoch, -(-total // per_epoch)):
            bounds |= {e * per_epoch + k
                       for k in range(0, per_epoch, data_chunk)
                       if start < e * per_epoch + k}
    cuts = sorted(b for b in bounds if start < b <= total)
    out, prev = [], start
    for b in cuts:
        out.append(b - prev)
        prev = b
    return out


def resolve_stream_chunk(requested, budget, per_device_epoch_bytes,
                         per_epoch):
    """-> effective streaming chunk size in scan units, or None.

    ``requested`` wins when set; otherwise ``budget`` (bytes of
    per-device data residency) auto-sizes a chunk so TWO in-flight
    chunks (executing + prefetched) fit inside it — only when the
    epoch tensor actually exceeds the budget.
    """
    C = requested
    if C is None and budget and per_device_epoch_bytes > budget:
        per_unit = max(1, per_device_epoch_bytes // per_epoch)
        C = max(1, budget // (2 * per_unit))
    if C:
        C = max(1, min(int(C), per_epoch))
    return C


def epoch_spans(plan, start, per_epoch):
    """Epoch-relative (offset, length) data slices, one per chunk."""
    u, spans = start, []
    for K in plan:
        spans.append((u % per_epoch, K))
        u += K
    return spans


def run_chunked(trainer, xs, ys, *, start, total, per_epoch, stream_units,
                cadence, samples_per_unit, dispatch, sync_ref, state_fn,
                carry_leaves, fetch_global):
    """The full chunked-dispatch recipe shared by the windowed family and
    DynSGD: streaming decision -> chunk plan -> feed-or-resident data
    setup (with the pre-clock drain) -> ChunkRunner -> history reshape.

    ``stream_units`` is the trainer's requested streaming chunk already
    converted to scan units (windows for the windowed family, steps for
    DynSGD); ``carry_leaves`` are the device carries whose distribution
    must complete before the clock starts.  Returns the history list:
    losses concatenated over chunks and reshaped to
    ``(workers, epochs, per_epoch, *rest)`` when the run covered whole
    epochs (a mid-epoch resume keeps its partial run flat — see
    ``Trainer.get_history``).
    """
    stream_C = resolve_stream_chunk(
        stream_units, trainer.max_resident_bytes,
        (xs.nbytes + ys.nbytes) // max(1, xs.shape[0]), per_epoch)
    trainer._streamed = bool(stream_C)
    plan = chunk_plan(start, total, per_epoch,
                      epoch_bounds=bool(trainer.callbacks),
                      cadence=cadence, data_chunk=stream_C)
    feed = None
    if stream_C:
        from dist_keras_tpu.data.feed import ChunkFeed

        feed = ChunkFeed(epoch_spans(plan, start, per_epoch),
                         trainer._put_worker_chunk, xs, ys)
        trainer._last_feed = feed  # test introspection
        # chunk 0's transfer and the carry state land OUTSIDE the clock,
        # like the resident path's one-shot H2D; chunks 1.. transfer
        # inside it, overlapped under the running dispatch (plan may be
        # empty: resume of an already-finished run)
        first = feed.get(0) if plan else ()
        drain(*carry_leaves, *first)
        resident = ()
    else:
        xs_d = trainer._to_device(xs)
        ys_d = trainer._to_device(ys)
        # data AND carry-state distribution completes OUTSIDE the clock
        drain(xs_d, ys_d, *carry_leaves)
        resident = (xs_d, ys_d)

    runner = ChunkRunner(
        trainer, plan=plan, start=start, total=total, per_epoch=per_epoch,
        samples_per_unit=samples_per_unit, cadence=cadence, feed=feed,
        fetch_global=fetch_global)
    all_losses = runner.run(dispatch, sync_ref=sync_ref, state_fn=state_fn,
                            resident_data=resident)
    if not all_losses:
        return []
    flat = np.concatenate(all_losses, axis=1)
    if flat.shape[1] % per_epoch == 0:
        flat = flat.reshape(flat.shape[0], -1, per_epoch, *flat.shape[2:])
    return flat.tolist()


class ChunkRunner:
    """Drives a chunk plan through dispatch/pipeline/sync/checkpoint.

    The trainer supplies closures:

    - ``dispatch(i, K, units_done, data) -> device losses`` — enqueue
      chunk i (the trainer reassigns its carry state inside);
    - ``sync_ref() -> pytree`` — what to ``drain`` at boundaries (the
      latest carry; per-device in-order execution makes it cover the
      whole chunk);
    - ``state_fn() -> dict`` — the checkpoint payload (lazy: only
      evaluated when a save is due).

    Timing: boundary-time host work (loss fetches, checkpoint I/O, user
    callbacks) happens between ``t_mark`` resets — off the clock, like
    the round-3 loop.  The ONE exception is the streamed path's mid-loop
    depth-2 backpressure retire: it blocks until the PREVIOUS chunk's
    compute finishes (so at most two chunks' data is ever
    device-resident), which is genuine training wall-time and is
    counted; the loss bytes it also fetches are KBs riding that same
    round trip.  A round-5 experiment replaced that in-window fetch with
    a ``drain`` probe + boundary-deferred fetch (equalizing the fetch
    convention with the resident path, as the round-4 advisor suggested)
    and it CRATERED the measured streaming parity 0.988 -> 0.637 on the
    tunnel backend: ``drain`` costs a probe DISPATCH (~50-190 ms tunnel
    latency) on top of the blocking round trip, per retire, inside the
    clock.  One blocking fetch is the cheapest correct barrier, so the
    fetch stays in-window (the documented conservative convention).
    """

    def __init__(self, trainer, *, plan, start, total, per_epoch,
                 samples_per_unit, cadence=None, feed=None,
                 fetch_global=None):
        self.tr = trainer
        self.plan = plan
        self.start = start
        self.total = total
        self.per_epoch = per_epoch
        self.samples_per_unit = samples_per_unit
        self.cadence = cadence
        self.feed = feed
        self._fetch = fetch_global or (lambda x: x)

    # checkpoint cadence in scan units; trainer._last_ckpt_epoch is the
    # unit count of the last save (set by _maybe_resume on restore)
    def _ckpt_due(self, units_done):
        if self.tr._checkpointer_or_none() is None:
            return False
        last = getattr(self.tr, "_last_ckpt_epoch", 0)
        cadence = self.cadence or self.total
        return units_done - last >= cadence or units_done >= self.total

    def _maybe_ckpt(self, units_done, state_fn):
        if self._ckpt_due(units_done):
            # async (DK_CKPT_ASYNC, default): only the host snapshot
            # runs here.  The returned handle is deliberately dropped —
            # the preempt boundary and the end-of-run drain wait
            # through Checkpointer.wait_until_finished, which covers
            # whatever write is in flight regardless of coalescing.
            # A PREVIOUS background failure re-raises out of save() at
            # this boundary — like a synchronous failure one cadence
            # late.  Rapid boundary saves coalesce latest-wins inside
            # the Checkpointer (bounded: one in flight + one pending).
            self.tr._checkpointer_or_none().save(units_done, state_fn())
            self.tr._last_ckpt_epoch = units_done

    def _drain_saves(self, raise_errors, timeout_s=None):
        """Wait (bounded by the coordination deadline, or an explicit
        ``timeout_s``) for any in-flight async save — a run leaving the
        dispatch loop must never leave a background writer racing a
        relaunched incarnation in the same checkpoint directory."""
        ckptr = self.tr._checkpointer_or_none()
        if ckptr is None:
            return
        ckptr.wait_until_finished(
            timeout_s=(coordination.default_timeout_s()
                       if timeout_s is None else timeout_s),
            raise_errors=raise_errors)

    def _preempt_save(self, units_done, state_fn, world=1):
        """Boundary checkpoint on a delivered SIGTERM/SIGINT — saved
        regardless of cadence (deduped against a save that already
        landed at this unit), so the restart loses nothing.  The None
        sentinel (vs the 0 default used by the cadence math) matters: a
        fresh run preempted before any save still writes its unit-0
        state, so ``Preempted.saved_step`` never claims a checkpoint
        that does not exist.

        The save is VERIFIED before the exit (single-host; on a pod the
        non-leaders return before the leader's promotion, so there is
        no committed step for them to probe yet): the whole point of
        the typed 128+signum exit is that the restart can stand on this
        exact checkpoint — a torn boundary save must surface as a typed
        ``CheckpointCorrupt`` NOW, not as a restore explosion in the
        relaunched incarnation.  ``Preempted.saved_step`` is therefore
        a *checked* claim.  (Skipped under ``DK_CKPT_VERIFY=0``: no
        manifest was written, ``verify`` reports a soft
        "unverifiable".)"""
        ckptr = self.tr._checkpointer_or_none()
        if ckptr is None:
            return None
        # the async pipeline must not stretch the SIGTERM→exit window:
        # the boundary save (and any still-in-flight cadence save it
        # coalesced behind) is waited on with a bounded deadline —
        # Preempted is only raised once the bytes are promoted, so
        # saved_step stays a checked claim under DK_CKPT_ASYNC too
        deadline = coordination.default_timeout_s()
        if getattr(self.tr, "_last_ckpt_epoch", None) != units_done:
            handle = ckptr.save(units_done, state_fn())
            self.tr._last_ckpt_epoch = units_done
            handle.wait(timeout_s=deadline)
        else:
            # a cadence save of this exact unit may still be in flight
            ckptr.wait_until_finished(timeout_s=deadline)
        if world == 1:
            ckptr.verify(units_done)
        return units_done

    def run(self, dispatch, sync_ref, state_fn, resident_data=()):
        tr = self.tr
        all_losses, acc_losses = [], []
        acc_dt, acc_samples = 0.0, 0
        units_done = self.start
        self._halt = False  # set by the NaN sentinel under policy "halt"
        # pipelined in-flight chunks whose losses are not yet fetched
        pending = []  # [(chunk_idx, device losses, units when done)]

        def _retire_one():
            # the blocking fetch doubles as the backpressure barrier —
            # see the class docstring for why a drain + deferred fetch
            # is NOT cheaper here.  perf attribution: the fetch wall is
            # the host-side "step" phase (it blocks on the dispatched
            # compute) and the fetched bytes are the D2H proxy row; the
            # step.loss fault stays INSIDE the phase so an injected
            # delay (gates.py --watchdog-only) reads as a slow step.
            j, lj, units_after = pending.pop(0)
            with perf.phase("step"):
                t_fetch = time.perf_counter()
                arr = np.asarray(self._fetch(lj))  # blocks: chunk j done
                perf.d2h(arr.nbytes, time.perf_counter() - t_fetch)
                # deterministic NaN injection rides the fetched host
                # array (device math untouched) — the nan_policy hook
                arr = fault_point("step.loss", value=arr)
            if self.feed is not None:
                self.feed.release(j)
            all_losses.append(arr)
            acc_losses.append(arr)
            # the sentinel: count NaN/Inf, apply the trainer's policy
            # ("raise" aborts HERE — before any boundary save can
            # persist post-divergence state; "halt" drains and stops)
            if check_losses(tr, arr, units_done=units_after):
                self._halt = True

        # graceful preemption window: handlers only set a flag; the loop
        # notices it at the next chunk boundary below.  Off the main
        # thread there is no graceful window (strict=False) — signal
        # handlers are main-thread-only, the run proceeds uninstalled.
        installed = (tr.handle_preemption
                     and preemption.install(strict=False))
        # cluster consensus (tentpole, ISSUE 2): on a pod the SIGTERM
        # reaches hosts at different instants, so a LOCAL flag is not
        # enough — every chunk boundary piggybacks an any_flag vote, and
        # all hosts agree on one save step and exit Preempted together.
        # Single-process this is the trivial LocalCoordinator (its only
        # cost is the "coord.flag" fault point's dict lookup, which is
        # also what makes the whole path injectable without a cluster).
        # The coordinator is resolved REGARDLESS of handle_preemption:
        # the NaN-halt verdict below must be cluster-wide on ANY
        # multi-host run — a host halting alone would strand its peers'
        # next two-phase save against a marker that never comes.  Only
        # the preemption VOTE is gated on handle_preemption (a config
        # every host shares, so the collective op order stays SPMD).
        coord = coordination.get_coordinator()
        # perf attribution (observability.perf): retrace listener on,
        # phases + dispatch counts below — always-on host-side proxies
        # for the device-only perf story (one flag check when already
        # installed)
        perf.install()
        tr.record_training_start()
        t_mark = time.time()
        # the run's ROOT span: every per-chunk breadcrumb, coordination
        # vote and checkpoint event below auto-stamps its trace identity
        # (and the async writer's ckpt.save span resumes it), so a whole
        # training run stitches into one trace — on a launched pod,
        # DK_TRACE_ID makes that trace span every host.  Entered/exited
        # manually: the existing try/except/finally unwind structure
        # must stay byte-identical.
        _run_span = obs_spans.span("train.run", start=self.start)
        _run_span.__enter__()
        try:
            for i, K in enumerate(self.plan):
                sig = (preemption.requested()
                       if tr.handle_preemption else None)
                # did THIS host's OS deliver the signal?  (vs adopting
                # it from the vote below) — the report uses this to
                # attribute the preemption to the right rank
                signalled = sig is not None
                if tr.handle_preemption:
                    # boundary vote: did ANY host see the signal?  A
                    # host whose own flag is clear adopts SIGTERM — its
                    # scheduler's signal is merely in flight.
                    with perf.phase("comm"):
                        voted = coord.any_flag(sig is not None)
                    if voted:
                        sig = signal.SIGTERM if sig is None else sig
                    if sig is not None and coord.world > 1:
                        with perf.phase("comm"):
                            agreed = coord.agree_min(units_done)
                        if agreed != units_done:  # pragma: no cover
                            # identical plans + the same vote boundary
                            # make this impossible unless hosts diverged
                            # (NOT a lost peer — PeerLost is reserved
                            # for heartbeat-proven deaths)
                            raise RuntimeError(
                                f"coordinated save step disagreement: "
                                f"this host at {units_done}, cluster "
                                f"min {agreed} — hosts ran different "
                                "chunk plans")
                if sig is not None:
                    # checkpoint at the boundary, then exit 128+signum
                    # (Preempted is a SystemExit) so the scheduler
                    # restarts with resume=True.  The drain can trip the
                    # NaN sentinel ("raise" aborts inside _retire_one;
                    # "halt" sets the flag) — a halted run's diverged
                    # state must NOT be persisted here either.
                    # (this is also where the preemption SIGNAL becomes
                    # an event: the handler itself must not emit — see
                    # preemption._handler — so the boundary that notices
                    # the flag stamps signum + where the run was.
                    # adopted=True marks a host that only learned of the
                    # signal through the vote: the report attributes the
                    # preemption to the non-adopted rank(s) only)
                    obs_events.emit("preempt", signum=int(sig),
                                    units_done=units_done,
                                    adopted=not signalled)
                    # crash-safe tail: the grace window may not survive
                    # the drain+save below, so the recorder dumps NOW —
                    # the post-mortem exists even if the scheduler's
                    # second SIGTERM lands mid-checkpoint
                    if obs_events.enabled():
                        from dist_keras_tpu.observability import flight
                        flight.dump("preempt", signum=int(sig),
                                    units_done=units_done)
                    while pending:
                        _retire_one()
                    if coord.world > 1:
                        # the halt verdict must be CLUSTER-wide too: a
                        # NaN seen by one host only would otherwise make
                        # it skip the save while its peers enter the
                        # two-phase commit — the leader would then wait
                        # out the whole deadline on a marker that never
                        # comes.  Either every host saves or none does.
                        self._halt = coord.any_flag(self._halt)
                    with perf.phase("ckpt"):
                        saved = (None if self._halt
                                 else self._preempt_save(
                                     units_done, state_fn,
                                     world=coord.world))
                    if coord.world > 1:
                        # every host's save (incl. the leader's
                        # promotion) lands before ANY host exits — the
                        # scheduler restarts a pod whose checkpoint is
                        # fully committed, never torn
                        with perf.phase("comm"):
                            coord.barrier("preempt_exit")
                    obs_events.emit("preempt_exit", signum=int(sig),
                                    saved_step=saved)
                    # the run ENDED here: stamp the wall clock (the
                    # trained-time answer is truthful — training
                    # stopped at this boundary) — which also writes
                    # the leader's merged report.txt; the flagship
                    # post-mortem artifact must exist precisely for
                    # ABNORMAL exits, not only clean completions
                    tr.record_training_end()
                    raise Preempted(sig, saved_step=saved)
                with perf.phase("data"):
                    data = (self.feed.get(i) if self.feed is not None
                            else resident_data)
                with perf.phase("step"):
                    losses = dispatch(i, K, units_done, data)
                perf.count_dispatch()
                units_done += K
                # per-CHUNK (not per-step — steps live inside the
                # compiled scan) breadcrumb: the last of these in a
                # host's log is where a hung run stopped
                obs_events.emit("chunk", i=i, units=K,
                                units_done=units_done,
                                streamed=self.feed is not None)
                pending.append((i, losses, units_done))
                if self.feed is not None:
                    # retire the previous chunk BEFORE prefetching the
                    # next: at most two chunks' data is ever
                    # device-resident, and the i+1 transfer still
                    # overlaps chunk i's execution
                    while len(pending) > 1:
                        _retire_one()
                    with perf.phase("data"):
                        self.feed.prefetch(i + 1)
                multi = coord.world > 1
                # multi-host: a locally-tripped halt must NOT cut a
                # boundary only this host sees — every consensus op has
                # to happen at the same loop position on every host
                # (SPMD discipline), so halt waits for the next NATURAL
                # boundary and is put to a cluster vote there
                boundary = (units_done % self.per_epoch == 0
                            or i == len(self.plan) - 1
                            or self._ckpt_due(units_done)
                            or (self._halt and not multi))
                acc_samples += self.samples_per_unit * K
                if not boundary:
                    continue
                with perf.phase("step"):
                    drain(sync_ref())  # block_until_ready lies via tunnel
                acc_dt += time.time() - t_mark
                # host-side work below (loss fetches, checkpoint I/O,
                # user callbacks) stays OUTSIDE the clock
                while pending:
                    _retire_one()
                if multi:
                    # cluster halt verdict: one host's NaN halts the
                    # whole pod together (or nobody) — an uncoordinated
                    # break here would leave the peers blocking in their
                    # next vote until the deadline
                    with perf.phase("comm"):
                        self._halt = coord.any_flag(self._halt)
                # save BEFORE user callbacks run: a callback that dies
                # (preemption simulation) must not lose the chunk — but
                # NEVER persist a halted (diverged) run's state
                if not self._halt:
                    with perf.phase("ckpt"):
                        self._maybe_ckpt(units_done, state_fn)
                if units_done % self.per_epoch == 0:
                    tr._emit_epoch_end(
                        units_done // self.per_epoch,
                        np.concatenate(acc_losses, axis=1),
                        acc_dt, acc_samples)
                    acc_losses, acc_dt, acc_samples = [], 0.0, 0
                if self._halt:
                    obs_events.emit("nan_halt", units_done=units_done)
                    # halting mid-epoch: emit the partial epoch too
                    # (numbered as the epoch in progress) so the
                    # nonfinite ledger reaches trainer.metrics — a
                    # monitor reading metrics must see WHY the run
                    # stopped early, not a clean truncation
                    if acc_losses:
                        tr._emit_epoch_end(
                            units_done // self.per_epoch + 1,
                            np.concatenate(acc_losses, axis=1),
                            acc_dt, acc_samples)
                    break
                t_mark = time.time()
        # dklint: ignore[broad-except] re-raised immediately — this arm
        # only drains the async writer on the UNWIND path (bounded,
        # no-raise, so the original exception is never masked); the
        # clean path drains exactly once inside record_training_end
        # below (a double drain would double the worst-case stall on a
        # wedged writer).  An unwinding run must not leave a background
        # writer racing a relaunched incarnation in the same directory.
        except BaseException as e:
            # a TimeoutError unwinding here means a handle wait ALREADY
            # burned one full deadline against this same wedged writer
            # (_preempt_save) — paying a second would double the
            # SIGTERM→exit stall the preemption contract bounds; a
            # zero-timeout probe keeps the no-zombie intent for the
            # wedged case without the second wait
            self._drain_saves(
                raise_errors=False,
                timeout_s=0 if isinstance(e, TimeoutError) else None)
            raise
        finally:
            _run_span.__exit__(None, None, None)
            # exception-safe (a raising user callback must not leave
            # the feed pinning the host epoch tensors)
            if self.feed is not None:
                self.feed.close()
            if installed:
                preemption.restore()
        tr.record_training_end()
        # the CLEAN-path error surface: record_training_end already
        # drained (no-raise — it also runs right before `raise
        # Preempted` — and it paid the one bounded deadline).  This
        # zero-timeout probe only CLASSIFIES that outcome: it raises
        # the deferred background-save error, or TimeoutError for a
        # writer still wedged past the deadline, without waiting a
        # second one.  A completed run must fail exactly like a
        # synchronous save raising at the last boundary.
        ckptr = tr._checkpointer_or_none()
        if ckptr is not None:
            ckptr.wait_until_finished(timeout_s=0, raise_errors=True)
        return all_losses
