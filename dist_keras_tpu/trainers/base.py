"""Trainer base classes — parity with ``distkeras/trainers.py``.

``Trainer`` (trainers.py:~35) holds the serialized model + loss + worker
optimizer, records wall-clock training time (``record_training_start/stop``,
trainers.py:~60) and exposes ``get_history()`` / ``get_training_time()``.

``DistributedTrainer`` (trainers.py:~290) adds ``num_workers`` and the mesh
(the TPU stand-in for the Spark executor pool + parameter-server service:
``start_service``/``stop_service`` became "construct a Mesh").  The
``master_port``/``master_host`` kwargs of the reference are accepted and
ignored — there is no socket server to bind; the exchange compiles into ICI
collectives (see parallel/collectives.py).
"""

from __future__ import annotations

import time

import jax
import numpy as np

from dist_keras_tpu.ops.losses import get_loss
from dist_keras_tpu.ops.optimizers import get_optimizer
from dist_keras_tpu.parallel.mesh import worker_mesh
from dist_keras_tpu.utils.serialization import deserialize_model, serialize_model


class Trainer:
    def __init__(self, keras_model, loss="categorical_crossentropy",
                 worker_optimizer="adam", optimizer_kwargs=None,
                 features_col="features", label_col="label",
                 batch_size=32, num_epoch=1, seed=0, compute_dtype=None):
        self.serialized_model = serialize_model(keras_model)
        self.loss = loss
        self.worker_optimizer = worker_optimizer
        self.optimizer_kwargs = dict(optimizer_kwargs or {})
        self.features_col = features_col
        self.label_col = label_col
        self.batch_size = int(batch_size)
        self.num_epoch = int(num_epoch)
        self.seed = int(seed)
        self.compute_dtype = compute_dtype
        self.history = []
        self._t_start = None
        self._t_stop = None

    # ---- timing (trainers.py:~60) ----
    def record_training_start(self):
        self._t_start = time.time()

    def record_training_end(self):
        self._t_stop = time.time()

    def get_training_time(self):
        if self._t_start is None or self._t_stop is None:
            return 0.0
        return self._t_stop - self._t_start

    def get_history(self):
        """Per-step training losses.

        Shapes by trainer: SingleTrainer -> (steps,); AveragingTrainer /
        EnsembleTrainer -> (workers, epochs, steps); windowed family
        (DOWNPOUR/ADAG/AEASGD/EAMSGD) -> (workers, epochs, windows, W);
        DynSGD -> (workers, epochs, steps).
        """
        return self.history

    def get_averaged_history(self):
        return float(np.mean(np.asarray(self.history))) if len(
            np.ravel(self.history)) else float("nan")

    # ---- compiled-program cache ----
    # XLA compilation is expensive (tens of seconds through a remote-compile
    # tunnel); trainers with equal configuration produce identical traced
    # programs, so the jitted callables are shared process-wide.  Shape/dtype
    # changes are handled by jit's own retracing — the key only carries what
    # changes the *structure* of the traced program.  LRU-bounded: cached
    # builder closures pin model params, so unbounded growth would leak a
    # weight copy per hyperparameter-sweep point.
    _jit_cache = {}
    _jit_cache_max = 32
    # Non-string key components are tokened by id(); pin them so a GC'd
    # object's address can never be reused by a different config.
    _id_pins = []

    def _cache_extras(self):
        """Subclass hook: hyperparameters baked into the trace."""
        return ()

    def _cache_key(self):
        def _tok(v):
            if isinstance(v, str):
                return v
            Trainer._id_pins.append(v)
            return f"obj:{id(v)}"

        # num_epoch is deliberately absent: trainers that bake the epoch
        # count into the trace (epoch-scan) add it via _cache_extras;
        # trainers that loop epochs on the host must share executables
        # across different epoch counts.
        return (type(self).__name__,
                self.serialized_model["model"],
                _tok(self.loss), _tok(self.worker_optimizer),
                tuple(sorted(self.optimizer_kwargs.items())),
                str(self.compute_dtype),
                self._cache_extras())

    def _compiled(self, builder):
        key = self._cache_key()
        cache = Trainer._jit_cache
        fn = cache.pop(key, None)
        if fn is None:
            fn = builder()
            while len(cache) >= Trainer._jit_cache_max:
                cache.pop(next(iter(cache)))  # evict least recently used
        cache[key] = fn  # (re)insert at the back = most recent
        return fn

    # ---- shared plumbing ----
    def _fresh_model(self):
        return deserialize_model(self.serialized_model)

    def _resolve(self):
        """-> (model, loss_fn, optimizer transform)."""
        model = self._fresh_model()
        return (model, get_loss(self.loss),
                get_optimizer(self.worker_optimizer, **self.optimizer_kwargs))

    def _finalize(self, params, history):
        """Install trained params into a fresh model; record history."""
        self.history = history
        model = self._fresh_model()
        model.set_params(jax.tree.map(np.asarray, params))
        return model

    def train(self, dataset, shuffle=False):
        raise NotImplementedError


class DistributedTrainer(Trainer):
    """Base for every multi-worker trainer (trainers.py:~290)."""

    def __init__(self, keras_model, num_workers=2, master_host=None,
                 master_port=5000, mesh=None, **kw):
        super().__init__(keras_model, **kw)
        self.num_workers = int(num_workers)
        # master_host/master_port: reference PS kwargs, accepted for parity.
        del master_host, master_port
        self._mesh = mesh

    def _cache_extras(self):
        custom = id(self._mesh) if self._mesh is not None else None
        return (self.num_workers, custom)

    @property
    def mesh(self):
        if self._mesh is None:
            self._mesh = worker_mesh(self.num_workers)
        return self._mesh

    def _shards(self, dataset):
        return dataset.worker_shards(
            self.num_workers, self.batch_size,
            features_col=self.features_col, label_col=self.label_col)
