"""Trainer base classes — parity with ``distkeras/trainers.py``.

``Trainer`` (trainers.py:~35) holds the serialized model + loss + worker
optimizer, records wall-clock training time (``record_training_start/stop``,
trainers.py:~60) and exposes ``get_history()`` / ``get_training_time()``.

``DistributedTrainer`` (trainers.py:~290) adds ``num_workers`` and the mesh
(the TPU stand-in for the Spark executor pool + parameter-server service:
``start_service``/``stop_service`` became "construct a Mesh").  The
``master_port``/``master_host`` kwargs of the reference are accepted and
ignored — there is no socket server to bind; the exchange compiles into ICI
collectives (see parallel/collectives.py).
"""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np

from dist_keras_tpu.ops.losses import get_loss
from dist_keras_tpu.ops.optimizers import get_optimizer
from dist_keras_tpu.parallel.mesh import worker_mesh
from dist_keras_tpu.utils.serialization import deserialize_model, serialize_model


class Trainer:
    def __init__(self, keras_model, loss="categorical_crossentropy",
                 worker_optimizer="adam", optimizer_kwargs=None,
                 features_col="features", label_col="label",
                 batch_size=32, num_epoch=1, seed=0, compute_dtype=None,
                 data_dtype=np.float32,
                 checkpoint_dir=None, checkpoint_every=None,
                 max_checkpoints=3, resume=False, callbacks=None,
                 nan_policy="raise", handle_preemption=False):
        self.serialized_model = serialize_model(keras_model)
        self.loss = loss
        self.worker_optimizer = worker_optimizer
        self.optimizer_kwargs = dict(optimizer_kwargs or {})
        self.features_col = features_col
        self.label_col = label_col
        self.batch_size = int(batch_size)
        self.num_epoch = int(num_epoch)
        self.seed = int(seed)
        self.compute_dtype = compute_dtype
        # dtype the host batches are materialized (and H2D-shipped) in;
        # None keeps the dataset columns' native dtypes — uint8 images
        # then transfer at 1/4 the float32 volume and the train step
        # casts on-device (cast-late, like the reference's uint8 MNIST
        # feed).  float32 default = the round-1..3 behavior.
        self.data_dtype = data_dtype
        # ---- mid-training hooks (beyond the reference: SURVEY §5 owes
        # checkpoint/resume + structured metrics) ----
        self.checkpoint_dir = checkpoint_dir
        self.checkpoint_every = (int(checkpoint_every)
                                 if checkpoint_every else None)
        if self.checkpoint_every and not self.checkpoint_dir:
            raise ValueError(
                "checkpoint_every requires checkpoint_dir (otherwise the "
                "dispatch would be chunked but nothing ever saved)")
        if self.checkpoint_dir and self.checkpoint_every is None:
            self.checkpoint_every = 1
        self.max_checkpoints = int(max_checkpoints)
        # resume: False = fresh run; True = continue from the latest
        # (verified — restore() falls back past a corrupt step) step;
        # an INT = continue from exactly that step.  The explicit form
        # is what the auto-resume supervisor passes: its fn receives
        # the latest VERIFIED step as resume_step and hands it straight
        # to Trainer(resume=resume_step), so the relaunch provably
        # consumes the agreed units_done instead of whatever the
        # directory happens to hold by the time the trainer starts.
        if isinstance(resume, bool) or resume is None:
            self.resume = bool(resume)
        else:
            self.resume = int(resume)
        self.callbacks = list(callbacks or [])
        # ---- resilience (round 6) ----
        # nan_policy: what the loss sentinel does on NaN/Inf —
        # "raise" (default: abort BEFORE the boundary checkpoint, so the
        # last save predates the divergence), "skip" (device-side guard:
        # a non-finite step keeps the previous params/opt state), "halt"
        # (stop dispatching at the boundary, return what trained), or
        # None/"off" (count only).  Counted per epoch in
        # metrics[...]["nonfinite_steps"] either way.
        from dist_keras_tpu.resilience.guards import normalize_policy

        self.nan_policy = normalize_policy(nan_policy)
        # handle_preemption: install SIGTERM/SIGINT handlers around the
        # dispatch loop; on delivery, checkpoint at the next chunk
        # boundary and raise resilience.Preempted (exit code 128+signum)
        self.handle_preemption = bool(handle_preemption)
        self.nonfinite_steps = 0   # cumulative non-finite loss entries
        self._nonfinite_emitted = 0
        self.metrics = []  # per-epoch {"epoch", "mean_loss", ...}
        self._checkpointer = None
        self.history = []
        self._t_start = None
        self._t_stop = None

    # ---- timing (trainers.py:~60) ----
    def record_training_start(self):
        self._t_start = time.time()
        from dist_keras_tpu.observability import events, timeseries

        events.emit("train_start", trainer=type(self).__name__,
                    num_epoch=self.num_epoch,
                    batch_size=self.batch_size)
        # live-telemetry plane: with DK_OBS_SAMPLE_S set this arms the
        # per-process MetricsSampler (time-series rings + anomaly
        # watchdog) and the DK_METRICS_PORT Prometheus exporter; one
        # env read when unset.  Deliberately NOT stopped at train end —
        # the series/watchdog keep covering whatever the process does
        # next (another train, a serving phase), like the registry.
        timeseries.maybe_start_sampler()

    def record_training_end(self):
        # drain any in-flight async checkpoint save FIRST (bounded by
        # the coordination deadline) so a completed train() leaves its
        # last cadence save promoted — but NEVER raise from here: this
        # is the post-mortem stamper, and it runs on the preempt/halt
        # path right before `raise Preempted` (a raise would replace
        # the typed 128+signum exit and skip the report.txt that must
        # exist precisely for abnormal exits).  The CLEAN path
        # surfaces deferred background-save errors one line later, in
        # ChunkRunner.run's post-record drain.
        ckptr = getattr(self, "_checkpointer", None)
        if ckptr is not None:
            from dist_keras_tpu.resilience.coordination import (
                default_timeout_s,
            )

            ckptr.wait_until_finished(timeout_s=default_timeout_s(),
                                      raise_errors=False)
        self._t_stop = time.time()
        from dist_keras_tpu.observability import events, timeseries

        events.emit("train_end", trainer=type(self).__name__,
                    seconds=self.get_training_time())
        # the sampler keeps running (see record_training_start), but
        # the watchdog must learn this quiet is COMPLETION: without a
        # quiesce, the dispatch counter stopping at train end reads as
        # a throughput stall and pages the operator for a run that
        # succeeded
        sampler = timeseries.get_sampler()
        if sampler is not None and sampler.watchdog is not None:
            sampler.watchdog.quiesce()
        # leader-side merged report: when the obs dir is shared
        # storage, rank 0 leaves report.txt next to the logs at run
        # end — the post-hoc CLI remains for collected/per-host dirs.
        # Best-effort like every emit: telemetry must not kill a run
        # that just finished training.
        if events.rank() == 0:
            try:
                from dist_keras_tpu.observability import report

                report.write_report(events.obs_dir())
            # dklint: ignore[broad-except] best-effort report write on the way out of training
            except Exception:  # pragma: no cover - fs failure
                pass

    def get_training_time(self):
        if self._t_start is None or self._t_stop is None:
            return 0.0
        return self._t_stop - self._t_start

    def get_history(self):
        """Per-step training losses.

        Shapes by trainer: SingleTrainer -> (steps,); AveragingTrainer ->
        (workers, epochs, steps); EnsembleTrainer -> (num_models, epochs,
        steps); windowed family (DOWNPOUR/ADAG/AEASGD/EAMSGD) ->
        (workers, epochs, windows, W) — except a run RESUMED mid-epoch
        (``checkpoint_every_windows``), whose partial first epoch makes
        its own losses (workers, windows_run, W); DynSGD -> (workers,
        epochs, steps).
        """
        return self.history

    def get_averaged_history(self):
        return float(np.mean(np.asarray(self.history))) if len(
            np.ravel(self.history)) else float("nan")

    # ---- compiled-program cache ----
    # XLA compilation is expensive (tens of seconds through a remote-compile
    # tunnel); trainers with equal configuration produce identical traced
    # programs, so the jitted callables are shared process-wide.  Shape/dtype
    # changes are handled by jit's own retracing — the key only carries what
    # changes the *structure* of the traced program.  LRU-bounded: cached
    # builder closures pin model params, so unbounded growth would leak a
    # weight copy per hyperparameter-sweep point.
    _jit_cache = {}
    _jit_cache_max = 32
    # Non-string key components are tokened by id(); pin them (dict keyed
    # by id, so repeated _cache_key calls — e.g. once per epoch chunk —
    # never duplicate) so a GC'd object's address can never be reused by a
    # different config.  Pins are refcounted per CACHE KEY and released
    # when eviction drops the last key referencing them, so a long
    # hyperparameter sweep can't leak one pinned object per point.
    _id_pins = {}
    _id_pin_refs = {}

    def _cache_extras(self):
        """Subclass hook: hyperparameters baked into the trace."""
        return ()

    def _cache_key(self):
        def _tok(v):
            if isinstance(v, str):
                return v
            Trainer._id_pins[id(v)] = v
            return f"obj:{id(v)}"

        # num_epoch is deliberately absent: trainers that bake the epoch
        # count into the trace (epoch-scan) add it via _cache_extras;
        # trainers that loop epochs on the host must share executables
        # across different epoch counts.
        # nan_policy="skip" compiles a different step (finite-guarded
        # update); the other policies are host-side and share executables
        return (type(self).__name__,
                self.serialized_model["model"],
                _tok(self.loss), _tok(self.worker_optimizer),
                tuple(sorted(self.optimizer_kwargs.items())),
                str(self.compute_dtype),
                self.nan_policy == "skip",
                self._cache_extras())

    @staticmethod
    def _key_obj_ids(key):
        """ids of every ``obj:<id>`` token inside a (nested) cache key."""
        out = []

        def walk(t):
            if isinstance(t, tuple):
                for e in t:
                    walk(e)
            elif isinstance(t, str) and t.startswith("obj:"):
                out.append(int(t[4:]))

        walk(key)
        return out

    def _compiled(self, builder, extra_key=()):
        key = self._cache_key() + tuple(extra_key)
        cache = Trainer._jit_cache
        refs, pins = Trainer._id_pin_refs, Trainer._id_pins
        fn = cache.pop(key, None)
        if fn is None:
            try:
                fn = builder()
            # dklint: ignore[broad-except] a failed jit build must drop its key pins, then re-raise
            except Exception:
                # _cache_key's _tok pinned the key's objects into
                # _id_pins before the lookup; a failed build never gets
                # a refcount, so drop any pin no live key refcounts or
                # it leaks for the process lifetime
                for i in Trainer._key_obj_ids(key):
                    if i not in refs:
                        pins.pop(i, None)
                raise
            for i in Trainer._key_obj_ids(key):  # new key: pin its objs
                refs[i] = refs.get(i, 0) + 1
            while len(cache) >= Trainer._jit_cache_max:
                old_key = next(iter(cache))  # evict least recently used
                cache.pop(old_key)
                for i in Trainer._key_obj_ids(old_key):
                    n = refs.get(i, 1) - 1
                    if n <= 0:  # last key using this obj: unpin it
                        refs.pop(i, None)
                        pins.pop(i, None)
                    else:
                        refs[i] = n
        cache[key] = fn  # (re)insert at the back = most recent
        return fn

    # ---- epoch chunking / checkpoint / callbacks ----------------------
    # The whole num_epoch run compiles into ONE dispatch when no hooks are
    # requested (fastest path, round-1 behavior).  checkpoint_every=K
    # chunks the dispatch at K-epoch boundaries; any registered callback
    # forces per-epoch chunks so on_epoch_end really fires every epoch.
    def _chunk_plan(self, start_epoch=0):
        remaining = self.num_epoch - start_epoch
        if remaining <= 0:
            return []
        if self.callbacks:
            size = 1
        elif self.checkpoint_every:
            size = min(self.checkpoint_every, remaining)
        else:
            size = remaining
        chunks = [size] * (remaining // size)
        if remaining % size:
            chunks.append(remaining % size)
        return chunks

    def _checkpointer_or_none(self):
        if self.checkpoint_dir and self._checkpointer is None:
            from dist_keras_tpu.checkpoint import Checkpointer

            self._checkpointer = Checkpointer(
                self.checkpoint_dir, max_to_keep=self.max_checkpoints)
        return self._checkpointer

    def _maybe_resume(self, template, incompatible_hint=None):
        """-> (start_epoch, restored_state | None).

        ``incompatible_hint``: actionable message appended when the
        restore fails on a template/checkpoint structure mismatch (e.g.
        a round-3 checkpoint without the round-4 'rng' leaf — orbax
        raises its own opaque tree error long before a key check on the
        restored dict could run)."""
        ckptr = self._checkpointer_or_none()
        # resume=0 is an EXPLICIT step (the supervisor's resume_step can
        # legitimately be the unit-0 preemption save), so the gate tests
        # identity against False, not truthiness
        if self.resume is False or ckptr is None:
            return 0, None
        explicit = None if self.resume is True else int(self.resume)
        if explicit is None and ckptr.latest_step() is None:
            return 0, None
        from dist_keras_tpu.checkpoint import CheckpointCorrupt

        try:
            step, state = ckptr.restore(step=explicit, template=template)
        except (OSError, CheckpointCorrupt):
            # NOT wrapped in ValueError: the auto-resume supervisor
            # classifies ValueError as a never-retried config mistake,
            # but a transient I/O error is the one failure mode the
            # self-healing layer exists to absorb, and CheckpointCorrupt
            # is its typed verdict — laundering either into ValueError
            # would turn a retryable restart into a permanent giveup
            raise
        # dklint: ignore[broad-except] re-raised (with an actionable incompatible-checkpoint hint)
        except Exception as e:
            if incompatible_hint:
                raise ValueError(
                    f"checkpoint restore failed ({type(e).__name__}); "
                    f"{incompatible_hint}") from e
            raise
        # the RETURNED step is authoritative — a verified fallback may
        # have restored an earlier step than requested, and the cadence
        # counter below plus the dispatch start must follow the state
        # actually loaded, not the step asked for
        from dist_keras_tpu.observability import events

        events.emit("resume", step=int(step),
                    requested=explicit, trainer=type(self).__name__)
        self._last_ckpt_epoch = int(step)
        return int(step), state

    # (the cadence-save implementation lives in ChunkRunner._maybe_ckpt
    # — every trainer routes through the chunked dispatch loop, which
    # also owns the async-handle drain/error-surfacing scaffolding; a
    # second copy here would silently drop AsyncSaveHandles)

    def _emit_epoch_end(self, epochs_done, losses, seconds, samples):
        """Record structured per-epoch metrics; fire callbacks.

        Under nan_policy="skip" — and ONLY there — ``mean_loss``
        averages the finite losses: one exploding batch must not poison
        the epoch's metric (and any loss-watching callback) after the
        step itself was correctly skipped.  Every other policy keeps the
        plain mean, so with the sentinel opted out (None) a divergence
        still surfaces as a NaN mean_loss exactly as before round 6;
        the non-finite count is reported alongside either way."""
        arr = np.asarray(losses, dtype=np.float64)
        if self.nan_policy == "skip" and arr.size:
            arr = arr[np.isfinite(arr)]
        logs = {
            "epoch": epochs_done,
            "mean_loss": float(np.mean(arr)) if arr.size else
            float("nan"),
            "seconds": float(seconds),
            "samples_per_sec": float(samples / seconds) if seconds > 0
            else float("nan"),
            # non-finite loss entries seen since the previous emit (the
            # NaN sentinel's per-epoch ledger; cumulative total lives on
            # trainer.nonfinite_steps)
            "nonfinite_steps": self.nonfinite_steps
            - self._nonfinite_emitted,
        }
        self._nonfinite_emitted = self.nonfinite_steps
        self.metrics.append(logs)
        # the epoch boundary is the natural telemetry cadence: one
        # typed event carrying the epoch record, plus a snapshot of the
        # process-wide metrics registry riding the same stream (both
        # no-ops when DK_OBS_DIR is unset)
        from dist_keras_tpu.observability import events
        from dist_keras_tpu.observability import metrics as obs_metrics

        events.emit("epoch_end", trainer=type(self).__name__, **logs)
        obs_metrics.emit_snapshot(epoch=epochs_done)
        for cb in self.callbacks:
            hook = getattr(cb, "on_epoch_end", cb)
            hook(self, epochs_done, logs)

    # ---- shared plumbing ----
    def _fresh_model(self):
        return deserialize_model(self.serialized_model)

    def _resolve(self):
        """-> (model, loss_fn, optimizer transform)."""
        model = self._fresh_model()
        return (model, get_loss(self.loss),
                get_optimizer(self.worker_optimizer, **self.optimizer_kwargs))

    def _make_step(self, model, loss_fn, tx):
        """``make_model_step`` with this trainer's NaN policy compiled in
        — the single seam every trainer family builds its step through,
        so ``nan_policy="skip"`` guards all of them identically."""
        from dist_keras_tpu.trainers.step import make_model_step

        return make_model_step(
            model, loss_fn, tx, self.compute_dtype,
            skip_nonfinite=(self.nan_policy == "skip"))

    def _finalize(self, params, history):
        """Install trained params into a fresh model; record history."""
        self.history = history
        model = self._fresh_model()
        model.set_params(jax.tree.map(np.asarray, params))
        return model

    def train(self, dataset, shuffle=False):
        raise NotImplementedError


class DistributedTrainer(Trainer):
    """Base for every multi-worker trainer (trainers.py:~290)."""

    def __init__(self, keras_model, num_workers=2, master_host=None,
                 master_port=5000, mesh=None, **kw):
        super().__init__(keras_model, **kw)
        self.num_workers = int(num_workers)
        # master_host/master_port: reference PS kwargs, accepted for parity.
        del master_host, master_port
        self._mesh = mesh

    def _cache_extras(self):
        custom = id(self._mesh) if self._mesh is not None else None
        return (self.num_workers, custom)

    @property
    def mesh(self):
        if self._mesh is None:
            from dist_keras_tpu.comm import backend as comm

            # multi-host bring-up: no-op single-process; on a pod it reads
            # the JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES /
            # JAX_PROCESS_ID env that launch.Job exports per host
            comm.initialize()
            self._mesh = worker_mesh(self.num_workers)
        return self._mesh

    def _local_worker_range(self):
        """[lo, hi) worker-mesh slots whose device lives on this process.

        jax.devices() orders devices by process, so a 1-D worker mesh
        gives every host a contiguous run of workers."""
        import jax as _jax

        devs = list(self.mesh.devices.ravel())
        mine = [i for i, d in enumerate(devs)
                if d.process_index == _jax.process_index()]
        if not mine:
            return 0, 0
        lo, hi = mine[0], mine[-1] + 1
        if mine != list(range(lo, hi)):  # pragma: no cover - defensive
            raise RuntimeError(
                "non-contiguous local worker slots; pass an explicit mesh")
        return lo, hi

    def _shards(self, dataset):
        """-> (xs, ys) host arrays with a leading worker axis.

        Single-process: the full (num_workers, steps, batch, ...) deal.
        Multi-host: ONLY this host's workers' rows are materialized
        (leading axis = local worker count); every host computes the
        identical global geometry from the dataset length, so the
        concatenation over hosts equals the single-host deal.  Feed the
        result through ``_to_device`` to get the global sharded array.
        The reference analogue is Spark shipping each executor only its
        partitions (trainers.py:~365) — via ``comm.local_data_slice``
        semantics (comm/backend.py).
        """
        from dist_keras_tpu.comm import backend as comm

        _ = self.mesh  # force process-group bring-up (informative error
        # if comm.initialize() was forgotten at program start)
        return dataset.worker_shards(
            self.num_workers, self.batch_size,
            features_col=self.features_col, label_col=self.label_col,
            worker_range=(self._local_worker_range()
                          if comm.is_multi_host() else None),
            dtype=self.data_dtype)

    def _to_device(self, x):
        """Host (local_workers, ...) array -> device array sharded over
        the worker mesh axis; on multi-host the global array is assembled
        from each process's local block without any host materializing
        the global data."""
        from dist_keras_tpu.comm import backend as comm

        if not comm.is_multi_host():
            return jnp.asarray(x)
        return self._put_worker_chunk(x)[0]

    def _put_worker_chunk(self, *arrays):
        """Async device_put of host ``(local_workers, ...)`` arrays with
        the worker sharding — the streaming feed's transfer primitive
        (``data/feed.py``).  Unlike ``_to_device`` the sharding is always
        explicit, so each chunk's H2D goes straight to its worker's
        device and can overlap the running dispatch."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        from dist_keras_tpu.comm import backend as comm
        from dist_keras_tpu.parallel.mesh import WORKER_AXIS

        sharding = NamedSharding(self.mesh, P(WORKER_AXIS))
        if not comm.is_multi_host():
            return tuple(jax.device_put(a, sharding) for a in arrays)
        out = []
        for a in arrays:
            a = np.ascontiguousarray(a)
            out.append(jax.make_array_from_process_local_data(
                sharding, a, (self.num_workers,) + a.shape[1:]))
        return tuple(out)

    def _stack_workers(self, tree, inner=()):
        """Replicate a pytree with a leading (num_workers, *inner) axis —
        the host-side layout of per-worker carry state (local replicas,
        optimizer state) that crosses chunked-dispatch boundaries sharded
        over the worker mesh axis.  ``inner`` adds unsharded replica dims
        inside each slot (EnsembleTrainer's models-per-slot).

        The broadcast stays a zero-copy numpy view on the host and each
        leaf is ``device_put`` (or process-local assembly on multi-host)
        directly with the worker sharding, so no device ever holds more
        than its own (1, ...) shard — materializing the full
        (workers, ...) stack on one chip could OOM where the per-worker
        state fits fine."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        from dist_keras_tpu.comm import backend as comm
        from dist_keras_tpu.parallel.mesh import WORKER_AXIS

        n = self.num_workers
        sharding = NamedSharding(self.mesh, P(WORKER_AXIS))

        lead = (1,) * (1 + len(inner))
        if comm.is_multi_host():
            lo, hi = self._local_worker_range()

            def _stack(x):
                x = np.asarray(x)
                return jax.make_array_from_process_local_data(
                    sharding,
                    np.broadcast_to(x.reshape(lead + x.shape),
                                    (hi - lo,) + inner + x.shape),
                    (n,) + inner + x.shape)
        else:
            def _stack(x):
                x = np.asarray(x)
                return jax.device_put(
                    np.broadcast_to(x.reshape(lead + x.shape),
                                    (n,) + inner + x.shape), sharding)

        return jax.tree.map(_stack, tree)
