from dist_keras_tpu.comm.backend import (
    barrier,
    fetch_global,
    global_devices,
    initialize,
    is_multi_host,
    local_data_slice,
    local_devices,
    num_processes,
    process_index,
)

__all__ = [
    "initialize", "num_processes", "process_index", "is_multi_host",
    "local_devices", "global_devices", "local_data_slice", "barrier",
    "fetch_global",
]
