"""Distributed communication backend — multi-host scale-out.

Role in the architecture: the reference scales out with Spark executors plus
a hand-rolled pickle-over-TCP parameter server (``networking.py`` +
``parameter_servers.py``).  On TPU, scale-out is ``jax.distributed`` over
DCN for the control plane and XLA collectives over ICI/DCN for the data
plane; this module is the thin host-side layer that stands where the
reference's socket plumbing stood:

- ``initialize``: process-group bring-up (maps to the PS bind/connect dance,
  networking.py:~35).
- ``local_data_slice``: which rows of a global dataset this host feeds — the
  multi-host analogue of the trainer's repartition-to-workers step
  (trainers.py:~365).
- ``barrier``: a psum over all devices, replacing ad-hoc socket round-trips.
- ``fetch_global``: host-side all-gather for metrics/history aggregation
  (what the reference got from Spark's collect()).
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp
import numpy as np

from dist_keras_tpu.utils import knobs


_initialized = False
_barrier_poisoned = None  # message of the timeout that desynced barriers


def initialize(coordinator_address=None, num_processes=None, process_id=None,
               **kw):
    """Bring up the multi-host process group (no-op when single-process).

    Mirrors ``jax.distributed.initialize``.  With no arguments it falls back
    to the ``JAX_COORDINATOR_ADDRESS`` / ``JAX_NUM_PROCESSES`` /
    ``JAX_PROCESS_ID`` environment variables — exactly what
    ``launch.Job.launch`` exports on each pod host — and is a safe no-op
    when neither arguments nor environment are present, so the same training
    script works from a laptop CPU to a multi-host pod.
    """
    import os

    global _initialized
    if _initialized:
        return
    if coordinator_address is None:
        coordinator_address = os.environ.get("JAX_COORDINATOR_ADDRESS")
    if num_processes is None and "JAX_NUM_PROCESSES" in os.environ:
        num_processes = int(os.environ["JAX_NUM_PROCESSES"])
    if process_id is None and "JAX_PROCESS_ID" in os.environ:
        process_id = int(os.environ["JAX_PROCESS_ID"])
    if coordinator_address is None and num_processes is None:
        # single-process mode: nothing to do
        _initialized = True
        return
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes, process_id=process_id, **kw)
    except RuntimeError as e:
        if "must be called before" in str(e):
            # dklint: ignore[untyped-raise] bring-up ordering mistake
            # rewritten with the actionable fix — fatal by design
            raise RuntimeError(
                "multi-host bring-up came too late: something already "
                "initialised the XLA backend (model construction, "
                "jax.devices(), ...). Call dist_keras_tpu.comm.initialize() "
                "as the FIRST thing in your pod entrypoint — before "
                "building models or trainers (launch.Job exports the JAX_* "
                "env; see tests/test_multihost.py's worker for the "
                "pattern).") from e
        raise
    _initialized = True


def num_processes():
    return jax.process_count()


def process_index():
    return jax.process_index()


def is_multi_host():
    return jax.process_count() > 1


def local_devices():
    return jax.local_devices()


def global_devices():
    return jax.devices()


def local_data_slice(n_rows, process=None, count=None):
    """Row range [start, stop) this host should load from a global dataset
    of ``n_rows`` (contiguous split, same dealing order as worker_shards)."""
    process = jax.process_index() if process is None else process
    count = jax.process_count() if count is None else count
    per = n_rows // count
    start = process * per
    stop = n_rows if process == count - 1 else start + per
    return start, stop


def barrier_default_timeout_s():
    """The multi-host barrier deadline used when a caller passes
    ``timeout_s=None``: ``coordination.default_timeout_s()`` — the ONE
    ``DK_COORD_TIMEOUT_S`` knob (``launch.Job(coord_timeout_s=...)`` /
    ``JobConfig.coord_timeout_s`` export it per host), default 120 s,
    re-read per call so a launcher-exported env wins over import
    order.  Returns 0.0 (no deadline) when the env opts out with 0."""
    from dist_keras_tpu.resilience.coordination import default_timeout_s

    return default_timeout_s()


def barrier(tag="dist_keras_tpu_barrier", timeout_s=None):
    """Block until every PROCESS reaches this point.

    Multi-host: ``multihost_utils.sync_global_devices`` — a named psum
    across all hosts' devices (``device_put`` onto an all-devices
    sharding, the round-3 implementation, raises on non-addressable
    devices and could never have worked beyond one process).
    Single-process: a tiny all-device reduction with a blocking fetch.
    Returns the number of participating devices.

    ``timeout_s``: deadline for the multi-host sync — a dead host used
    to hang every survivor here forever; now the wait gives up with a
    typed ``resilience.coordination.PeerLost`` (when heartbeat liveness
    files under ``DK_COORD_DIR`` name the dark rank) or
    ``BarrierTimeout``.  Since the observability PR ``timeout_s=None``
    no longer means "wait forever": the default comes from
    :func:`barrier_default_timeout_s` (``DK_COORD_TIMEOUT_S``, wired
    through ``JobConfig.coord_timeout_s``), so an UNparameterized pod
    barrier still cannot hang indefinitely.  Pass ``timeout_s=0`` to
    explicitly opt out of the deadline.  The single-process path has
    nobody to wait for and keeps returning the device count
    immediately.
    """
    devs = jax.devices()
    if is_multi_host():
        from jax.experimental import multihost_utils

        if timeout_s is None:
            timeout_s = barrier_default_timeout_s()

        global _barrier_poisoned
        if _barrier_poisoned:
            # the abandoned sync from the earlier timeout may still
            # complete on the peers — ANY further barrier (timed or
            # not) would pair this host's op N+1 with their op N (the
            # same desync hazard Coordinator poisoning guards against)
            from dist_keras_tpu.resilience.coordination import (
                CoordinatorPoisoned,
            )

            # typed (not a bare RuntimeError): the auto-resume
            # supervisor must classify a desynced collective stream as
            # never-retried — only a fresh incarnation can help
            raise CoordinatorPoisoned(
                "comm.barrier is poisoned: a previous timed "
                f"barrier gave up ({_barrier_poisoned}) and this "
                "host's position in the collective stream is "
                "unknowable — restart the process instead of "
                "retrying barriers")

        import time as _time

        from dist_keras_tpu.observability import events

        t0 = _time.perf_counter()
        if timeout_s:
            from dist_keras_tpu.resilience import coordination

            def probe():
                d = knobs.raw("DK_COORD_DIR")
                if not d:
                    return []
                # evidence-only (beat once, went dark): PeerLost must
                # never name a host that simply hasn't started beating
                return coordination.dead_peers_at(
                    d, jax.process_count(), require_file=True)

            try:
                coordination.with_deadline(
                    lambda: multihost_utils.sync_global_devices(tag),
                    timeout_s, f"barrier({tag!r})", probe)
            except (coordination.PeerLost,
                    coordination.BarrierTimeout) as e:
                _barrier_poisoned = str(e)
                events.emit("barrier", tag=tag,
                            duration_s=_time.perf_counter() - t0,
                            error=type(e).__name__)
                raise
        else:
            multihost_utils.sync_global_devices(tag)
        events.emit("barrier", tag=tag,
                    duration_s=_time.perf_counter() - t0,
                    n_devices=len(devs))
        return len(devs)
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    mesh = Mesh(np.array(devs), ("i",))
    x = jax.device_put(jnp.ones((len(devs),)), NamedSharding(mesh, P("i")))
    return int(float(jnp.sum(x)))


def fetch_global(tree):
    """Device pytree -> host numpy pytree (full value on every host).

    With jax's global arrays, addressable shards are materialized and
    non-addressable ones fetched via allgather under the hood of
    ``jax.experimental.multihost_utils`` when multi-host.
    """
    if is_multi_host():
        from jax.experimental import multihost_utils

        # tiled=True: global sharded arrays concatenate along their
        # existing axes (the only mode jax supports for non-fully-
        # addressable inputs); host-local values gather equivalently
        return jax.tree.map(
            lambda x: multihost_utils.process_allgather(x, tiled=True),
            tree)
    return jax.tree.map(np.asarray, tree)
