"""dist_keras_tpu — a TPU-native distributed training framework with the
capability set of dist-keras (Spark + Keras parameter-server training),
re-designed for JAX/XLA: jitted scan train loops, shard_map data parallelism,
and the async optimizer family (DOWNPOUR, ADAG, AEASGD, EAMSGD, DynSGD)
re-expressed as windowed local accumulation + ICI collectives.

See SURVEY.md at the repo root for the reference blueprint this implements.
"""

__version__ = "0.1.0"

from dist_keras_tpu import (
    data,
    models,
    ops,
    parallel,
    resilience,
    serving,
    trainers,
    utils,
)
from dist_keras_tpu.data import (
    AccuracyEvaluator,
    AUCEvaluator,
    Dataset,
    DenseTransformer,
    LabelIndexTransformer,
    LossEvaluator,
    MinMaxTransformer,
    ModelPredictor,
    OneHotTransformer,
    ReshapeTransformer,
    StandardScaleTransformer,
)
from dist_keras_tpu.trainers import (
    ADAG,
    AEASGD,
    DOWNPOUR,
    EAMSGD,
    AveragingTrainer,
    DynSGD,
    EnsembleTrainer,
    SingleTrainer,
)

__all__ = [
    "data", "models", "ops", "parallel", "resilience", "serving",
    "trainers", "utils",
    "Dataset", "ModelPredictor",
    "MinMaxTransformer", "OneHotTransformer", "LabelIndexTransformer",
    "ReshapeTransformer", "DenseTransformer", "StandardScaleTransformer",
    "AccuracyEvaluator", "LossEvaluator", "AUCEvaluator",
    "SingleTrainer", "AveragingTrainer", "EnsembleTrainer",
    "DOWNPOUR", "ADAG", "AEASGD", "EAMSGD", "DynSGD",
]
