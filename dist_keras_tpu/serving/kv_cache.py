"""Paged KV-cache allocator — fixed-size pages, free list, exact accounting.

The decode engine (``serving/decode.py``) keeps each replica's attention
keys/values in a page pool: one device array per replica of shape
``(layers, heads, num_pages + 1, page_size, head_dim)`` whose page axis
is carved into fixed-size pages.  This module owns the HOST-side
accounting for that pool — which pages are free, which sequence holds
which pages — so the device arrays never need compaction and a
sequence's KV never moves once written (vLLM's PagedAttention layout,
PAPERS.md).

Contract (the decode engine's admission story depends on every clause):

- **Worst-case reservation at the door.**  ``alloc`` hands out every
  page a sequence could EVER need (``ceil((prompt + max_new) / page
  size)``) in one call, so an admitted sequence can never stall or die
  mid-decode on KV exhaustion — rejection happens strictly at
  admission, as a typed :class:`PagesExhausted` the engine converts to
  ``Overloaded(reason="kv_exhausted")`` (rejected, not lost).
- **Page-exact accounting.**  ``free + held == num_pages`` after every
  operation; double-free and foreign-page frees raise instead of
  corrupting the free list.  ``assert_balanced`` is the leak check the
  chaos tests and the ``--decode-only`` gate call after every sweep.
- **The scratch page.**  Page index ``num_pages`` (one PAST the
  accounted pool) is a write-only spill target: padding slots in a
  fixed-shape decode step and padded prefill positions beyond a
  prompt's real length must write THEIR k/v somewhere with the same
  jitted scatter, and the scratch page absorbs them.  It is never
  allocated, never read (masked by per-sequence lengths), and never
  counted.

Thread safety: the allocator has its own lock, but the decode engine
additionally serializes alloc/free per replica under its scheduler
lock — the lock here makes ``stats()`` safe from any thread (the bench
and ``/metricsz`` read it live).
"""

from __future__ import annotations

import threading

from dist_keras_tpu.resilience.faults import fault_point


class PagesExhausted(RuntimeError):
    """Typed allocation failure: the pool cannot cover the request.

    Carries ``needed`` / ``free`` / ``capacity`` so the admission door
    can answer 503 with real numbers.  Nothing is allocated on this
    path — a failed alloc is side-effect free.
    """

    def __init__(self, needed, free, capacity):
        self.needed = int(needed)
        self.free = int(free)
        self.capacity = int(capacity)
        super().__init__(
            f"KV pool exhausted: need {self.needed} pages, "
            f"{self.free} free of {self.capacity}")


class PagedKVCache:
    """Free-list page allocator over a ``num_pages`` pool.

    Pure host-side accounting — the device pool arrays live with the
    replica that owns them (the engine threads page ids from here into
    the jitted prefill/decode scatters).
    """

    def __init__(self, num_pages, page_size):
        self.num_pages = int(num_pages)
        self.page_size = int(page_size)
        if self.num_pages < 1 or self.page_size < 1:
            raise ValueError(
                f"PagedKVCache(num_pages={num_pages}, "
                f"page_size={page_size}): both must be >= 1")
        # LIFO free list: a just-freed page is the next handed out, so
        # a steady workload touches a small working set of pages
        self._free = list(range(self.num_pages - 1, -1, -1))
        self._held = {}      # seq_id -> [page ids]
        self._peak = 0
        self._allocs = 0
        self._frees = 0
        self._lock = threading.Lock()

    @property
    def scratch_page(self):
        """The write-only spill page index (one past the pool)."""
        return self.num_pages

    def pages_for(self, tokens):
        """Pages needed to hold ``tokens`` KV positions."""
        t = int(tokens)
        return max(1, -(-t // self.page_size))

    def alloc(self, seq_id, tokens):
        """Reserve every page ``tokens`` positions need; -> page-id
        list.  Raises :class:`PagesExhausted` (side-effect free) when
        the free list cannot cover it, ``ValueError`` on a duplicate
        ``seq_id`` (an accounting bug, not load)."""
        fault_point("decode.kv_alloc")
        n = self.pages_for(tokens)
        with self._lock:
            if seq_id in self._held:
                raise ValueError(
                    f"sequence {seq_id!r} already holds pages")
            if n > len(self._free):
                raise PagesExhausted(n, len(self._free), self.num_pages)
            pages = [self._free.pop() for _ in range(n)]
            self._held[seq_id] = pages
            self._allocs += 1
            used = self.num_pages - len(self._free)
            self._peak = max(self._peak, used)
            return list(pages)

    def free(self, seq_id):
        """Return every page ``seq_id`` holds to the free list — the
        single reclamation path for completion, cancel, error and
        engine shutdown.  Idempotent-hostile by design: freeing an
        unknown sequence raises ``KeyError`` (callers own exactly-once
        reclamation; a silent second free would hide a leak of the
        OPPOSITE sign)."""
        with self._lock:
            pages = self._held.pop(seq_id)
            self._free.extend(pages)
            self._frees += 1
            return len(pages)

    def holds(self, seq_id):
        with self._lock:
            return seq_id in self._held

    def sequence_ids(self):
        """Sequence ids currently holding pages — the engine's
        periodic self-check reconciles this against the sequences the
        scheduler actually owns (anything unowned is a leak)."""
        with self._lock:
            return tuple(self._held)

    def used_pages(self):
        with self._lock:
            return self.num_pages - len(self._free)

    def assert_balanced(self):
        """The leak invariant: every non-free page is attributable to
        exactly one live sequence.  Raises ``AssertionError`` naming
        the imbalance — the chaos sweep's zero-leak check."""
        with self._lock:
            held = sum(len(p) for p in self._held.values())
            free = len(self._free)
            if held + free != self.num_pages:
                raise AssertionError(
                    f"KV page leak: {held} held + {free} free != "
                    f"{self.num_pages} pool pages "
                    f"({sorted(self._held)} live)")
            if len(set(self._free)) != free:
                raise AssertionError("KV free list holds duplicates")

    def stats(self):
        """JSON-ready pool counters (occupancy is the bench's
        ``kv_occupancy`` series)."""
        with self._lock:
            used = self.num_pages - len(self._free)
            return {
                "num_pages": self.num_pages,
                "page_size": self.page_size,
                "used_pages": used,
                "free_pages": len(self._free),
                "peak_pages": self._peak,
                "occupancy": used / self.num_pages,
                "sequences": len(self._held),
                "allocs": self._allocs,
                "frees": self._frees,
            }
