"""Online serving subsystem — dynamic batching, replica scheduling,
hot checkpoint reload, HTTP front end, graceful drain.

The repo's offline ``ModelPredictor`` and pull-based
``StreamingPredictor`` answer "run the model over this data"; this
package answers "keep the model UP for concurrent callers": a
:class:`ServingEngine` packs requests into a fixed ladder of jitted
batch shapes across N device replicas, :class:`CheckpointWatcher` rolls
newly promoted checkpoints in with zero dropped requests, and
:class:`ServingServer` is the stdlib HTTP boundary with typed
backpressure and SIGTERM-drain via ``resilience.preemption``.

See the README "Serving" section for endpoints, env knobs and drain
semantics; ``examples/serving.py`` is the runnable demo;
``python -m dist_keras_tpu.serving.bench`` the offered-load benchmark.
"""

from dist_keras_tpu.serving.engine import Overloaded, ServingEngine
from dist_keras_tpu.serving.reload import CheckpointWatcher
from dist_keras_tpu.serving.server import ServingServer, default_port

__all__ = ["ServingEngine", "Overloaded", "CheckpointWatcher",
           "ServingServer", "default_port"]
