"""Online serving subsystem — dynamic batching, replica scheduling,
hot checkpoint reload, HTTP front end, multi-host routing, graceful
drain.

The repo's offline ``ModelPredictor`` and pull-based
``StreamingPredictor`` answer "run the model over this data"; this
package answers "keep the model UP for concurrent callers": a
:class:`ServingEngine` packs requests into a fixed ladder of jitted
batch shapes across N device replicas, :class:`CheckpointWatcher` rolls
newly promoted checkpoints in with zero dropped requests, and
:class:`ServingServer` is the stdlib HTTP boundary with typed
backpressure and SIGTERM-drain via ``resilience.preemption``.

On top of the per-host stack, the serving FABRIC (round 21):
:class:`RouterServer` spreads ``POST /predict`` across hosts by their
``/metricsz`` queue depth with evidence-based eviction/re-admission
(:class:`BackendPool` is the HTTP-free policy core the simulator
drives), :class:`BlueGreenEngine` turns a reload into one atomic
traffic cutover between two engines sharing devices, and
:class:`ReplicaAutoscaler` closes the ``QueueDepthGrowth`` alerting
loop into ``engine.resize`` actuation with hysteresis.

Round 23 adds token-level DECODE serving for the causal transformer:
:class:`DecodeEngine` continuously batches autoregressive sequences
(per-sequence futures, prefill/decode phase split, paged KV cache via
:class:`PagedKVCache` with typed ``kv_exhausted`` admission), the
server grows ``POST /generate`` (batched or streamed), and the router
forwards it with the same traceparent stitching.

See the README "Serving" and "Serving fabric" sections for endpoints,
env knobs, failure matrix and drain semantics; ``examples/serving.py``
is the runnable demo; ``python -m dist_keras_tpu.serving.bench`` the
offered-load benchmark.
"""

from dist_keras_tpu.serving.autoscale import ReplicaAutoscaler
from dist_keras_tpu.serving.decode import DecodeEngine, Generation
from dist_keras_tpu.serving.engine import Overloaded, ServingEngine
from dist_keras_tpu.serving.kv_cache import PagedKVCache, PagesExhausted
from dist_keras_tpu.serving.reload import (
    BlueGreenEngine,
    CheckpointWatcher,
)
from dist_keras_tpu.serving.router import (
    BackendPool,
    ForwardError,
    NoBackends,
    RouterServer,
    default_route_port,
)
from dist_keras_tpu.serving.server import ServingServer, default_port

__all__ = ["ServingEngine", "Overloaded", "CheckpointWatcher",
           "ServingServer", "default_port",
           "RouterServer", "BackendPool", "ForwardError", "NoBackends",
           "BlueGreenEngine", "ReplicaAutoscaler",
           "default_route_port",
           "DecodeEngine", "Generation", "PagedKVCache",
           "PagesExhausted"]
