"""Hot model reload — roll promoted checkpoints into a live engine.

A serving fleet must stay up across model updates: the trainer keeps
promoting new steps through ``checkpoint.Checkpointer`` (whose readers
only ever see FULLY COMMITTED steps — the two-phase promotion rename is
the cluster's single publish instant), and this watcher polls
``latest_step()`` from the serving side, restores any new step, and
swaps the params into every replica between batches via
``ServingEngine.set_params`` — zero dropped in-flight requests by the
engine's swap contract.

Failure semantics (the serving third of the resilience story):

- Restore I/O runs under a named retry policy (``"serve.reload"``
  surface: transient ``OSError`` absorbed with backoff, events/counters
  on every attempt).
- The ``"serve.reload"`` fault point fires per reload attempt, so tests
  inject a failing reload deterministically.  ``FaultInjected`` is not
  retryable (a simulated kill stays a kill).
- A reload that still fails is a TYPED error: :meth:`poll_once` raises
  it to a direct caller; the background loop records a
  ``serve_reload_error`` event + ``serve.reload.errors`` counter,
  keeps serving the OLD params, and keeps watching — a bad checkpoint
  must never take the fleet down or hang it.
- Every candidate step is INTEGRITY-VERIFIED before the swap
  (``Checkpointer.verify`` — a strictly read-only probe of the
  manifest hashes): a corrupt promoted step is SKIPPED with a typed
  ``reload_skipped_corrupt`` event + ``serve.reload.skipped_corrupt``
  counter and the NEWEST verifiable newer step is loaded instead
  (none at all: the engine keeps serving the old params; previously a
  rotted step would fail inside the restore mid-swap attempt and burn
  the reload loop's whole retry budget each poll).  The restore
  itself runs ``verify=False`` — the probe already passed, and the
  verified-restore path would quarantine (rename) inside the
  trainer's live directory, which a reader must never do.  A legacy
  pre-manifest checkpoint verifies "unverifiable" and reloads as
  before.
- A checkpoint written by a DIFFERENT world size than the server's —
  a world-1 serving host hot-loading a pod-written two-phase step —
  is an ELASTIC reload: the probe verifies EVERY host payload and the
  restore re-partitions through ``resilience.elastic.reshard_restore``
  (sharded leaves gathered by global index, replicated leaves from
  the leader) instead of failing the per-rank payload lookup.
- Remote tier (round 18): with ``DK_CKPT_REMOTE`` configured on the
  serving host, the watcher becomes a PULL-THROUGH cache — each poll
  first fetches any newly completed remote step missing locally
  (``Checkpointer.fetch_remote_newer``; the spot-serving host whose
  disk shares nothing with the trainer's), and a candidate convicted
  corrupt is re-fetched clean from the store ONCE before being
  skipped.  Both paths assume the watcher's checkpoint directory is
  this host's own cache dir, which is exactly the deployment that
  configures a remote tier.
- Async/chunked saves (``DK_CKPT_ASYNC`` / ``DK_CKPT_CHUNK_MB`` on the
  TRAINER side) need nothing special here: the watcher still only ever
  sees PROMOTED steps (async staging is invisible until the same
  atomic promote), and the verify probe walks the manifest's
  PER-CHUNK entries — each ``chunk_NNNN.KKKKK`` file of a large leaf
  hashes independently, so a single rotted chunk convicts the step
  exactly like a rotted whole-payload file, and the restore reads the
  chunked format transparently.
"""

from __future__ import annotations

import threading

from dist_keras_tpu.observability import events, metrics
from dist_keras_tpu.observability.spans import span
from dist_keras_tpu.resilience.faults import fault_point
from dist_keras_tpu.resilience.retry import RetryPolicy


class BlueGreenEngine:
    """Two engines, one traffic pointer — reload as an atomic cutover.

    :meth:`ServingEngine.set_params` already hot-swaps params with zero
    dropped requests, but the swap is gradual per replica and the new
    params serve from engines whose queues still hold old-params work.
    Blue/green makes the rollout a single atomic TRAFFIC decision
    instead: two :class:`~.engine.ServingEngine` instances share the
    same devices (the standby idles, so the device cost is memory, not
    compute); ``set_params`` loads the new params into the STANDBY,
    then flips the active index — one reference assignment, atomic
    under the GIL.  Requests admitted before the flip drain on the old
    params inside the old engine (its no-drop contract is untouched);
    requests after the flip land on the new ones.  Nothing is ever
    in-between, and a bad load never touches the serving color.

    The class quacks like a single engine everywhere the serving stack
    cares (``submit`` / ``predict`` / ``submit_generate`` /
    ``generate`` / ``set_params`` / ``resize`` / ``stats`` / ``drain``
    / ``close`` / ``draining`` / ``running``),
    so :class:`ServingServer`, :class:`CheckpointWatcher`, and the
    autoscaler compose with it unchanged.  Each cutover emits
    ``route_cutover`` + the ``route.cutovers`` counter.
    """

    def __init__(self, make_engine):
        """``make_engine`` builds one engine (called twice — the
        factory form keeps the two engines' construction identical
        without this class knowing the model/ladder/device args)."""
        self._engines = [make_engine(), make_engine()]
        self._active_idx = 0
        self._lock = threading.Lock()  # serializes cutovers, not reads
        self.cutovers = 0

    @property
    def active(self):
        return self._engines[self._active_idx]

    @property
    def standby(self):
        return self._engines[1 - self._active_idx]

    # -- serving surface (active color) ---------------------------------
    def submit(self, row):
        # one atomic read of the index: a request races the flip into
        # exactly one color, and whichever engine admitted it delivers
        # it (the old color keeps draining after a flip)
        return self._engines[self._active_idx].submit(row)

    def predict(self, rows, timeout_s=None):
        return self._engines[self._active_idx].predict(
            rows, timeout_s=timeout_s)

    def submit_generate(self, tokens, max_new_tokens=None, eos_id=None,
                        on_token=None, deadline_s=None,
                        priority="interactive"):
        # decode passthrough (DecodeEngine colors): same atomic-read
        # race rule — a generation lands WHOLE in one color; after a
        # cutover the old color finishes every sequence it admitted on
        # the params they were admitted under (the engine pins them),
        # so a mid-decode rollout never drops a sequence
        return self._engines[self._active_idx].submit_generate(
            tokens, max_new_tokens=max_new_tokens, eos_id=eos_id,
            on_token=on_token, deadline_s=deadline_s,
            priority=priority)

    def generate(self, tokens, max_new_tokens=None, eos_id=None,
                 timeout_s=None):
        return self.submit_generate(
            tokens, max_new_tokens=max_new_tokens,
            eos_id=eos_id).result(timeout=timeout_s)

    # -- rollout --------------------------------------------------------
    def set_params(self, state, step=None):
        """Load ``state`` into the standby, then atomically cut traffic
        over to it.  The previous active keeps its queue and finishes
        every admitted request on the params they were admitted under,
        then becomes the next rollout's standby."""
        with self._lock:
            standby_idx = 1 - self._active_idx
            self._engines[standby_idx].set_params(state, step=step)
            self._active_idx = standby_idx  # THE cutover instant
            self.cutovers += 1
        metrics.counter("route.cutovers").inc()
        events.emit("route_cutover", step=step,
                    active_engine=standby_idx, cutovers=self.cutovers)

    def resize(self, n):
        """Fan to both colors: the standby must already be at size when
        it becomes active mid-incident."""
        with self._lock:
            for e in self._engines:
                e.resize(n)
        return n

    # -- lifecycle / introspection --------------------------------------
    def drain(self, timeout_s=None):
        outs = [e.drain(timeout_s=timeout_s) for e in self._engines]
        a = outs[self._active_idx]
        return {**a, "standby_delivered":
                outs[1 - self._active_idx]["delivered"]}

    def close(self, drain=True, timeout_s=None):
        for e in self._engines:
            e.close(drain=drain, timeout_s=timeout_s)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    @property
    def draining(self):
        return self._engines[self._active_idx].draining

    @property
    def running(self):
        return self._engines[self._active_idx].running

    def stats(self):
        st = self._engines[self._active_idx].stats()
        st["cutovers"] = self.cutovers
        st["active_engine"] = self._active_idx
        st["standby_outstanding"] = \
            self._engines[1 - self._active_idx].stats()["outstanding"]
        return st


class CheckpointWatcher:
    """Poll a ``Checkpointer`` for newly promoted steps and hot-swap
    them into a :class:`~dist_keras_tpu.serving.engine.ServingEngine`.

    Args:
      engine: the live engine (anything with ``set_params``).
      checkpointer: ``checkpoint.Checkpointer`` (read-only use: a
        polling watcher can never interfere with the writer).
      poll_s: latest-step poll interval for the background loop.
      template: pytree template for exact orbax restore (defaults to
        None — fallback-format checkpoints need none).
      initial_step: steps <= this are considered already served.
        Default: the latest step at construction, so a fresh watcher
        only reacts to NEW promotions.
      on_error: optional callback ``(step, exc)`` from the background
        loop after a reload fails (already recorded + old params kept).
    """

    def __init__(self, engine, checkpointer, poll_s=1.0, template=None,
                 initial_step=None, retry=None, on_error=None):
        self.engine = engine
        self.checkpointer = checkpointer
        self.poll_s = float(poll_s)
        self.template = template
        self.on_error = on_error
        self._retry = retry or RetryPolicy(
            attempts=3, backoff=0.05, jitter=0.0, retryable=(OSError,),
            name="serve.reload")
        self.last_step = (checkpointer.latest_step()
                          if initial_step is None else int(initial_step))
        self.reloads = 0
        self.errors = 0
        self.skipped_corrupt = 0
        # steps already convicted corrupt but not yet folded into
        # last_step (a restore failure on the chosen INTACT step keeps
        # last_step put so the restore is retried next poll — without
        # this set each such poll would re-hash the corrupt steps'
        # whole payloads and re-emit reload_skipped_corrupt for them)
        self._corrupt_seen = set()
        # steps whose rotted local copy was already re-fetched once
        # from the remote tier — a remote copy that convicts too must
        # not re-download every poll
        self._remote_healed = set()
        self._stop = threading.Event()
        self._thread = None

    def poll_once(self):
        """Check for a newer promoted step; reload it into the engine.

        -> the step reloaded (the NEWEST verifiable step newer than
        ``last_step`` — a rotted latest falls back to an intact
        intermediate promotion), or None when nothing new OR every
        newer step failed integrity verification (skipped, typed
        ``reload_skipped_corrupt`` event per corrupt step, old params
        kept — for both direct callers and the background loop; a
        rotted promoted step is an expected hazard of watching a live
        training directory, not an exception for every caller to
        re-handle).  Raises the (typed) reload error to a direct
        caller — the background loop is the path that absorbs it."""
        from dist_keras_tpu.checkpoint import CheckpointCorrupt

        # remote tier first: a serving host whose checkpointer points
        # at its OWN local cache dir (the spot-serving deployment that
        # configures DK_CKPT_REMOTE) pulls newly completed remote
        # steps down before the local scan — the pull-through half of
        # the remote fallback.  Typed pull failures are absorbed (the
        # ckpt.pull retry surface already recorded them); the engine
        # keeps serving whatever it has.
        if self.checkpointer.has_remote():
            try:
                self.checkpointer.fetch_remote_newer(
                    self.last_step, skip=self._corrupt_seen)
            except (OSError, CheckpointCorrupt) as e:
                metrics.counter("serve.reload.errors").inc()
                events.emit("serve_reload_error",
                            error=type(e).__name__,
                            detail="remote fetch: " + str(e)[:160])
        # timeout_s=0 = a single non-blocking probe of the promoted
        # steps; the BLOCKING wait stays in wait_for_step_after for
        # direct callers, while this loop keeps its own stoppable
        # cadence (self._stop.wait between probes)
        newest = self.checkpointer.wait_for_step_after(
            step=self.last_step, timeout_s=0)
        if newest is None:
            return None
        # newest-first over EVERY promoted step newer than last_step:
        # a rotted latest must not shadow an intact intermediate
        # promotion (trainer promotes 5 then 6, 6 rots between polls —
        # serving step-4 params until step 7 lands would be one full
        # cadence of staleness the directory already has the cure for)
        candidates = [s for s in self.checkpointer.all_steps()
                      if s > (self.last_step or 0)] or [newest]
        step = None
        for cand in reversed(candidates):
            if cand in self._corrupt_seen:
                continue  # convicted on an earlier poll: dead bytes
            try:
                # read-only probe (never quarantines — this process is
                # a reader of someone else's training directory); "ok"
                # and the legacy "unverifiable" both proceed to the
                # swap.  A step written by a DIFFERENT world than this
                # server's (a world-1 server hot-loading a pod-written
                # checkpoint) is a RESHARD restore — it will read
                # EVERY host's payload, so the probe must cover them
                # all, and the restore below re-partitions via
                # resilience.elastic instead of failing the per-rank
                # payload lookup.
                _rank, world = self.checkpointer._coord_ids()
                if self.checkpointer.saved_world(cand) != world:
                    self.checkpointer.verify(cand, all_hosts=True)
                else:
                    self.checkpointer.verify(cand)
                step = cand
                break
            except CheckpointCorrupt as e:
                if cand not in self._remote_healed \
                        and self.checkpointer._remote_has_quiet(cand):
                    # the remote tier still holds this exact step:
                    # replace the rotted local copy with the clean
                    # remote bytes and re-verify ONCE — the serving
                    # analogue of restore()'s remote self-heal.
                    # (Assumes the watcher's directory is this host's
                    # own pull-through cache — the deployment that
                    # configures a remote tier.)
                    self._remote_healed.add(cand)
                    try:
                        self.checkpointer.fetch_remote(cand)
                        _r, world = self.checkpointer._coord_ids()
                        self.checkpointer.verify(
                            cand, all_hosts=self.checkpointer
                            .saved_world(cand) != world)
                        step = cand
                        break
                    except (OSError, CheckpointCorrupt):
                        pass  # remote copy unusable too: convict
                self._corrupt_seen.add(cand)
                self.skipped_corrupt += 1
                metrics.counter("serve.reload.skipped_corrupt").inc()
                events.emit("reload_skipped_corrupt", step=int(cand),
                            detail=str(e)[:200])
        # every newer step is now seen — loaded, or skipped as corrupt
        # bytes that cannot heal (hot-looping verification against
        # them would melt the poll loop; the trainer's NEXT promotion
        # supersedes them)
        if step is None:
            self._advance(max(candidates))
            return None
        with span("serve.reload", step=step):
            def attempt():
                fault_point("serve.reload")
                # verify=False: the read-only probe above already ran.
                # The default VERIFIED restore would, if the step rots
                # between probe and read, QUARANTINE it (a rename in
                # the trainer's live directory this reader must never
                # perform) and silently fall back — the engine would
                # then serve step-N-1 params stamped as step N.  With
                # verification pinned off the race window collapses to
                # a typed load error, absorbed like any reload failure.
                return self.checkpointer.restore(
                    step=step, template=self.template, verify=False)
            got, state = self._retry.call(attempt)
            self.engine.set_params(state, step=got)
        # max, not step: a corrupt candidate NEWER than the one loaded
        # is seen too, or the next poll would re-verify dead bytes
        self._advance(max(candidates))
        self.reloads += 1
        return step

    def _advance(self, step):
        self.last_step = step
        # convictions at or below the new horizon are subsumed by
        # last_step; the sets only ever hold the (bounded) window of
        # corrupt steps newer than an intact one still being retried
        self._corrupt_seen = {s for s in self._corrupt_seen if s > step}
        self._remote_healed = {s for s in self._remote_healed
                               if s > step}

    def _loop(self):
        while not self._stop.is_set():
            try:
                self.poll_once()
            # dklint: ignore[broad-except] reload failure is typed + non-fatal; old params keep serving
            except Exception as e:
                # typed, recorded, non-fatal: keep serving old params
                self.errors += 1
                metrics.counter("serve.reload.errors").inc()
                events.emit("serve_reload_error",
                            error=type(e).__name__, detail=str(e)[:200])
                if self.on_error is not None:
                    try:
                        self.on_error(self.checkpointer.latest_step(), e)
                    # dklint: ignore[broad-except] user on_reload hook is best-effort
                    except Exception:  # pragma: no cover - user hook
                        pass
            self._stop.wait(self.poll_s)

    def start(self):
        """Start the background watch loop (daemon thread); -> self."""
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="dk-serve-reload")
        self._thread.start()
        return self

    def stop(self, timeout_s=5.0):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout_s)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False
