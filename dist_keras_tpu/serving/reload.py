"""Hot model reload — roll promoted checkpoints into a live engine.

A serving fleet must stay up across model updates: the trainer keeps
promoting new steps through ``checkpoint.Checkpointer`` (whose readers
only ever see FULLY COMMITTED steps — the two-phase promotion rename is
the cluster's single publish instant), and this watcher polls
``latest_step()`` from the serving side, restores any new step, and
swaps the params into every replica between batches via
``ServingEngine.set_params`` — zero dropped in-flight requests by the
engine's swap contract.

Failure semantics (the serving third of the resilience story):

- Restore I/O runs under a named retry policy (``"serve.reload"``
  surface: transient ``OSError`` absorbed with backoff, events/counters
  on every attempt).
- The ``"serve.reload"`` fault point fires per reload attempt, so tests
  inject a failing reload deterministically.  ``FaultInjected`` is not
  retryable (a simulated kill stays a kill).
- A reload that still fails is a TYPED error: :meth:`poll_once` raises
  it to a direct caller; the background loop records a
  ``serve_reload_error`` event + ``serve.reload.errors`` counter,
  keeps serving the OLD params, and keeps watching — a bad checkpoint
  must never take the fleet down or hang it.
"""

from __future__ import annotations

import threading

from dist_keras_tpu.observability import events, metrics
from dist_keras_tpu.observability.spans import span
from dist_keras_tpu.resilience.faults import fault_point
from dist_keras_tpu.resilience.retry import RetryPolicy


class CheckpointWatcher:
    """Poll a ``Checkpointer`` for newly promoted steps and hot-swap
    them into a :class:`~dist_keras_tpu.serving.engine.ServingEngine`.

    Args:
      engine: the live engine (anything with ``set_params``).
      checkpointer: ``checkpoint.Checkpointer`` (read-only use: a
        polling watcher can never interfere with the writer).
      poll_s: latest-step poll interval for the background loop.
      template: pytree template for exact orbax restore (defaults to
        None — fallback-format checkpoints need none).
      initial_step: steps <= this are considered already served.
        Default: the latest step at construction, so a fresh watcher
        only reacts to NEW promotions.
      on_error: optional callback ``(step, exc)`` from the background
        loop after a reload fails (already recorded + old params kept).
    """

    def __init__(self, engine, checkpointer, poll_s=1.0, template=None,
                 initial_step=None, retry=None, on_error=None):
        self.engine = engine
        self.checkpointer = checkpointer
        self.poll_s = float(poll_s)
        self.template = template
        self.on_error = on_error
        self._retry = retry or RetryPolicy(
            attempts=3, backoff=0.05, jitter=0.0, retryable=(OSError,),
            name="serve.reload")
        self.last_step = (checkpointer.latest_step()
                          if initial_step is None else int(initial_step))
        self.reloads = 0
        self.errors = 0
        self._stop = threading.Event()
        self._thread = None

    def poll_once(self):
        """Check for a newer promoted step; reload it into the engine.

        -> the step reloaded, or None when nothing new.  Raises the
        (typed) reload error to a direct caller — the background loop
        is the path that absorbs it."""
        # timeout_s=0 = a single non-blocking probe of the promoted
        # steps; the BLOCKING wait stays in wait_for_step_after for
        # direct callers, while this loop keeps its own stoppable
        # cadence (self._stop.wait between probes)
        step = self.checkpointer.wait_for_step_after(
            step=self.last_step, timeout_s=0)
        if step is None:
            return None
        with span("serve.reload", step=step):
            def attempt():
                fault_point("serve.reload")
                return self.checkpointer.restore(
                    step=step, template=self.template)
            _, state = self._retry.call(attempt)
            self.engine.set_params(state, step=step)
        self.last_step = step
        self.reloads += 1
        return step

    def _loop(self):
        while not self._stop.is_set():
            try:
                self.poll_once()
            except Exception as e:
                # typed, recorded, non-fatal: keep serving old params
                self.errors += 1
                metrics.counter("serve.reload.errors").inc()
                events.emit("serve_reload_error",
                            error=type(e).__name__, detail=str(e)[:200])
                if self.on_error is not None:
                    try:
                        self.on_error(self.checkpointer.latest_step(), e)
                    except Exception:  # pragma: no cover - user hook
                        pass
            self._stop.wait(self.poll_s)

    def start(self):
        """Start the background watch loop (daemon thread); -> self."""
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="dk-serve-reload")
        self._thread.start()
        return self

    def stop(self, timeout_s=5.0):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout_s)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False
