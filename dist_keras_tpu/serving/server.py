"""Stdlib-only HTTP front end for the serving engine.

JSON rows in, predictions out — the serving analogue of the reference's
Kafka topic boundary, but request/response so millions of independent
clients can call it.  Deliberately ``http.server`` + ``json`` only (the
image bakes no web framework, and the repo's dependency rule is "gate or
stub, never install").

Endpoints:

- ``POST /predict`` — body ``{"rows": [[...], ...]}`` (or a bare JSON
  list of rows); answers ``{"predictions": [[...], ...], "n": N}``.
  Typed failure mapping: :class:`~.engine.Overloaded` -> **503** (with
  ``Retry-After``), a per-batch predict error -> **500** naming the
  error type, a response outliving ``request_timeout_s`` -> **504**,
  bad JSON -> **400**.  Rejected requests are REJECTED AT THE DOOR —
  admitted ones are always answered (the engine's no-drop contract).
- ``POST /generate`` — autoregressive decode against a
  :class:`~.decode.DecodeEngine` backend: body ``{"tokens": [...],
  "max_new_tokens": N, "eos_id": E, "stream": bool}``.  Batched replies
  return the engine's result doc (tokens, TTFT, finish reason);
  ``stream: true`` answers chunked NDJSON, one line per token as it
  lands.  The same typed mapping applies (503 incl.
  ``kv_exhausted``, 400, 504 — a timed-out generation is cancelled so
  its KV pages reclaim); a fixed-shape predict backend answers **501**.
- ``GET /healthz`` — **200** ``{"status": "serving"}`` while accepting;
  **503** ``{"status": "draining"}`` once drain began, so a load
  balancer stops routing here during the grace window.
- ``GET /metricsz`` — engine stats + the process metrics registry
  snapshot, JSON.
- ``GET /statusz`` — build/config/knob snapshot + open-span summary
  (the shared ``observability.statusz`` renderer, plus an ``engine``
  section) — the same document the standalone metrics exporter serves.
- ``GET /tracez`` — the flight recorder's retained span/event records.

Tracing: ``POST /predict`` honors an incoming ``traceparent`` header
(W3C ``00-<trace>-<span>-01``) — the whole request lifecycle runs under
one ``serve.request`` span continuing the caller's trace, the
batcher/replica threads stamp their stages into it, and the response
echoes a ``traceparent`` naming that span for client-side correlation.

Graceful drain rides the EXISTING preemption path
(``resilience.preemption``): :meth:`ServingServer.install_signal_drain`
installs the flag-only SIGTERM/SIGINT handler, and a watcher thread
(``preemption.on_request``) notices the flag, drains the engine (every
admitted request delivered, new ones 503), and stops the listener.
:meth:`run_forever` then re-raises :class:`Preempted`, so an uncaught
drain exits ``128+signum`` — the same scheduler convention trainers and
bench follow.
"""

from __future__ import annotations

import concurrent.futures
import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np

from dist_keras_tpu.observability import events, spans
from dist_keras_tpu.observability import metrics as _metrics
from dist_keras_tpu.resilience import preemption
from dist_keras_tpu.serving.engine import Overloaded
from dist_keras_tpu.utils import knobs


def default_port(fallback=8000):
    """The port a launched serving job should bind: ``DK_SERVE_PORT``
    (exported per host by ``launch.Job(serve_port=...)``), else
    ``fallback``."""
    try:
        return int(knobs.raw("DK_SERVE_PORT") or fallback)
    except ValueError:
        return fallback


class _Handler(BaseHTTPRequestHandler):
    server_version = "dk-serve/0.1"
    protocol_version = "HTTP/1.1"
    _trace_header = None  # per-request traceparent echo (do_POST sets it)

    def log_message(self, fmt, *args):  # quiet: the event log is the log
        pass

    def _reply(self, code, payload, retry_after=None):
        self._reply_text(code, json.dumps(payload), "application/json",
                         retry_after=retry_after)

    def _reply_text(self, code, text, content_type, retry_after=None):
        body = text.encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        if retry_after is not None:
            self.send_header("Retry-After", str(retry_after))
        if self._trace_header is not None:
            # the round-trip half of trace propagation: the response
            # names the serve.request span the caller's trace continued
            # into, so a client log line and a server trace correlate
            self.send_header("traceparent", self._trace_header)
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        srv = self.server
        self._trace_header = None  # keep-alive: no stale POST echo
        path, _, query = self.path.partition("?")
        if path == "/healthz":
            if srv.engine.draining or not srv.engine.running:
                self._reply(503, {"status": "draining"})
            else:
                st = srv.engine.stats()
                self._reply(200, {"status": "serving",
                                  "replicas": st["replicas"],
                                  "pending": st["pending"]})
        elif path == "/metricsz":
            if "format=prometheus" in query:
                # the scrape-plane view: registry exposition + the
                # engine's numeric stats as dk_serve_engine_* gauges,
                # text format 0.0.4 — the same rendering the standalone
                # per-host exporter serves, so a router/Prometheus
                # scrapes one vocabulary everywhere
                from dist_keras_tpu.observability import prometheus

                extras = {
                    f"serve.engine.{k}": v
                    for k, v in srv.engine.stats().items()
                    if isinstance(v, (int, float))
                    and not isinstance(v, bool)}
                self._reply_text(
                    200, prometheus.render(extra_gauges=extras),
                    prometheus.CONTENT_TYPE)
            else:
                self._reply(200, {"engine": srv.engine.stats(),
                                  "registry": _metrics.snapshot()})
        elif path == "/statusz":
            # build/config/open-span snapshot — the SHARED renderer
            # (observability.statusz) both this server and the
            # standalone exporter serve, plus the engine section
            from dist_keras_tpu.observability import statusz

            self._reply_text(
                200, statusz.render(extra={"engine": srv.engine.stats()}),
                "application/json")
        elif path == "/tracez":
            # the flight recorder's retained span/event records, on
            # demand — the live half of the dump-on-incident story.
            # default=str: ring records hold the PRE-serialization
            # field values (numpy scalars and friends included)
            from dist_keras_tpu.observability import flight

            self._reply_text(200, json.dumps(flight.tracez_doc(),
                                             default=str),
                             "application/json")
        else:
            self._reply(404, {"error": "not_found", "path": self.path})

    def do_POST(self):
        srv = self.server
        self._trace_header = None
        path = self.path.split("?")[0]
        if path == "/generate":
            self._handle_generate(srv)
            return
        if path != "/predict":
            self._reply(404, {"error": "not_found", "path": self.path})
            return
        try:
            n = int(self.headers.get("Content-Length", 0))
            doc = json.loads(self.rfile.read(n).decode("utf-8"))
            rows = doc["rows"] if isinstance(doc, dict) else doc
            rows = [np.asarray(r, dtype=np.float32) for r in rows]
            if not rows:
                raise ValueError("empty rows")
        except (ValueError, KeyError, TypeError) as e:
            self._reply(400, {"error": "bad_request",
                              "detail": str(e)[:200]})
            return
        # the whole lifecycle — admission, queue wait, batching,
        # in-flight, reply assembly — runs under ONE serve.request span
        # continuing the caller's trace when a traceparent header came
        # in (a malformed header degrades to a fresh root, never a 4xx)
        ctx = spans.parse_traceparent(self.headers.get("traceparent"))
        with spans.resume(ctx):
            with spans.span("serve.request", n=len(rows)):
                self._trace_header = spans.traceparent()
                code, payload, retry_after = self._predict(srv, rows)
                self._reply(code, payload, retry_after=retry_after)

    def _predict(self, srv, rows):
        """Admission + result gathering -> (status, payload,
        retry_after) with the engine's typed failure mapping."""
        try:
            futs = [srv.engine.submit(r) for r in rows]
        except Overloaded as e:
            # the engine's typed backpressure -> LB-visible 503; rows
            # admitted before the rejection still complete inside the
            # engine (rejected-not-lost), the caller just retries whole
            return 503, {"error": "overloaded", "reason": e.reason,
                         "pending": e.pending,
                         "capacity": e.capacity}, 1
        except ValueError as e:  # row shape mismatch: the CALLER's bug
            return 400, {"error": "bad_request",
                         "detail": str(e)[:200]}, None
        # dklint: ignore[broad-except] admission error maps to a typed HTTP status, never a dead handler
        except Exception as e:  # typed admission error (enqueue fault)
            return 500, {"error": type(e).__name__,
                         "detail": str(e)[:200]}, None
        try:
            deadline = time.monotonic() + srv.request_timeout_s
            preds = [f.result(timeout=max(0.0,
                                          deadline - time.monotonic()))
                     for f in futs]
        except (TimeoutError, concurrent.futures.TimeoutError):
            # (distinct classes before py3.11, one alias after)
            return 504, {"error": "timeout",
                         "timeout_s": srv.request_timeout_s}, None
        # dklint: ignore[broad-except] predict error maps to a typed HTTP 500 naming the type
        except Exception as e:  # typed predict error (fault, OOM, ...)
            return 500, {"error": type(e).__name__,
                         "detail": str(e)[:200]}, None
        return 200, {
            "predictions": [np.asarray(p).tolist() for p in preds],
            "n": len(preds)}, None

    # -- decode serving (POST /generate) -------------------------------
    def _handle_generate(self, srv):
        """``POST /generate`` — body ``{"tokens": [...],
        "max_new_tokens": N, "eos_id": E, "stream": bool,
        "deadline_s": S, "priority": "interactive"|"batch"}`` (or a
        bare token list).  Batched replies carry the engine's result
        doc; ``stream: true`` answers chunked NDJSON, one ``{"token":
        t}`` line per generated token as it lands plus a final
        ``{"done": true, ...}`` summary line.  ``deadline_s`` /
        ``priority`` also arrive as ``x-dk-deadline-s`` /
        ``x-dk-priority`` headers (the router's propagation channel;
        the body wins).  Same typed mapping as /predict: Overloaded ->
        503 + Retry-After (incl. ``kv_exhausted``, ``shed_batch``,
        ``deadline_infeasible``), malformed prompt -> 400, deadline ->
        504 (the generation is CANCELLED so its slot and KV pages free
        immediately)."""
        if not hasattr(srv.engine, "submit_generate"):
            self._reply(501, {
                "error": "not_implemented",
                "detail": "this backend serves a fixed-shape predict "
                          "engine; /generate needs a DecodeEngine"})
            return
        try:
            n = int(self.headers.get("Content-Length", 0))
            doc = json.loads(self.rfile.read(n).decode("utf-8"))
            if isinstance(doc, list):
                doc = {"tokens": doc}
            tokens = [int(t) for t in doc["tokens"]]
            max_new = doc.get("max_new_tokens")
            eos_id = doc.get("eos_id")
            stream = bool(doc.get("stream", False))
            # end-to-end deadline: the body field wins; the
            # ``x-dk-deadline-s`` header is the ROUTER's propagation
            # channel (it forwards the body verbatim, so only a
            # header survives the hop without a rewrite)
            deadline_s = doc.get("deadline_s")
            if deadline_s is None:
                hdr = self.headers.get("x-dk-deadline-s")
                deadline_s = float(hdr) if hdr else None
            elif deadline_s is not None:
                deadline_s = float(deadline_s)
            priority = doc.get("priority",
                               self.headers.get("x-dk-priority",
                                                "interactive"))
        except (ValueError, KeyError, TypeError) as e:
            self._reply(400, {"error": "bad_request",
                              "detail": str(e)[:200]})
            return
        ctx = spans.parse_traceparent(self.headers.get("traceparent"))
        with spans.resume(ctx):
            with spans.span("serve.generate", prompt_len=len(tokens),
                            stream=stream):
                self._trace_header = spans.traceparent()
                if stream:
                    self._generate_stream(srv, tokens, max_new, eos_id,
                                          deadline_s, priority)
                else:
                    code, payload, retry = self._generate(
                        srv, tokens, max_new, eos_id, deadline_s,
                        priority)
                    self._reply(code, payload, retry_after=retry)

    def _admit_generate(self, srv, tokens, max_new, eos_id,
                        on_token=None, deadline_s=None,
                        priority="interactive"):
        """-> (generation, None) or (None, (status, payload,
        retry_after)) with the engine's typed failure mapping."""
        try:
            gen = srv.engine.submit_generate(
                tokens, max_new_tokens=max_new, eos_id=eos_id,
                on_token=on_token, deadline_s=deadline_s,
                priority=priority)
        except Overloaded as e:
            return None, (503, {"error": "overloaded",
                                "reason": e.reason,
                                "pending": e.pending,
                                "capacity": e.capacity}, 1)
        except ValueError as e:  # malformed prompt: the CALLER's bug
            return None, (400, {"error": "bad_request",
                                "detail": str(e)[:200]}, None)
        # dklint: ignore[broad-except] admission error maps to a typed HTTP status, never a dead handler
        except Exception as e:  # typed admission error (fault, ...)
            return None, (500, {"error": type(e).__name__,
                                "detail": str(e)[:200]}, None)
        return gen, None

    def _generate(self, srv, tokens, max_new, eos_id,
                  deadline_s=None, priority="interactive"):
        gen, err = self._admit_generate(srv, tokens, max_new, eos_id,
                                        deadline_s=deadline_s,
                                        priority=priority)
        if err is not None:
            return err
        try:
            doc = gen.result(timeout=srv.request_timeout_s)
        except (TimeoutError, concurrent.futures.TimeoutError):
            # reclaim the slot and its KV pages NOW — a deadline miss
            # must not keep burning decode iterations
            gen.cancel()
            return 504, {"error": "timeout",
                         "timeout_s": srv.request_timeout_s}, None
        # dklint: ignore[broad-except] decode error maps to a typed HTTP 500 naming the type
        except Exception as e:  # typed decode error (fault, ...)
            return 500, {"error": type(e).__name__,
                         "detail": str(e)[:200]}, None
        return 200, doc, None

    def _generate_stream(self, srv, tokens, max_new, eos_id,
                         deadline_s=None, priority="interactive"):
        """Chunked-NDJSON streaming: tokens flush as the scheduler
        emits them (the engine's ``on_token`` callback feeds a local
        queue this handler drains)."""
        import queue as _queue

        q = _queue.Queue()
        gen, err = self._admit_generate(srv, tokens, max_new, eos_id,
                                        on_token=q.put,
                                        deadline_s=deadline_s,
                                        priority=priority)
        if err is not None:
            code, payload, retry = err
            self._reply(code, payload, retry_after=retry)
            return
        self.send_response(200)
        self.send_header("Content-Type", "application/x-ndjson")
        self.send_header("Transfer-Encoding", "chunked")
        if self._trace_header is not None:
            self.send_header("traceparent", self._trace_header)
        self.end_headers()

        def chunk(obj):
            data = (json.dumps(obj) + "\n").encode("utf-8")
            self.wfile.write(b"%x\r\n" % len(data) + data + b"\r\n")
            self.wfile.flush()

        deadline = time.monotonic() + srv.request_timeout_s
        i = 0
        try:
            while True:
                try:
                    chunk({"i": i, "token": q.get(timeout=0.05)})
                    i += 1
                except _queue.Empty:
                    if gen.done():
                        break
                    if time.monotonic() > deadline:
                        gen.cancel()  # resolves as finish=cancelled
                        deadline = float("inf")
            # the future resolves AFTER its last on_token fired (same
            # scheduler thread), so a drained queue here is complete
            while not q.empty():
                chunk({"i": i, "token": q.get()})
                i += 1
            try:
                doc = gen.result(timeout=0)
                chunk({"done": True, "finish": doc["finish"],
                       "prompt_len": doc["prompt_len"],
                       "steps": doc["steps"], "ttft_s": doc["ttft_s"],
                       "recoveries": doc.get("recoveries", 0)})
            # dklint: ignore[broad-except] a failed generation ends the stream with a typed error line
            except Exception as e:
                chunk({"done": True, "error": type(e).__name__,
                       "detail": str(e)[:200]})
            self.wfile.write(b"0\r\n\r\n")
        except OSError:
            # client went away mid-stream (reset, broken pipe, or any
            # other socket-level failure — ConnectionError alone missed
            # plain OSErrors from a torn-down TLS/proxy hop): stop
            # decoding for it NOW so its slot and KV pages reclaim
            gen.cancel()


class ServingServer(ThreadingHTTPServer):
    """Threaded HTTP server wrapping one :class:`ServingEngine`.

    ``port=None`` binds :func:`default_port` (the ``DK_SERVE_PORT``
    launch export); ``port=0`` picks a free one (tests).
    """

    daemon_threads = True

    def __init__(self, engine, host="127.0.0.1", port=0,
                 request_timeout_s=30.0):
        self.engine = engine
        self.request_timeout_s = float(request_timeout_s)
        self.preempted_signum = None
        self._stop_watch = None
        self._thread = None
        # lifecycle guard: BaseServer.shutdown() BLOCKS FOREVER unless
        # serve_forever is actually running (it waits on an event only
        # serve_forever's exit sets) — drain()/close() on a constructed-
        # but-never-started server must not wedge the calling thread
        self._lifecycle = threading.Lock()
        self._serving = False
        self._stopping = False
        if port is None:
            port = default_port(fallback=0)
        super().__init__((host, int(port)), _Handler)

    @property
    def address(self):
        """(host, bound_port) — port resolved after bind."""
        return self.server_address[:2]

    # -- lifecycle -----------------------------------------------------
    def serve_forever(self, poll_interval=0.5):
        with self._lifecycle:
            if self._stopping:
                return  # a drain/close already won the race: stay down
            self._serving = True
        try:
            super().serve_forever(poll_interval)
        finally:
            with self._lifecycle:
                self._serving = False

    def _stop_listener(self):
        """Stop the accept loop (only if it ever started) and close the
        socket — safe from any thread, any lifecycle state."""
        with self._lifecycle:
            self._stopping = True
            serving = self._serving
        if serving:
            self.shutdown()
        self.server_close()

    def start(self):
        """Serve on a background thread (tests / notebook use);
        -> (host, port)."""
        # live-telemetry plane: with DK_OBS_SAMPLE_S set, the sampler
        # (time series + watchdog — incl. the serve.pending queue-growth
        # rule) and the DK_METRICS_PORT exporter come up with the
        # server; one env read when unset
        from dist_keras_tpu.observability import timeseries

        timeseries.maybe_start_sampler()
        self._thread = threading.Thread(
            target=self.serve_forever, daemon=True, name="dk-serve-http")
        self._thread.start()
        events.emit("serve_listen", host=self.address[0],
                    port=self.address[1])
        return self.address

    def install_signal_drain(self, poll_s=0.05):
        """Wire SIGTERM/SIGINT -> graceful drain through the existing
        ``resilience.preemption`` path: the signal handler only sets a
        flag (async-signal-safe); a watcher thread notices it and runs
        the drain.  Off the main thread this degrades (``strict=False``)
        to watching flags set via ``preemption.request`` only.  -> True
        when the real handlers installed."""
        installed = preemption.install(strict=False)
        self._stop_watch = preemption.on_request(self._drain_on_signal,
                                                 poll_s=poll_s)
        return installed

    def _drain_on_signal(self, signum):
        self.preempted_signum = signum
        events.emit("serve_drain_signal", signum=signum)
        self.drain()

    def drain(self, timeout_s=None):
        """Stop admission, deliver every in-flight request, stop the
        listener.  Idempotent; while the backlog drains, /healthz and
        /predict answer typed 503s; once drained the listening socket
        CLOSES — late clients get connection-refused (a fast typed
        failure), never a connection parked in an unserviced backlog."""
        out = self.engine.drain(timeout_s=timeout_s)
        self._stop_listener()  # in-flight handler threads still finish
        from dist_keras_tpu.observability import flight, timeseries

        # flush in-flight retention buffers (no-op when off): a pod
        # dying right after the drain must not take undecided traces
        # with it
        flight.retain_flush()
        sampler = timeseries.get_sampler()
        if sampler is not None:
            # one FINAL tick before quiescing: the drain may land
            # right after an incident, and without this pass the
            # perf_sample / SLO evaluation / watchdog check that would
            # have fired the alert dies with the pod (the round-22
            # regression fix — same contract as stop(final_tick=True))
            sampler.tick()
        # deliberate completion: the serve.* counters stop advancing
        # now — quiesce the watchdog so drained-quiet is not judged a
        # throughput stall by the still-running sampler
        if sampler is not None and sampler.watchdog is not None:
            sampler.watchdog.quiesce()
        return out

    def run_forever(self):
        """Serve on the CALLING thread until stopped.  After a
        signal-initiated drain, re-raises :class:`Preempted` so the
        process exits ``128+signum`` (scheduler convention)."""
        from dist_keras_tpu.observability import timeseries

        timeseries.maybe_start_sampler()  # same wiring as start()
        try:
            self.serve_forever()
        finally:
            self.server_close()
        if self.preempted_signum is not None:
            raise preemption.Preempted(self.preempted_signum)

    def close(self):
        if self._stop_watch is not None:
            self._stop_watch()
        self._stop_listener()
        if self.engine.running:
            self.engine.close()
