"""Multi-host serving router — evidence-based eviction over a pod.

Each serving host runs its own :class:`~.server.ServingServer` over its
own engine; this tier is the thin stdlib-HTTP router in front of the
pod (``serving/server.py`` / ``ps/server.py`` style — body consumed
before early replies, SIGTERM drain via ``resilience.preemption``,
``/healthz`` / ``/metricsz`` / ``/statusz`` / ``/tracez``).  A host
dying mid-load stops being a client-visible outage and becomes the
same typed, evidence-judged, bounded event the training path already
made of it.

Routing policy (:class:`BackendPool` — HTTP-free, so the cluster
simulator drives the identical code on simulated time):

- **Least-loaded by queue depth.** The health prober reads each
  backend's ``/metricsz`` engine ``outstanding`` (admitted-but-
  unresolved — the same number the engine's admission bound and the
  ``QueueDepthGrowth`` watchdog rule judge); ``pick`` routes to the
  shallowest backend, round-robin on ties.  A backend whose depth is
  UNKNOWN (malformed or missing ``/metricsz`` — a degraded host is
  exactly when its telemetry rots first) degrades the WHOLE pick to
  round-robin rather than starving the blind host or trusting a stale
  number.
- **Eviction on evidence, never on suspicion.** Three independent
  convictions: (1) consecutive connect/forward failures
  (``DK_ROUTE_FAILS``); (2) a last good ``/healthz`` older than the
  stale window (``DK_ROUTE_STALE_S``); (3) the pod's own heartbeat
  files via ``coordination.dead_peers_at(require_file=True)`` when the
  router watches the job's coord dir — the SAME liveness evidence the
  supervisor and barrier already act on, so router and trainer never
  disagree about who is dead.  Every eviction is a typed
  ``route_evict`` event naming its evidence.
- **Re-admission with hysteresis.** An evicted backend must pass
  ``DK_ROUTE_READMIT_CHECKS`` consecutive healthy probes (and not be
  heartbeat-dead) before re-entering rotation — one lucky probe never
  re-admits a flapping host (``route_readmit``).

Forward path (``POST /predict``): one attempt per backend through the
named ``"route.forward"`` retry surface (``attempts=2`` — a connect
failure or backend 503 is retried on a SIBLING exactly once, with the
failed host excluded; predict is stateless/pure so the single re-send
is idempotent by construction).  Both attempts run under the
``"route.forward"`` fault point; the prober runs under
``"route.health"``.  When no live backend exists, or the sibling
retry also fails, the client gets a typed **503 + Retry-After** —
never a hang, never a silent drop: requests a backend ADMITTED are
the backend's no-drop contract; requests the router could not place
are whole-request retries for the caller.

``POST /generate`` adds three survivability layers on top:

- **Deadline/priority propagation.**  ``x-dk-deadline-s`` and
  ``x-dk-priority`` request headers forward verbatim to the backend,
  whose admission turns an infeasible deadline into a typed 503 at
  the door instead of a burned decode slot.
- **Hedged retries under a budget.**  A non-streaming ``/generate``
  still unanswered past the observed ``route.forward_s`` tail
  (``DK_ROUTE_HEDGE_QUANTILE``) launches ONE duplicate on a sibling;
  first complete answer wins and the loser is CANCELLED (the hedge
  hop runs the backend's streaming surface, so closing the loser's
  socket makes its next token write fail and the backend reclaims
  the slot + KV pages through its own cancel path).  A token-bucket
  budget (``DK_ROUTE_HEDGE_BUDGET`` tokens earned per request) caps
  hedges to a fraction of traffic — a brownout cannot be amplified
  into a retry storm (``route.hedges`` / ``route.hedge_wins`` /
  ``route.hedge_denied``).
- **Streaming relay with typed loss.**  ``stream: true`` bodies relay
  chunk-for-chunk; a backend dying MID-STREAM ends the response with
  a final typed NDJSON record ``{"error": "backend_stream_lost",
  "retryable": true}`` instead of a truncated stream
  (``route.stream_errors`` / ``route_stream_error``), and the death
  counts as forward evidence against the backend.

Tracing: the router parses the caller's ``traceparent``, opens one
``route.forward`` span, and forwards ITS traceparent to the backend —
whose ``serve.request`` span (and the batcher/replica stage spans
under it) then parents to the router's hop: one user request is ONE
stitched trace across router -> host -> replica, and the response
echoes the router's span for client-side correlation.

4xx/5xx semantics: backend 400/500/504 pass through verbatim (the
caller's bug / the backend's typed predict failure — a sibling would
fail the same way); only connect-level failures and backend 503s
(shedding load or draining) move the request to a sibling.
"""

from __future__ import annotations

import http.client
import json
import queue as _queue
import threading
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from dist_keras_tpu.observability import events, spans
from dist_keras_tpu.observability import metrics as _metrics
from dist_keras_tpu.resilience import coordination, preemption
from dist_keras_tpu.resilience import world as _world
from dist_keras_tpu.resilience.faults import fault_point
from dist_keras_tpu.resilience.retry import RetryPolicy
from dist_keras_tpu.utils import knobs


def default_route_port(fallback=8080):
    """The port a launched router should bind: ``DK_ROUTE_PORT``
    (exported per host by ``launch.Job(route_port=...)``), else
    ``fallback``."""
    try:
        return int(knobs.raw("DK_ROUTE_PORT") or fallback)
    except ValueError:
        return fallback


class ForwardError(OSError):
    """One failed forward attempt to one backend — connect-level
    failure, or the backend shedding load (503).  Retryable on a
    SIBLING through the ``route.forward`` surface; the failed backend
    is excluded from the retry's pick."""

    def __init__(self, addr, reason):
        self.addr = addr
        self.reason = str(reason)
        super().__init__(f"forward to {addr} failed: {self.reason}")


class NoBackends(RuntimeError):
    """Typed routing failure: no live backend to place the request on
    (all evicted, or every candidate already excluded this request).
    The front end answers 503 + Retry-After — deliberately NOT
    retryable in-process: the caller's whole-request retry is the
    bounded one."""

    def __init__(self, live=0, total=0):
        self.live = int(live)
        self.total = int(total)
        super().__init__(
            f"no live backends ({live} live of {total} known)")


class _HedgeBudget:
    """Token-bucket retry budget for hedged requests: every forwarded
    request EARNS ``ratio`` tokens (capped at ``cap``), every hedge
    SPENDS one — so hedges are bounded to roughly ``ratio`` of traffic
    no matter how bad the tail gets, and a brownout can never be
    amplified into a retry storm (the classic hedged-request guard)."""

    def __init__(self, ratio=None, cap=10.0):
        self.ratio = float(ratio if ratio is not None
                           else knobs.get("DK_ROUTE_HEDGE_BUDGET"))
        self.cap = float(cap)
        self._tokens = self.cap   # a warm start: first hedges allowed
        self._lock = threading.Lock()

    def earn(self):
        with self._lock:
            self._tokens = min(self.cap, self._tokens + self.ratio)

    def try_spend(self):
        with self._lock:
            if self._tokens >= 1.0:
                self._tokens -= 1.0
                return True
            return False

    def tokens(self):
        with self._lock:
            return self._tokens


class _Backend:
    """Router-side view of one serving host (mutated only under the
    pool lock)."""

    __slots__ = ("addr", "rank", "live", "depth", "fails",
                 "heal_streak", "last_ok", "evicted_reason")

    def __init__(self, addr, rank=None):
        self.addr = str(addr)
        self.rank = rank
        self.live = True
        self.depth = None           # last known queue depth (None: blind)
        self.fails = 0              # consecutive connect/forward failures
        self.heal_streak = 0        # consecutive healthy probes while out
        self.last_ok = _world.monotonic()  # admission grace at birth
        self.evicted_reason = None


class BackendPool:
    """The routing policy core — membership, depth, eviction,
    re-admission.  Pure bookkeeping over the ``world`` clock seam (no
    sockets), so the cluster simulator exercises the exact policy the
    live router runs; :class:`RouterServer` owns the HTTP on both
    sides of it.

    Args:
      addrs: ``host:port`` backend addresses.
      ranks: optional per-backend pod ranks, aligning each backend
        with its heartbeat file when ``coord_dir`` is set (default:
        list position).
      fail_threshold / stale_s / readmit_checks: eviction and
        re-admission policy; default to the ``DK_ROUTE_*`` knobs.
      coord_dir / world_size / session: the pod's coordination dir —
        when set, ``sweep`` folds ``coordination.dead_peers_at``
        heartbeat evidence (beat once, went dark) into eviction and
        blocks re-admission of a heartbeat-dead rank.
    """

    def __init__(self, addrs, ranks=None, fail_threshold=None,
                 stale_s=None, readmit_checks=None, coord_dir=None,
                 world_size=None, session=None):
        addrs = [str(a) for a in addrs]
        if not addrs:
            raise ValueError("BackendPool needs at least one backend")
        if ranks is None:
            ranks = list(range(len(addrs)))
        self.fail_threshold = int(fail_threshold
                                  if fail_threshold is not None
                                  else knobs.get("DK_ROUTE_FAILS"))
        self.stale_s = float(stale_s if stale_s is not None
                             else knobs.get("DK_ROUTE_STALE_S"))
        self.readmit_checks = int(
            readmit_checks if readmit_checks is not None
            else knobs.get("DK_ROUTE_READMIT_CHECKS"))
        self.coord_dir = coord_dir
        self.world_size = (int(world_size) if world_size is not None
                           else len(addrs))
        self.session = session
        self._lock = threading.Lock()
        self._backends = {a: _Backend(a, rank=r)
                          for a, r in zip(addrs, ranks)}
        self._rr = 0
        self.evictions = 0
        self.readmissions = 0
        self._gauge_live = _metrics.gauge("route.backends_live")
        self._gauge_live.set(len(addrs))

    def addrs(self):
        with self._lock:
            return list(self._backends)

    # -- evidence intake ------------------------------------------------
    def note_probe(self, addr, healthy, depth=None):
        """Record one health-probe outcome (healthy + last known queue
        depth).  Healthy probes build an evicted backend's heal streak;
        unhealthy ones reset it and count toward the fail threshold."""
        transitions = []
        with self._lock:
            b = self._backends[addr]
            if healthy:
                b.last_ok = _world.monotonic()
                b.fails = 0
                b.depth = depth
                if not b.live:
                    b.heal_streak += 1
            else:
                b.depth = None
                b.heal_streak = 0
                b.fails += 1
                if b.live and b.fails >= self.fail_threshold:
                    transitions.append(self._evict_locked(
                        b, "consecutive_failures"))
        self._emit(transitions)

    def note_forward(self, addr, ok):
        """Record one forward outcome.  ``ok=False`` (connect-level
        failure) counts toward the fail threshold and evicts at it —
        the data path notices a dead host faster than the probe
        cadence."""
        transitions = []
        with self._lock:
            b = self._backends.get(addr)
            if b is None:
                return
            if ok:
                b.fails = 0
            else:
                b.heal_streak = 0
                b.fails += 1
                if b.live and b.fails >= self.fail_threshold:
                    transitions.append(self._evict_locked(
                        b, "consecutive_failures"))
        self._emit(transitions)

    def sweep(self):
        """One policy pass: evict on stale health / dead heartbeat,
        re-admit on a full heal streak.  The prober calls this once per
        round; the simulator calls it from scripted time."""
        dead_ranks = set()
        if self.coord_dir is not None:
            try:
                dead_ranks = set(coordination.dead_peers_at(
                    self.coord_dir, self.world_size,
                    stale_after_s=self.stale_s, require_file=True,
                    session=self.session))
            except OSError:
                dead_ranks = set()  # unreadable coord dir: no evidence
        transitions = []
        now = _world.monotonic()
        with self._lock:
            for b in self._backends.values():
                hb_dead = b.rank in dead_ranks
                if b.live:
                    if hb_dead:
                        transitions.append(self._evict_locked(
                            b, "heartbeat_dead"))
                    elif now - b.last_ok > self.stale_s:
                        transitions.append(self._evict_locked(
                            b, "stale_health"))
                elif (b.heal_streak >= self.readmit_checks
                        and not hb_dead):
                    b.live = True
                    b.evicted_reason = None
                    b.fails = 0
                    b.heal_streak = 0
                    self.readmissions += 1
                    transitions.append(("route_readmit", b.addr,
                                        "healed"))
            live = sum(1 for b in self._backends.values() if b.live)
            self._gauge_live.set(live)
        self._emit(transitions)

    def _evict_locked(self, b, reason):
        b.live = False
        b.depth = None
        b.heal_streak = 0
        b.evicted_reason = reason
        self.evictions += 1
        return ("route_evict", b.addr, reason)

    def _emit(self, transitions):
        # events + counters OUTSIDE the pool lock: the event writer and
        # counter leaf locks stay strictly independent of _lock
        for kind, addr, reason in transitions:
            if kind == "route_evict":
                _metrics.counter("route.evictions").inc()
            else:
                _metrics.counter("route.readmissions").inc()
            # dklint: events=route_evict,route_readmit
            events.emit(kind, backend=addr, reason=reason)

    # -- placement ------------------------------------------------------
    def pick(self, exclude=()):
        """-> the backend address to place a request on, or None when
        no live candidate remains.  Least-loaded by last known depth
        when EVERY candidate's depth is known; any blind candidate
        degrades the pick to round-robin (fair, never starving)."""
        with self._lock:
            cands = [b for b in self._backends.values()
                     if b.live and b.addr not in exclude]
            if not cands:
                return None
            if all(b.depth is not None for b in cands):
                best = min(b.depth for b in cands)
                cands = [b for b in cands if b.depth == best]
            pick = cands[self._rr % len(cands)]
            self._rr = (self._rr + 1) % max(
                1, len(self._backends))
            return pick.addr

    def live_count(self):
        with self._lock:
            return sum(1 for b in self._backends.values() if b.live)

    def snapshot(self):
        """JSON-ready per-backend state — the ``/metricsz`` payload."""
        with self._lock:
            return [{"addr": b.addr, "rank": b.rank, "live": b.live,
                     "depth": b.depth, "fails": b.fails,
                     "heal_streak": b.heal_streak,
                     "evicted_reason": b.evicted_reason}
                    for b in self._backends.values()]


class _Handler(BaseHTTPRequestHandler):
    server_version = "dk-route/0.1"
    protocol_version = "HTTP/1.1"
    _trace_header = None  # per-request traceparent echo (do_POST sets it)

    def log_message(self, fmt, *args):  # quiet: the event log is the log
        pass

    def _reply(self, code, payload, retry_after=None):
        self._reply_text(code, json.dumps(payload), "application/json",
                         retry_after=retry_after)

    def _reply_bytes(self, code, body, content_type, retry_after=None,
                     trace=None):
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        if retry_after is not None:
            self.send_header("Retry-After", str(retry_after))
        if trace is None:
            trace = self._trace_header
        if trace is not None:
            # response names the route.forward hop the caller's trace
            # continued into — same correlation contract as the backend
            self.send_header("traceparent", trace)
        self.end_headers()
        self.wfile.write(body)

    def _reply_text(self, code, text, content_type, retry_after=None):
        self._reply_bytes(code, text.encode("utf-8"), content_type,
                          retry_after=retry_after)

    def do_GET(self):
        srv = self.server
        self._trace_header = None  # keep-alive: no stale POST echo
        path, _, query = self.path.partition("?")
        if path == "/healthz":
            live = srv.pool.live_count()
            if srv.draining:
                self._reply(503, {"status": "draining"})
            else:
                self._reply(200, {"status": "routing",
                                  "backends_live": live,
                                  "backends": len(srv.pool.addrs())})
        elif path == "/metricsz":
            if "format=prometheus" in query:
                from dist_keras_tpu.observability import prometheus

                self._reply_text(
                    200, prometheus.render(extra_gauges={
                        "route.pool.live": srv.pool.live_count()}),
                    prometheus.CONTENT_TYPE)
            else:
                self._reply(200, {"router": srv.pool.snapshot(),
                                  "registry": _metrics.snapshot()})
        elif path == "/statusz":
            from dist_keras_tpu.observability import statusz

            self._reply_text(
                200,
                statusz.render(extra={"router": srv.pool.snapshot()}),
                "application/json")
        elif path == "/tracez":
            from dist_keras_tpu.observability import flight

            self._reply_text(200, json.dumps(flight.tracez_doc(),
                                             default=str),
                             "application/json")
        else:
            self._reply(404, {"error": "not_found", "path": self.path})

    def do_POST(self):
        srv = self.server
        self._trace_header = None
        # body FIRST, unconditionally — replying before consuming it
        # would poison the keep-alive connection's framing
        n = int(self.headers.get("Content-Length", 0))
        body = self.rfile.read(n)
        path = self.path.split("?")[0]
        if path not in ("/predict", "/generate"):
            self._reply(404, {"error": "not_found", "path": self.path})
            return
        if srv.draining:
            self._reply(503, {"error": "draining"}, retry_after=1)
            return
        _metrics.counter("route.requests").inc()
        # end-to-end deadline + priority ride headers across the hop
        # (the body forwards verbatim, so headers are the only channel
        # that survives without a rewrite)
        fwd_headers = {}
        for h in ("x-dk-deadline-s", "x-dk-priority"):
            v = self.headers.get(h)
            if v is not None:
                fwd_headers[h] = v
        # the forward hop runs under ONE route.forward span continuing
        # the caller's trace; the traceparent sent DOWN names this span,
        # so the backend's serve.request parents to the router's hop —
        # one stitched trace across router -> host -> replica
        ctx = spans.parse_traceparent(self.headers.get("traceparent"))
        with spans.resume(ctx):
            with spans.span("route.forward", n_bytes=len(body)):
                self._trace_header = spans.traceparent()
                if path == "/generate" and _wants_stream(body):
                    # streaming relay replies chunked from inside —
                    # including the typed final record on backend loss
                    srv.relay_stream(self, body, headers=fwd_headers)
                    return
                if path == "/generate":
                    code, payload, ctype, retry_after = \
                        srv.forward_generate(body, headers=fwd_headers)
                else:
                    code, payload, ctype, retry_after = srv.forward(
                        body, path=path, headers=fwd_headers)
        self._reply_bytes(code, payload, ctype, retry_after=retry_after)


def _wants_stream(body):
    """True when a ``/generate`` body asks for token streaming (a
    bare token list never does; unparseable bodies fall through to
    the buffered path, whose backend will 400 them typed)."""
    try:
        doc = json.loads(body.decode("utf-8"))
    except (ValueError, UnicodeDecodeError):
        return False
    return isinstance(doc, dict) and bool(doc.get("stream", False))


class RouterServer(ThreadingHTTPServer):
    """Threaded HTTP router over one :class:`BackendPool`.

    ``backends`` is the ``host:port`` list (or a prebuilt pool via
    ``pool=``); ``port=None`` binds :func:`default_route_port` (the
    ``DK_ROUTE_PORT`` launch export), ``port=0`` picks a free one.
    Lifecycle mirrors :class:`~.server.ServingServer`: ``start()`` /
    ``install_signal_drain()`` / ``drain()`` / ``run_forever()`` /
    ``close()``.
    """

    daemon_threads = True

    def __init__(self, backends=(), host="127.0.0.1", port=0,
                 pool=None, probe_s=None, forward_timeout_s=30.0,
                 probe_timeout_s=1.0, **pool_kw):
        self.pool = pool if pool is not None \
            else BackendPool(backends, **pool_kw)
        self.probe_s = float(probe_s if probe_s is not None
                             else knobs.get("DK_ROUTE_PROBE_S"))
        self.forward_timeout_s = float(forward_timeout_s)
        self.probe_timeout_s = float(probe_timeout_s)
        self.preempted_signum = None
        self.draining = False
        self._stop_watch = None
        self._thread = None
        self._probe_thread = None
        self._probe_stop = threading.Event()
        self._retry = RetryPolicy(
            attempts=2, backoff=0.02, jitter=0.0,
            retryable=(ForwardError,), name="route.forward")
        self._m_forward = _metrics.histogram("route.forward_s")
        self._hedge_budget = _HedgeBudget()
        # lifecycle guard: BaseServer.shutdown() BLOCKS FOREVER unless
        # serve_forever is actually running — same hazard and cure as
        # ServingServer
        self._lifecycle = threading.Lock()
        self._serving = False
        self._stopping = False
        if port is None:
            port = default_route_port(fallback=0)
        super().__init__((host, int(port)), _Handler)

    @property
    def address(self):
        """(host, bound_port) — port resolved after bind."""
        return self.server_address[:2]

    # -- forwarding -----------------------------------------------------
    def forward(self, body, path="/predict", headers=None):
        """Place one ``/predict`` or ``/generate`` body on a live
        backend; -> (status, body bytes, content type, retry_after).
        Connect failures and backend 503s burn the attempt and move to
        a SIBLING (excluded set) through the ``route.forward`` retry
        surface — at most one re-send, idempotent because an admission
        either lands whole or is typed-rejected at the backend's door
        (``/generate`` included: a 503 ``kv_exhausted`` moves the
        request to a sibling with free pages).  Exhaustion and an empty
        pool are typed 503 + Retry-After.  ``headers`` carries hop
        headers (``x-dk-deadline-s`` / ``x-dk-priority``) verbatim.
        Non-streaming ``/generate`` goes through
        :meth:`forward_generate` (hedging); ``stream: true`` bodies
        through :meth:`relay_stream` (chunk-for-chunk with a typed
        final record on backend loss)."""
        t0 = _world.monotonic()
        excluded = set()

        def attempt():
            fault_point("route.forward")
            addr = self.pool.pick(exclude=excluded)
            if addr is None:
                raise NoBackends(live=self.pool.live_count(),
                                 total=len(self.pool.addrs()))
            hdrs = {"Content-Type": "application/json"}
            hdrs.update(headers or {})
            tp = spans.traceparent()  # None with tracing off
            if tp is not None:
                hdrs["traceparent"] = tp
            req = urllib.request.Request(
                f"http://{addr}{path}", data=body, method="POST",
                headers=hdrs)
            try:
                with urllib.request.urlopen(
                        req, timeout=self.forward_timeout_s) as resp:
                    code, data = resp.status, resp.read()
                    ctype = resp.headers.get("Content-Type",
                                             "application/json")
                    retry_after = resp.headers.get("Retry-After")
            except urllib.error.HTTPError as e:
                # an HTTP status IS a backend answer, not a transport
                # failure — read it fully (keep-alive framing)
                code, data = e.code, e.read()
                ctype = e.headers.get("Content-Type",
                                      "application/json")
                retry_after = e.headers.get("Retry-After")
            except (OSError, urllib.error.URLError) as e:
                # connect-level failure: evidence against the backend,
                # sibling retry for the request
                self.pool.note_forward(addr, ok=False)
                excluded.add(addr)
                raise ForwardError(addr, e) from e
            self.pool.note_forward(addr, ok=True)
            if code == 503:
                # the backend is shedding load or draining — reachable
                # (no eviction evidence), but this REQUEST moves on
                excluded.add(addr)
                raise ForwardError(addr, "backend 503")
            return code, data, ctype, retry_after

        try:
            code, data, ctype, retry_after = self._retry.call(attempt)
        except NoBackends as e:
            _metrics.counter("route.errors").inc()
            return (503, json.dumps(
                {"error": "no_backends", "live": e.live,
                 "total": e.total}).encode("utf-8"),
                "application/json", 1)
        except ForwardError as e:
            # both attempts burned (retry_exhausted already recorded on
            # the surface): typed 503, the caller's whole-request retry
            _metrics.counter("route.errors").inc()
            return (503, json.dumps(
                {"error": "backends_unavailable",
                 "detail": str(e)[:200]}).encode("utf-8"),
                "application/json", 1)
        finally:
            self._m_forward.observe(_world.monotonic() - t0)
        return code, data, ctype, retry_after

    # -- hedged /generate -----------------------------------------------
    def _hedge_delay(self):
        """Seconds to wait before hedging, or None when hedging is
        ineligible: the knob disables it, or too few ``route.forward_s``
        samples exist to trust a tail estimate (an uninformed hedge is
        just a doubled request)."""
        q = float(knobs.get("DK_ROUTE_HEDGE_QUANTILE"))
        if q <= 0:
            return None
        s = self._m_forward.summary()
        if s["count"] < 20:
            return None
        q = min(max(q, 0.5), 0.999)
        return s["p99"] if q >= 0.99 else s["p95"]

    def forward_generate(self, body, headers=None):
        """Non-streaming ``/generate``: the hedged path when the
        tail-latency evidence, a live sibling and the retry budget all
        allow it, else the plain :meth:`forward`."""
        self._hedge_budget.earn()
        delay = self._hedge_delay()
        if delay is None or self.pool.live_count() < 2:
            return self.forward(body, path="/generate",
                                headers=headers)
        try:
            doc = json.loads(body.decode("utf-8"))
        except (ValueError, UnicodeDecodeError):
            doc = None
        if not isinstance(doc, dict) or "tokens" not in doc:
            # bare-list or malformed bodies: the buffered path's
            # backend answers them typed (200 or 400)
            return self.forward(body, path="/generate",
                                headers=headers)
        return self._hedged_generate(doc, headers, delay)

    def _hedged_generate(self, doc, headers, delay):
        """Race a primary against (at most) one budget-gated hedge.
        Both attempts run the BACKEND's streaming surface — the body is
        rewritten to ``stream: true`` and the NDJSON reassembled into
        the batched result doc — because a buffered ``/generate`` hop
        cannot be cancelled: the backend handler sits in
        ``gen.result()`` until the doc is done whether anyone is
        listening or not.  On the streaming surface, closing the
        loser's socket makes its next token write fail, and the
        backend's own disconnect path cancels the generation (slot and
        KV pages reclaim).  First complete answer wins."""
        t0 = _world.monotonic()
        sdoc = dict(doc)
        sdoc["stream"] = True
        sbody = json.dumps(sdoc).encode("utf-8")
        try:
            prompt = [int(t) for t in doc.get("tokens", [])]
        except (TypeError, ValueError):
            prompt = []
        resq = _queue.Queue()
        conns = []
        conns_lock = threading.Lock()
        settled = threading.Event()   # a winner exists: losers hush

        def run(addr, hedge):
            host, _, port = addr.rpartition(":")
            conn = http.client.HTTPConnection(
                host, int(port), timeout=self.forward_timeout_s)
            with conns_lock:
                conns.append(conn)
            try:
                hdrs = {"Content-Type": "application/json"}
                hdrs.update(headers or {})
                tp = spans.traceparent()
                if tp is not None:
                    hdrs["traceparent"] = tp
                conn.request("POST", "/generate", sbody, hdrs)
                resp = conn.getresponse()
                if resp.status != 200:
                    data = resp.read()
                    if resp.status == 503:
                        # backend shedding: a failed attempt, the
                        # other arm (or the sibling retry) decides
                        resq.put(("err", ForwardError(addr,
                                                      "backend 503"),
                                  addr, hedge))
                    else:
                        # a non-503 status IS an answer: verbatim
                        resq.put(("http", (resp.status, data,
                                           resp.headers.get(
                                               "Content-Type",
                                               "application/json"),
                                           resp.headers.get(
                                               "Retry-After")),
                                  addr, hedge))
                    return
                toks = []
                final = None
                while True:
                    line = resp.readline()
                    if not line:
                        break
                    rec = json.loads(line)
                    if "token" in rec:
                        toks.append(int(rec["token"]))
                    if rec.get("done"):
                        final = rec
                        break
                if final is None:
                    raise ForwardError(addr, "stream truncated")
                self.pool.note_forward(addr, ok=True)
                if "error" in final:
                    # the backend's typed decode failure: an answer,
                    # not transport loss — map like _generate's 500
                    resq.put(("http", (500, json.dumps(
                        {"error": final["error"],
                         "detail": final.get("detail", "")}
                    ).encode("utf-8"), "application/json", None),
                        addr, hedge))
                    return
                out = {"tokens": prompt + toks, "generated": toks,
                       "prompt_len": final.get("prompt_len"),
                       "steps": final.get("steps"),
                       "ttft_s": final.get("ttft_s"),
                       "finish": final.get("finish"),
                       "recoveries": final.get("recoveries")}
                resq.put(("ok", out, addr, hedge))
            except (OSError, http.client.HTTPException,
                    ValueError) as e:
                if settled.is_set():
                    return   # cancelled loser: not evidence
                self.pool.note_forward(addr, ok=False)
                resq.put(("err", e, addr, hedge))
            finally:
                conn.close()

        primary = self.pool.pick()
        if primary is None:
            _metrics.counter("route.errors").inc()
            return (503, json.dumps(
                {"error": "no_backends",
                 "live": self.pool.live_count(),
                 "total": len(self.pool.addrs())}).encode("utf-8"),
                "application/json", 1)
        attempted = {primary}
        threading.Thread(target=run, args=(primary, False),
                         daemon=True).start()
        inflight = 1
        got = None
        try:
            got = resq.get(timeout=delay)
        except _queue.Empty:
            hedge_addr = self.pool.pick(exclude=attempted)
            if hedge_addr is not None \
                    and self._hedge_budget.try_spend():
                _metrics.counter("route.hedges").inc()
                events.emit("route_hedge", primary=primary,
                            hedge=hedge_addr,
                            delay_s=round(delay, 6))
                attempted.add(hedge_addr)
                threading.Thread(target=run,
                                 args=(hedge_addr, True),
                                 daemon=True).start()
                inflight = 2
            elif hedge_addr is not None:
                _metrics.counter("route.hedge_denied").inc()
        win = None
        answer = None
        last_err = None
        retried = False
        deadline = t0 + self.forward_timeout_s
        while True:
            if got is not None:
                kind = got[0]
                if kind == "ok":
                    win = got
                    break
                if kind == "http":
                    answer = got
                    break
                inflight -= 1
                last_err = got[1]
                got = None
                if inflight == 0:
                    if not retried:
                        # the plain path's sibling re-send, preserved:
                        # a fast connect failure must not end the
                        # request just because hedging was armed
                        retried = True
                        sib = self.pool.pick(exclude=attempted)
                        if sib is not None:
                            attempted.add(sib)
                            threading.Thread(
                                target=run, args=(sib, False),
                                daemon=True).start()
                            inflight = 1
                            continue
                    break
                continue
            rem = deadline - _world.monotonic()
            if rem <= 0:
                break
            try:
                got = resq.get(timeout=rem)
            except _queue.Empty:
                break
        settled.set()
        with conns_lock:
            for c in conns:
                # closing a loser's socket IS its cancellation: the
                # backend's next token write fails and its disconnect
                # path frees the slot + KV pages
                c.close()
        self._m_forward.observe(_world.monotonic() - t0)
        if win is not None:
            _, out, addr, was_hedge = win
            if was_hedge:
                _metrics.counter("route.hedge_wins").inc()
            return (200, json.dumps(out).encode("utf-8"),
                    "application/json", None)
        if answer is not None:
            return answer[1]
        _metrics.counter("route.errors").inc()
        detail = (str(last_err)[:200] if last_err is not None
                  else "hedged generate timed out")
        return (503, json.dumps(
            {"error": "backends_unavailable",
             "detail": detail}).encode("utf-8"),
            "application/json", 1)

    # -- streaming relay ------------------------------------------------
    def relay_stream(self, handler, body, headers=None):
        """Relay a ``stream: true`` ``/generate`` chunk-for-chunk.
        Pre-byte failures (connect, backend 503) move to a sibling
        with the same evidence accounting as :meth:`forward`; once
        token bytes have flowed the request is pinned to its backend —
        a backend dying MID-STREAM ends the response with a final
        typed NDJSON record (``{"error": "backend_stream_lost",
        "retryable": true}``) so the client sees a typed, resumable
        loss instead of a truncated stream.  Replies directly through
        ``handler`` (chunked)."""
        t0 = _world.monotonic()
        excluded = set()
        resp = None
        addr = None
        for _ in range(2):
            try:
                fault_point("route.forward")
            # dklint: ignore[broad-except] an injected route.forward fault burns this attempt; exhaustion is a typed 503
            except Exception:
                excluded.add(f"fault-{len(excluded)}")
                continue
            addr = self.pool.pick(exclude=excluded)
            if addr is None:
                break
            hdrs = {"Content-Type": "application/json"}
            hdrs.update(headers or {})
            tp = spans.traceparent()
            if tp is not None:
                hdrs["traceparent"] = tp
            req = urllib.request.Request(
                f"http://{addr}/generate", data=body, method="POST",
                headers=hdrs)
            try:
                resp = urllib.request.urlopen(
                    req, timeout=self.forward_timeout_s)
                break
            except urllib.error.HTTPError as e:
                data = e.read()
                if e.code == 503:
                    excluded.add(addr)   # shedding: sibling retry
                    continue
                handler._reply_bytes(     # an answer: verbatim
                    e.code, data,
                    e.headers.get("Content-Type", "application/json"),
                    retry_after=e.headers.get("Retry-After"))
                self._m_forward.observe(_world.monotonic() - t0)
                return
            except (OSError, urllib.error.URLError):
                self.pool.note_forward(addr, ok=False)
                excluded.add(addr)
                continue
        if resp is None:
            _metrics.counter("route.errors").inc()
            handler._reply(503, {"error": "backends_unavailable",
                                 "live": self.pool.live_count(),
                                 "total": len(self.pool.addrs())},
                           retry_after=1)
            self._m_forward.observe(_world.monotonic() - t0)
            return
        self.pool.note_forward(addr, ok=True)
        handler.send_response(200)
        handler.send_header("Content-Type", "application/x-ndjson")
        handler.send_header("Transfer-Encoding", "chunked")
        if handler._trace_header is not None:
            handler.send_header("traceparent", handler._trace_header)
        handler.end_headers()

        def chunk(data):
            handler.wfile.write(b"%x\r\n" % len(data) + data + b"\r\n")
            handler.wfile.flush()

        try:
            saw_done = False
            while True:
                try:
                    line = resp.readline()
                    if not line:
                        # chunked readline() swallows a mid-framing
                        # close as plain EOF (IncompleteRead is eaten
                        # by peek) — EOF without a ``done`` record IS
                        # the truncation signal
                        err = None if saw_done else "eof"
                except (OSError, http.client.HTTPException) as e:
                    err = type(e).__name__
                    line = b""
                if not line:
                    if err is not None:
                        # the backend died mid-stream: typed final
                        # record + forward evidence against it — never
                        # a silently truncated stream
                        self.pool.note_forward(addr, ok=False)
                        _metrics.counter("route.stream_errors").inc()
                        events.emit("route_stream_error", backend=addr,
                                    error=err)
                        chunk((json.dumps(
                            {"done": True,
                             "error": "backend_stream_lost",
                             "backend": addr, "retryable": True})
                            + "\n").encode("utf-8"))
                    break
                saw_done = saw_done or b'"done"' in line
                chunk(line)
            handler.wfile.write(b"0\r\n\r\n")
        except OSError:
            # OUR client went away mid-relay: closing the backend
            # response (finally) propagates the cancel downstream —
            # the backend's disconnect path frees the slot + pages
            pass
        finally:
            resp.close()
            self._m_forward.observe(_world.monotonic() - t0)

    # -- health probing -------------------------------------------------
    def probe_once(self):
        """One probe round over every backend + a policy sweep (the
        background loop's body; tests and the drain path call it
        directly)."""
        for addr in self.pool.addrs():
            healthy, depth = self._probe_backend(addr)
            self.pool.note_probe(addr, healthy, depth=depth)
        self.pool.sweep()

    def _probe_backend(self, addr):
        """-> (healthy, queue_depth_or_None).  A malformed or missing
        /metricsz leaves depth None — the pool degrades that backend's
        pick to round-robin rather than judging it on garbage."""
        try:
            fault_point("route.health")
            with urllib.request.urlopen(
                    f"http://{addr}/healthz",
                    timeout=self.probe_timeout_s) as resp:
                healthy = resp.status == 200
        # dklint: ignore[broad-except] probe failure (incl. injected route.health faults) IS the unhealthy verdict
        except Exception:
            return False, None
        if not healthy:
            return False, None
        try:
            with urllib.request.urlopen(
                    f"http://{addr}/metricsz",
                    timeout=self.probe_timeout_s) as resp:
                doc = json.loads(resp.read().decode("utf-8"))
            depth = doc["engine"]["outstanding"]
            if not isinstance(depth, (int, float)) \
                    or isinstance(depth, bool):
                raise ValueError("non-numeric depth")
            return True, int(depth)
        # dklint: ignore[broad-except] malformed metricsz degrades to depth-blind round-robin, never an eviction
        except Exception:
            return True, None

    def _health_loop(self):
        while not self._probe_stop.is_set():
            self.probe_once()
            self._probe_stop.wait(self.probe_s)

    # -- lifecycle ------------------------------------------------------
    def serve_forever(self, poll_interval=0.5):
        with self._lifecycle:
            if self._stopping:
                return  # a drain/close already won the race: stay down
            self._serving = True
        try:
            super().serve_forever(poll_interval)
        finally:
            with self._lifecycle:
                self._serving = False

    def _stop_listener(self):
        with self._lifecycle:
            self._stopping = True
            serving = self._serving
        if serving:
            self.shutdown()
        self.server_close()

    def start(self):
        """Serve + probe on background threads; -> (host, port)."""
        from dist_keras_tpu.observability import timeseries

        timeseries.maybe_start_sampler()
        self._probe_stop.clear()
        self._probe_thread = threading.Thread(
            target=self._health_loop, daemon=True,
            name="dk-route-health")
        self._probe_thread.start()
        self._thread = threading.Thread(
            target=self.serve_forever, daemon=True, name="dk-route-http")
        self._thread.start()
        events.emit("serve_listen", host=self.address[0],
                    port=self.address[1], role="router")
        return self.address

    def install_signal_drain(self, poll_s=0.05):
        """SIGTERM/SIGINT -> graceful drain via ``resilience.
        preemption`` (flag-only handler + watcher thread), exactly like
        :meth:`ServingServer.install_signal_drain`."""
        installed = preemption.install(strict=False)
        self._stop_watch = preemption.on_request(self._drain_on_signal,
                                                 poll_s=poll_s)
        return installed

    def _drain_on_signal(self, signum):
        self.preempted_signum = signum
        events.emit("serve_drain_signal", signum=signum, role="router")
        self.drain()

    def drain(self):
        """Stop admitting (``/predict`` and ``/healthz`` answer typed
        503s), stop the prober, stop the listener.  In-flight forwards
        finish on their handler threads; the router holds no queue of
        its own, so there is nothing to flush — admitted requests live
        in the BACKENDS' no-drop contract."""
        self.draining = True
        events.emit("serve_drain_begin", role="router")
        self._stop_probe()
        self._stop_listener()
        events.emit("serve_drain", role="router")
        from dist_keras_tpu.observability import flight, timeseries

        # same end-of-life telemetry contract as ServingServer.drain:
        # flush undecided retention buffers (route.forward traces) and
        # run one final sampler tick so an incident landing just
        # before the drain still fires its SLO evaluation
        flight.retain_flush()
        sampler = timeseries.get_sampler()
        if sampler is not None:
            sampler.tick()
            if sampler.watchdog is not None:
                sampler.watchdog.quiesce()

    def _stop_probe(self):
        self._probe_stop.set()
        if self._probe_thread is not None:
            self._probe_thread.join(timeout=5)

    def run_forever(self):
        """Serve on the CALLING thread until stopped; re-raises
        :class:`Preempted` after a signal drain (exit ``128+signum``,
        the scheduler convention)."""
        from dist_keras_tpu.observability import timeseries

        timeseries.maybe_start_sampler()
        if self._probe_thread is None \
                or not self._probe_thread.is_alive():
            self._probe_stop.clear()
            self._probe_thread = threading.Thread(
                target=self._health_loop, daemon=True,
                name="dk-route-health")
            self._probe_thread.start()
        try:
            self.serve_forever()
        finally:
            self.server_close()
        if self.preempted_signum is not None:
            raise preemption.Preempted(self.preempted_signum)

    def close(self):
        if self._stop_watch is not None:
            self._stop_watch()
        self._stop_probe()
        self._stop_listener()


def main(argv=None):
    """CLI: ``python -m dist_keras_tpu.serving.router`` — backends
    from ``DK_ROUTE_BACKENDS`` (or ``--backends host:port,...``), port
    from ``DK_ROUTE_PORT`` (or ``--port``); serves until SIGTERM, then
    drains and exits ``128+signum``.  This is the entry point
    ``launch.Job(route_port=...)`` wires per pod."""
    import argparse

    ap = argparse.ArgumentParser(prog="dist_keras_tpu.serving.router")
    ap.add_argument("--backends", default=None,
                    help="comma-separated host:port list "
                         "(default: DK_ROUTE_BACKENDS)")
    ap.add_argument("--port", type=int, default=None,
                    help="bind port (default: DK_ROUTE_PORT, else 8080)")
    ap.add_argument("--host", default="0.0.0.0")
    ap.add_argument("--coord-dir", default=None,
                    help="pod coordination dir for heartbeat evidence")
    ap.add_argument("--world-size", type=int, default=None)
    args = ap.parse_args(argv)
    raw = args.backends or knobs.raw("DK_ROUTE_BACKENDS") or ""
    backends = [a.strip() for a in raw.split(",") if a.strip()]
    if not backends:
        ap.error("no backends: pass --backends or set "
                 "DK_ROUTE_BACKENDS")
    srv = RouterServer(
        backends, host=args.host,
        port=args.port if args.port is not None
        else default_route_port(),
        coord_dir=args.coord_dir, world_size=args.world_size)
    srv.install_signal_drain()
    events.emit("serve_listen", host=srv.address[0],
                port=srv.address[1], role="router")
    srv.run_forever()  # starts the prober itself; foreground serve
    return 0


if __name__ == "__main__":  # pragma: no cover - CLI entry
    raise SystemExit(main())
