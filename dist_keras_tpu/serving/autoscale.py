"""Closed-loop replica autoscaling — actuate what the watchdog alerts.

The ``QueueDepthGrowth`` watchdog rule (round 11) already recognizes a
serving host falling behind: the ``serve.pending`` gauge rising
monotonically across sampler ticks, ending at depth.  This module
closes the loop: the SAME ramp signature (plus an optional latency-p99
breach) actuates :meth:`ServingEngine.resize` between a floor and a
ceiling, with the watchdog's ``clear_checks``-style hysteresis so
noise never oscillates the replica set.

Decision rule per :meth:`ReplicaAutoscaler.tick`:

- **Scale up** (by ``step``, bounded by ``ceiling``) when the last
  ``samples`` points of the ``serve.pending`` time-series ring are
  non-decreasing, strictly grew, and end at/above ``depth_high`` —
  exactly :class:`~dist_keras_tpu.observability.watchdog.
  QueueDepthGrowth`'s firing condition — OR when the engine's
  ``serve.predict_s`` windowed p99 exceeds ``p99_high_s`` (when set).
- **Scale down** (by ``step``, bounded by ``floor``) only after
  ``clear_checks`` CONSECUTIVE calm ticks (queue at/below
  ``depth_low`` and no ramp) — one quiet tick proves nothing, the
  same reasoning as the watchdog's consecutive-clear hysteresis.
- **Cooldown**: after ANY resize, ``cooldown_checks`` ticks must pass
  before the next one — the new replica set gets to absorb the
  backlog before being judged.

Every actuation emits ``autoscale_resize`` (direction, from, to,
evidence) + the ``autoscale.resizes`` counter; the ``autoscale.
replicas`` gauge tracks the current target.  The decision core is
:meth:`tick` — the background loop is just a cadence around it, so
tests and the simulator drive single deterministic ticks directly.

The scaler needs the time-series sampler to be feeding the
``serve.pending`` ring (``DK_OBS_SAMPLE_S`` — ``ServingServer.start``
wires it); without samples it holds still, which is the safe failure
mode for an actuator.
"""

from __future__ import annotations

import threading

import numpy as np

from dist_keras_tpu.observability import events, metrics, timeseries


class ReplicaAutoscaler:
    """Drive ``engine.resize`` from the ``serve.*`` telemetry rings.

    Args:
      engine: anything with ``resize(n)`` and ``stats()`` returning a
        ``"replicas"`` count (:class:`ServingEngine`, or
        :class:`~.reload.BlueGreenEngine` which fans resize to both
        colors).
      floor / ceiling: replica-count bounds (inclusive).
      interval_s: background-loop tick cadence.
      depth_high: ramp must END at/above this queue depth to scale up
        (the ``QueueDepthGrowth`` ``min_depth`` twin).
      depth_low: queue at/below this counts as a calm tick (default
        ``depth_high // 4``).
      p99_high_s: optional latency SLO — a ``serve.predict_s`` p99
        above it scales up even without a ramp.
      slo_signal: consume the SLO plane (round 22): any objective the
        default ``slo`` registry reports as burning past the
        multi-window thresholds counts as scale-up evidence alongside
        the ramp — inert unless ``DK_SLO`` is armed (``slo.breaching``
        returns ``[]`` when off).  Default True.
      samples: ring points the ramp test inspects.
      clear_checks: consecutive calm ticks before a scale-down.
      cooldown_checks: ticks held still after any resize.
      step: replicas added/removed per actuation.
    """

    def __init__(self, engine, floor=1, ceiling=8, interval_s=1.0,
                 depth_high=16, depth_low=None, p99_high_s=None,
                 slo_signal=True, samples=5, clear_checks=3,
                 cooldown_checks=2, step=1):
        if not 1 <= int(floor) <= int(ceiling):
            raise ValueError(
                f"need 1 <= floor ({floor}) <= ceiling ({ceiling})")
        self.engine = engine
        self.floor = int(floor)
        self.ceiling = int(ceiling)
        self.interval_s = float(interval_s)
        self.depth_high = float(depth_high)
        self.depth_low = (float(depth_low) if depth_low is not None
                          else self.depth_high / 4.0)
        self.p99_high_s = (None if p99_high_s is None
                           else float(p99_high_s))
        self.slo_signal = bool(slo_signal)
        self.samples = int(samples)
        self.clear_checks = int(clear_checks)
        self.cooldown_checks = int(cooldown_checks)
        self.step = int(step)
        self.resizes = 0
        self._calm_streak = 0
        self._cooldown = 0
        self._stop = threading.Event()
        self._thread = None
        self._gauge = metrics.gauge("autoscale.replicas")
        self._gauge.set(self._replicas())

    def _replicas(self):
        return int(self.engine.stats()["replicas"])

    def _ramp(self):
        """-> (firing, last_depth) over the serve.pending ring — the
        QueueDepthGrowth signature, evaluated here so sim ticks need
        no watchdog instance."""
        s = timeseries.get("serve.pending")
        if s is None:
            return False, None
        _, v = s.values()
        if len(v) == 0:
            return False, None
        if len(v) < self.samples:
            return False, float(v[-1])
        w = v[-self.samples:]
        firing = bool(np.all(np.diff(w) >= 0) and w[-1] > w[0]
                      and w[-1] >= self.depth_high)
        return firing, float(w[-1])

    def _slo_burning(self):
        """Firing objective names from the SLO plane's last evaluation
        — ``[]`` when ``slo_signal`` is off, ``DK_SLO`` is unarmed, or
        no objective burns.  Best-effort: the scaler must keep working
        on a process without the SLO plane."""
        if not self.slo_signal:
            return []
        try:
            from dist_keras_tpu.observability import slo

            return slo.breaching()
        # dklint: ignore[broad-except] a broken SLO plane degrades to ramp/p99 evidence only
        except Exception:  # pragma: no cover - slo plane optional
            return []

    def tick(self):
        """One decision: inspect the rings, maybe resize.  -> the
        action taken: ``"up"`` / ``"down"`` / ``None`` (held)."""
        if self._cooldown > 0:
            self._cooldown -= 1
            return None
        ramp, depth = self._ramp()
        p99 = metrics.histogram("serve.predict_s").summary()["p99"]
        slo_breach = (self.p99_high_s is not None and p99 is not None
                      and p99 > self.p99_high_s)
        burning = self._slo_burning()
        cur = self._replicas()
        if (ramp or slo_breach or burning) and cur < self.ceiling:
            self._calm_streak = 0
            return self._resize(min(self.ceiling, cur + self.step),
                                "up", depth=depth, p99=p99,
                                ramp=ramp, slo_breach=slo_breach,
                                slo_objectives=burning or None)
        if ramp or slo_breach or burning:
            self._calm_streak = 0  # pinned at the ceiling: no churn
            return None
        calm = depth is None or depth <= self.depth_low
        if not calm:
            self._calm_streak = 0
            return None
        self._calm_streak += 1
        if self._calm_streak >= self.clear_checks and cur > self.floor:
            self._calm_streak = 0
            return self._resize(max(self.floor, cur - self.step),
                                "down", depth=depth, p99=p99,
                                ramp=False, slo_breach=False)
        return None

    def _resize(self, target, direction, **evidence):
        before = self._replicas()
        self.engine.resize(target)
        self.resizes += 1
        self._cooldown = self.cooldown_checks
        self._gauge.set(target)
        metrics.counter("autoscale.resizes").inc()
        events.emit("autoscale_resize", direction=direction,
                    replicas_from=before, replicas_to=target,
                    **{k: v for k, v in evidence.items()
                       if v is not None})
        return direction

    # -- background loop ------------------------------------------------
    def _loop(self):
        while not self._stop.is_set():
            try:
                self.tick()
            # dklint: ignore[broad-except] a failed actuation (engine draining mid-tick) must not kill the scaler
            except Exception as e:
                events.emit("autoscale_resize", direction="error",
                            error=type(e).__name__,
                            detail=str(e)[:200])
            self._stop.wait(self.interval_s)

    def start(self):
        """Start the background decision loop (daemon); -> self."""
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="dk-serve-autoscale")
        self._thread.start()
        return self

    def stop(self, timeout_s=5.0):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout_s)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False
