"""Continuous-batching autoregressive decode engine over paged KV.

``ServingEngine`` packs fixed-shape classifier-style forward passes;
this engine serves the LLM-shaped workload the rest of the repo was
built for (causal ``models/transformer.py``, the flash/paged Pallas
kernels): token-level scheduling with per-sequence futures, no batch
barrier.

Design points (each mirrors an existing engine contract):

- **Continuous batching.**  A per-replica scheduler thread runs one
  decode iteration at a time over the replica's ACTIVE sequence set;
  between iterations it admits queued sequences into free slots and
  retires finished ones — a short sequence exits early and its slot
  refills on the very next iteration, never waiting for neighbours
  (the continuous-batching line of work in PAPERS.md).
- **Prefill / decode phase split, both ladder-bounded.**  A sequence's
  prompt runs ONCE through a fixed-shape prefill ladder (padded like
  the serving batch ladder); every subsequent token runs through a
  fixed ladder of decode SLOT counts.  Dispatched executable shapes
  are therefore bounded by ``len(prefill_ladder) + len(decode_ladder)``
  (x replica devices, inherent) — the same no-retrace contract
  ``ServingEngine.stats()["retrace_count"]`` verifies, reported the
  same way.
- **Paged KV.**  Each replica owns one KV pool array of shape
  ``(layers, heads, num_pages + 1, page_size, head_dim)`` and a
  :class:`~dist_keras_tpu.serving.kv_cache.PagedKVCache` allocator.
  Admission reserves a sequence's WORST-CASE page count up front, so
  decode never stalls mid-sequence on KV: exhaustion is a typed
  ``Overloaded(reason="kv_exhausted")`` strictly at the door (rejected,
  not lost), and completion/cancel/error all reclaim through the one
  allocator path (zero leaked pages — the chaos tests assert it).
- **Hot reload never drops a sequence.**  ``submit_generate`` pins the
  replica's CURRENT params reference into the sequence; a
  ``set_params`` (CheckpointWatcher promotion, blue/green cutover)
  swaps the replica reference only — in-flight sequences finish on the
  params they started with, decode iterations simply group active
  sequences by params generation (at most a couple in flight).
- **Typed errors, never hangs.**  The ``decode.admit`` /
  ``decode.kv_alloc`` / ``decode.step`` / ``decode.recover`` fault
  points cover admission, page reservation, the step dispatch and the
  quarantine re-admission path; any failure lands typed on the
  affected sequences' futures with their pages reclaimed.
- **Sequence-level recovery.**  A replica worker crash (the
  :meth:`DecodeEngine.kill_replica` chaos seam, or a ``decode.step``
  fault past the in-place retry) QUARANTINES that replica: its KV
  pages free, its in-flight sequences re-admit onto surviving
  replicas and REPLAY — prefill over the prompt, then teacher-forced
  decode steps over the already-generated tokens (the canonical
  ``seq.tokens`` are kept; replayed predictions are discarded, so
  streaming callbacks resume exactly where they stopped and the
  final doc is bit-identical to an undisturbed greedy run).  Futures
  never see the failure; only when NO survivor can hold a sequence
  does it resolve typed (never a hang).  Whole-pod loss is out of
  scope: killing the last live replica is refused.
- **End-to-end deadlines.**  ``submit_generate(deadline_s=...)``
  rejects at the door (``Overloaded("deadline_infeasible")``) when
  the observed prefill/step EWMA says ``max_new_tokens`` cannot
  finish in time; a deadline expiring mid-decode frees the slot and
  its pages between steps and resolves the future with
  ``finish="deadline"`` and the tokens produced so far.
- **Brownout shedding.**  ``priority="batch"`` admissions are shed
  typed (``Overloaded("shed_batch")``) while ``slo.breaching()`` or
  KV occupancy sits above ``DK_DECODE_SHED_WATERMARK`` —
  ``interactive`` traffic keeps its SLO through the brownout.
  Sheds count ``decode.shed``, deliberately NOT ``decode.rejected``:
  the ``generate_tokens`` SLO reads ``rejected``, and shedding that
  burned the SLO would amplify itself.

Observability: ``decode_*`` events at every seam, ``decode.*``
registry metrics (TTFT and step-time histograms carry trace
exemplars), and with tracing on each request's trace gains
``serve.queue_wait`` + ``serve.prefill`` spans stamped from the
scheduler thread — time-to-first-token is attributable per request.
The ``generate_ttft`` / ``generate_tokens`` SLO objectives read these
surfaces (``observability/slo.py``).
"""

from __future__ import annotations

import collections
import itertools
import threading
import time
from concurrent.futures import Future

import numpy as np

import jax
import jax.numpy as jnp

from dist_keras_tpu.models.transformer import layer_norm
from dist_keras_tpu.observability import events, metrics, perf, spans
from dist_keras_tpu.observability import slo as _slo
from dist_keras_tpu.ops.pallas.decode_attention import (
    paged_attention_auto,
)
from dist_keras_tpu.ops.pallas.flash_attention import (
    attention_auto,
    use_pallas,
)
from dist_keras_tpu.resilience.faults import fault_point
from dist_keras_tpu.serving.engine import Overloaded
from dist_keras_tpu.serving.kv_cache import PagedKVCache, PagesExhausted
from dist_keras_tpu.utils import knobs
from dist_keras_tpu.utils.serialization import (
    deserialize_model,
    serialize_model,
)


class _ReplicaDead(Exception):
    """Internal scheduler signal: this replica must quarantine (worker
    crash, kill seam, or a step failure past the retry policy with a
    survivor available).  Never escapes the engine."""

    def __init__(self, cause):
        self.cause = cause
        super().__init__(str(cause))


class _Sequence:
    """One admitted generation: host-side state the scheduler owns."""

    __slots__ = ("sid", "tokens", "prompt_len", "max_new", "eos_id",
                 "future", "on_token", "t", "tw", "ctx", "params",
                 "params_host", "pages", "kv_len", "steps", "cancelled",
                 "ttft_s", "t_first", "deadline", "priority",
                 "recoveries", "finished")

    def __init__(self, sid, tokens, max_new, eos_id, on_token, params,
                 params_host, pages, deadline=None,
                 priority="interactive"):
        self.sid = sid
        self.tokens = list(tokens)
        self.prompt_len = len(tokens)
        self.max_new = max_new
        self.eos_id = eos_id
        self.future = Future()
        self.on_token = on_token
        self.t = time.monotonic()
        self.tw = time.time()
        self.ctx = spans.capture()
        self.params = params      # pinned: reloads never touch us
        self.params_host = params_host  # host ref: re-pin on recovery
        self.pages = pages
        self.kv_len = 0           # KV positions written so far
        self.steps = 0            # decode iterations consumed
        self.cancelled = False
        self.ttft_s = None
        self.t_first = None
        self.deadline = deadline  # absolute monotonic, or None
        self.priority = priority
        self.recoveries = 0       # quarantine re-admissions survived
        self.finished = False     # exit accounted (pages reclaimed)

    def generated(self):
        return self.tokens[self.prompt_len:]

    def result_doc(self, finish):
        return {
            "tokens": list(self.tokens),
            "generated": self.generated(),
            "prompt_len": self.prompt_len,
            "steps": self.steps,
            "ttft_s": self.ttft_s,
            "finish": finish,
            "recoveries": self.recoveries,
        }


class Generation:
    """Caller-side handle: a future plus a cancel seam (cancel reclaims
    the sequence's KV pages; the future resolves with
    ``finish="cancelled"`` and the tokens produced so far)."""

    def __init__(self, engine, seq):
        self._engine = engine
        self._seq = seq
        self.future = seq.future

    def result(self, timeout=None):
        return self.future.result(timeout=timeout)

    def cancel(self):
        return self._engine.cancel(self)

    def done(self):
        return self.future.done()


class _DecodeReplica:
    """One replica: pinned device, params swap point, its KV pool."""

    def __init__(self, index, device, params, cache, kp, vp):
        self.index = index
        self.device = device
        self.params_host = params
        self.params = (jax.device_put(params, device)
                       if device is not None else params)
        self.cache = cache
        self.kp = kp
        self.vp = vp
        self.queue = collections.deque()
        self.active = []
        self.retiring = False
        self.killed = False       # crash requested (kill_replica seam)
        self.dead = False         # quarantined: out of service for good
        self.steps = 0
        self._pinned = {}         # id(params_host) -> device params

    def put_params(self, params):
        self.params_host = params
        self.params = (jax.device_put(params, self.device)
                       if self.device is not None else params)

    def pin(self, params_host):
        """Device-resident params for a recovered sequence's pinned
        generation.  The common case (no reload since admission) reuses
        this replica's current params; an older generation device-puts
        once and caches (at most a couple of generations in flight —
        the same bound the step grouping relies on)."""
        if params_host is self.params_host:
            return self.params
        key = id(params_host)
        if key not in self._pinned:
            self._pinned[key] = (
                jax.device_put(params_host, self.device)
                if self.device is not None else params_host)
        return self._pinned[key]


class DecodeEngine:
    """Continuous-batching decode over the causal Transformer.

    Args:
      keras_model: a ``models.transformer.Transformer`` (or anything
        the serialization layer round-trips to one).  Decode needs
        token in == logit out, so the config must have
        ``input_dim == n_classes`` (the vocabulary); MoE configs are
        rejected.
      replicas: replica count (default: one per visible device).
      prefill_ladder: ascending fixed PROMPT shapes; a prompt runs
        padded to the smallest rung that fits (``ValueError`` past the
        largest — the front end's 400).
      decode_ladder: ascending fixed SLOT counts for decode steps; the
        largest rung is the per-replica concurrency cap.
      page_size: KV positions per page.
      num_pages: pool pages per replica.  Default sizes the pool so a
        full slot set of maximum-length sequences fits.
      max_queue: admission bound on admitted-but-unresolved sequences.
      max_new_default: ``max_new_tokens`` when a request omits it.
      eos_id: default stop token (None = length-only stopping).
      devices: explicit device list (default ``jax.devices()``).
      step_retries: in-place retries of a failed decode-step dispatch
        (safe: pools and ``kv_len`` only advance on success).  Past
        them the replica quarantines when a survivor exists, else the
        group fails typed.
      shed_watermark: KV occupancy fraction above which ``batch``
        admissions shed (default: ``DK_DECODE_SHED_WATERMARK``).
      self_check_interval_s: cadence of the scheduler's allocator
        reconciliation pass (``decode.kv_leaked``).
    """

    def __init__(self, keras_model, replicas=None,
                 prefill_ladder=(16, 64), decode_ladder=(1, 4, 8),
                 page_size=8, num_pages=None, max_queue=256,
                 max_new_default=16, eos_id=None, devices=None,
                 step_retries=1, shed_watermark=None,
                 self_check_interval_s=1.0):
        self.serialized = serialize_model(keras_model)
        model = deserialize_model(self.serialized)
        cfg = getattr(model, "cfg", None)
        if cfg is None:
            raise ValueError(
                "DecodeEngine needs the causal Transformer model "
                "contract (a cfg dict); got "
                f"{type(model).__name__}")
        if cfg.get("moe_experts", 0):
            raise ValueError("MoE configs are not decodable here")
        if cfg["input_dim"] != cfg["n_classes"]:
            raise ValueError(
                "causal decode needs token-in == logit-out: "
                f"input_dim={cfg['input_dim']} != "
                f"n_classes={cfg['n_classes']}")
        self.cfg = cfg
        self.vocab = int(cfg["n_classes"])
        self.seq_len = int(cfg["seq_len"])
        self._host_params = model.params

        ladder = sorted(set(int(b) for b in prefill_ladder))
        if not ladder or ladder[0] < 1 or ladder[-1] > self.seq_len:
            raise ValueError(
                f"prefill_ladder {prefill_ladder!r} must hold positive "
                f"ints <= seq_len ({self.seq_len})")
        self.prefill_ladder = tuple(ladder)
        slots = sorted(set(int(b) for b in decode_ladder))
        if not slots or slots[0] < 1:
            raise ValueError(
                f"decode_ladder {decode_ladder!r} must hold positive "
                "ints")
        self.decode_ladder = tuple(slots)
        self.max_slots = slots[-1]
        self.max_queue = int(max_queue)
        self.max_new_default = int(max_new_default)
        self.eos_id = eos_id if eos_id is None else int(eos_id)
        self.page_size = int(page_size)
        self.max_pages_per_seq = -(-self.seq_len // self.page_size)
        if num_pages is None:
            num_pages = self.max_slots * self.max_pages_per_seq
        self.num_pages = int(num_pages)

        d, h = cfg["d_model"], cfg["n_heads"]
        self._dh = d // h
        self._heads = h
        self._layers = int(cfg["n_layers"])
        # donation keeps the pool update in place on TPU; CPU jax would
        # warn-and-copy, so only donate where donation is real
        donate = (1, 2) if use_pallas() else ()
        self._prefill_jit = jax.jit(self._prefill_fn,
                                    donate_argnums=donate)
        self._decode_jit = jax.jit(self._decode_fn,
                                   donate_argnums=donate)

        if devices is None:
            devices = jax.devices()
        n = int(replicas) if replicas is not None else len(devices)
        if n < 1:
            raise ValueError(f"replicas={replicas} must be >= 1")
        self._devices = list(devices) if devices else []
        self._next_replica_index = n
        self._seq_ids = itertools.count()
        self._replicas = [self._make_replica(i) for i in range(n)]

        self._cond = threading.Condition()
        self._outstanding = 0
        self._draining = False
        self._stopped = False
        self._drained = threading.Event()
        self._rr = 0
        self._shapes = set()      # (phase, rung) dispatched
        self.reload_count = 0
        self.step_retries = int(step_retries)
        self._shed_watermark = float(
            shed_watermark if shed_watermark is not None
            else knobs.get("DK_DECODE_SHED_WATERMARK"))
        self._self_check_interval = float(self_check_interval_s)
        self._next_self_check = (time.monotonic()
                                 + self._self_check_interval)
        # recovered sequences waiting for survivor KV capacity: they
        # hold no pages while pending; every worker iteration tries to
        # place them (admission-identical worst-case reservation)
        self._orphans = []
        # observed wall EWMAs feeding deadline feasibility at the door
        self._ewma_prefill = None
        self._ewma_step = None

        # engine-local instruments + the shared process registry (the
        # same split ServingEngine documents: per-engine truths vs
        # process-wide aggregates)
        self._m_ttft = metrics.Histogram("decode.ttft_s")
        self._m_step = metrics.Histogram("decode.step_s")
        self._n_admitted = 0
        self._n_completed = 0
        self._n_rejected = 0
        self._n_errors = 0
        self._n_cancelled = 0
        self._n_tokens = 0
        self._n_quarantines = 0
        self._n_recovered = 0
        self._n_shed = 0
        self._n_deadline_infeasible = 0
        self._n_deadline_expired = 0
        self._n_kv_leaked = 0
        self._reg_admitted = metrics.counter("decode.admitted")
        self._reg_completed = metrics.counter("decode.completed")
        self._reg_rejected = metrics.counter("decode.rejected")
        self._reg_errors = metrics.counter("decode.errors")
        self._reg_cancelled = metrics.counter("decode.cancelled")
        self._reg_tokens = metrics.counter("decode.tokens")
        self._reg_quarantines = metrics.counter("decode.quarantines")
        self._reg_recovered = metrics.counter("decode.recovered")
        self._reg_shed = metrics.counter("decode.shed")
        self._reg_deadline_infeasible = metrics.counter(
            "decode.deadline_infeasible")
        self._reg_deadline_expired = metrics.counter(
            "decode.deadline_expired")
        self._reg_kv_leaked = metrics.counter("decode.kv_leaked")
        self._reg_ttft = metrics.histogram("decode.ttft_s")
        self._reg_step = metrics.histogram("decode.step_s")
        self._reg_active = metrics.gauge("decode.active")
        self._reg_kv = metrics.gauge("decode.kv_used_pages")
        perf.install()  # retrace listener: the ladder bound, verified

        self._workers = [threading.Thread(
            target=self._worker_main, args=(rep,), daemon=True,
            name=f"dk-decode-worker-{rep.index}")
            for rep in self._replicas]
        for t in self._workers:
            t.start()

    # -- model math (jitted once per ladder rung) -----------------------
    def _make_replica(self, index):
        devs = self._devices
        device = devs[index % len(devs)] if devs else None
        cache = PagedKVCache(self.num_pages, self.page_size)
        shape = (self._layers, self._heads, self.num_pages + 1,
                 self.page_size, self._dh)
        kp = jnp.zeros(shape, jnp.float32)
        vp = jnp.zeros(shape, jnp.float32)
        if device is not None:
            kp = jax.device_put(kp, device)
            vp = jax.device_put(vp, device)
        return _DecodeReplica(index, device, self._host_params, cache,
                              kp, vp)

    def _prefill_fn(self, params, kp, vp, tokens, length, page_idx,
                    page_off):
        """One padded prompt -> (first generated token, updated pools).

        ``tokens (T,) int32`` padded to a prefill rung; positions past
        ``length`` write their K/V to the scratch page (``page_idx``
        routes them there) and never influence position ``length - 1``
        under the causal mask."""
        t = tokens.shape[0]
        x = jax.nn.one_hot(tokens, self.vocab, dtype=kp.dtype)
        hs = (x @ params["proj"] + params["pos"][:t])[None]
        for li, blk in enumerate(params["blocks"]):
            y = layer_norm(blk["ln1"], hs)
            q = jnp.einsum("btd,dhk->bthk", y, blk["wq"])
            k = jnp.einsum("btd,dhk->bthk", y, blk["wk"])
            v = jnp.einsum("btd,dhk->bthk", y, blk["wv"])
            # scalar layer + page arrays are non-adjacent advanced
            # indices: the update's broadcast dims lead -> (T, H, dh)
            kp = kp.at[li, :, page_idx, page_off, :].set(k[0])
            vp = vp.at[li, :, page_idx, page_off, :].set(v[0])
            a = attention_auto(q, k, v, causal=True)
            hs = hs + jnp.einsum("bthk,hkd->btd", a, blk["wo"])
            y = layer_norm(blk["ln2"], hs)
            u = jax.nn.gelu(y @ blk["w1"] + blk["b1"])
            hs = hs + u @ blk["w2"] + blk["b2"]
        hf = layer_norm(params["ln_f"], hs)[0, length - 1]
        logits = hf @ params["head"]["kernel"] + params["head"]["bias"]
        return jnp.argmax(logits).astype(jnp.int32), kp, vp

    def _decode_fn(self, params, kp, vp, tokens, positions, page_tables,
                   write_page, write_off, lengths):
        """One token step for a padded slot set -> (next tokens,
        updated pools).  Padding slots carry ``length == 0`` and write
        to the scratch page; the paged attention's dead-row guard
        makes their output exact zeros (then discarded)."""
        x = (jax.nn.one_hot(tokens, self.vocab, dtype=kp.dtype)
             @ params["proj"] + params["pos"][positions])
        hs = x
        for li, blk in enumerate(params["blocks"]):
            y = layer_norm(blk["ln1"], hs)
            q = jnp.einsum("sd,dhk->shk", y, blk["wq"])
            k = jnp.einsum("sd,dhk->shk", y, blk["wk"])
            v = jnp.einsum("sd,dhk->shk", y, blk["wv"])
            kp = kp.at[li, :, write_page, write_off, :].set(k)
            vp = vp.at[li, :, write_page, write_off, :].set(v)
            a = paged_attention_auto(q, kp[li], vp[li], page_tables,
                                     lengths)
            hs = hs + jnp.einsum("shk,hkd->sd", a, blk["wo"])
            y = layer_norm(blk["ln2"], hs)
            u = jax.nn.gelu(y @ blk["w1"] + blk["b1"])
            hs = hs + u @ blk["w2"] + blk["b2"]
        hf = layer_norm(params["ln_f"], hs)
        logits = hf @ params["head"]["kernel"] + params["head"]["bias"]
        return jnp.argmax(logits, axis=-1).astype(jnp.int32), kp, vp

    # -- admission ------------------------------------------------------
    def _rung_for(self, n, ladder):
        for b in ladder:
            if n <= b:
                return b
        return None

    def _live_replicas_locked(self):
        return [r for r in self._replicas
                if not r.retiring and not r.dead and not r.killed]

    def _pick_replica(self, needed_pages):
        """Most free pages wins (KV is the scarce resource), round-robin
        on ties; retiring and quarantined replicas are out of rotation.
        Caller holds the lock."""
        live = self._live_replicas_locked()
        if not live:
            return None, 0
        frees = [r.cache.stats()["free_pages"] for r in live]
        best = max(frees)
        order = range(self._rr, self._rr + len(live))
        for i in order:
            i %= len(live)
            if frees[i] == best:
                self._rr = (i + 1) % len(live)
                return (live[i] if best >= needed_pages else None), best
        return None, best  # pragma: no cover - unreachable

    def _should_shed_locked(self):
        """Brownout verdict for a ``batch`` admission: KV occupancy
        over the watermark, or any SLO objective firing.  Caller holds
        the lock ( ``slo.breaching`` takes only leaf locks)."""
        live = self._live_replicas_locked()
        total = used = 0
        for r in live:
            st = r.cache.stats()
            total += st["num_pages"]
            used += st["used_pages"]
        if total and used / total >= self._shed_watermark:
            return "kv_watermark"
        firing = _slo.breaching()
        if firing:
            return "slo:" + ",".join(firing)
        return None

    def submit_generate(self, tokens, max_new_tokens=None, eos_id=None,
                        on_token=None, deadline_s=None,
                        priority="interactive"):
        """Admit one prompt; -> :class:`Generation` whose future
        resolves to the result doc (tokens, ttft_s, finish reason).
        Raises :class:`Overloaded` at the door (``queue_full`` /
        ``kv_exhausted`` / ``draining`` / ``stopped`` /
        ``deadline_infeasible`` / ``shed_batch``) and ``ValueError``
        for malformed prompts — rejected, never lost.

        ``deadline_s`` is the caller's end-to-end budget: infeasible
        requests (per the observed prefill/step EWMAs) reject at the
        door instead of burning KV pages toward a 504; expiry
        mid-decode frees the slot between steps and resolves
        ``finish="deadline"``.  ``priority`` is ``interactive``
        (default) or ``batch``; ``batch`` sheds first in a brownout."""
        fault_point("decode.admit")
        toks = [int(t) for t in tokens]
        if not toks:
            raise ValueError("empty prompt")
        if any(t < 0 or t >= self.vocab for t in toks):
            raise ValueError(
                f"prompt tokens must be in [0, {self.vocab})")
        max_new = (self.max_new_default if max_new_tokens is None
                   else int(max_new_tokens))
        if max_new < 1:
            raise ValueError(f"max_new_tokens={max_new} must be >= 1")
        rung = self._rung_for(len(toks), self.prefill_ladder)
        if rung is None:
            raise ValueError(
                f"prompt length {len(toks)} exceeds the prefill "
                f"ladder (max {self.prefill_ladder[-1]})")
        total = len(toks) + max_new
        if total > self.seq_len:
            raise ValueError(
                f"prompt + max_new_tokens = {total} exceeds the "
                f"model's seq_len ({self.seq_len})")
        eos = self.eos_id if eos_id is None else int(eos_id)
        if priority not in ("interactive", "batch"):
            raise ValueError(
                f"priority={priority!r} must be 'interactive' or "
                "'batch'")
        if deadline_s is not None:
            deadline_s = float(deadline_s)
            if deadline_s <= 0:
                raise ValueError(
                    f"deadline_s={deadline_s} must be > 0")
        with self._cond:
            if self._draining or self._stopped:
                self._n_rejected += 1
                self._reg_rejected.inc()
                raise Overloaded(
                    "draining" if self._draining else "stopped")
            if self._outstanding >= self.max_queue:
                self._n_rejected += 1
                self._reg_rejected.inc()
                raise Overloaded("queue_full",
                                 pending=self._outstanding,
                                 capacity=self.max_queue)
            if priority == "batch":
                shed_why = self._should_shed_locked()
                if shed_why is not None:
                    # counted decode.shed, NOT decode.rejected: the
                    # generate_tokens SLO reads rejected, and a shed
                    # that burned the SLO would amplify itself
                    self._n_shed += 1
                    self._reg_shed.inc()
                    events.emit("decode_shed", reason=shed_why,
                                prompt_len=len(toks))
                    raise Overloaded("shed_batch",
                                     pending=self._outstanding,
                                     capacity=self.max_queue)
            if deadline_s is not None \
                    and self._ewma_prefill is not None \
                    and self._ewma_step is not None:
                est = self._ewma_prefill + max_new * self._ewma_step
                if est > deadline_s:
                    self._n_rejected += 1
                    self._reg_rejected.inc()
                    self._n_deadline_infeasible += 1
                    self._reg_deadline_infeasible.inc()
                    events.emit("decode_deadline", phase="admission",
                                deadline_s=deadline_s,
                                estimate_s=round(est, 6))
                    raise Overloaded("deadline_infeasible")
            sid = next(self._seq_ids)
            needed = max(1, -(-total // self.page_size))
            rep, best_free = self._pick_replica(needed)
            if rep is None:
                self._n_rejected += 1
                self._reg_rejected.inc()
                raise Overloaded("kv_exhausted", pending=needed,
                                 capacity=best_free)
            # the allocator's own fault point (decode.kv_alloc) fires
            # inside; a raise here admits nothing and leaks nothing
            pages = rep.cache.alloc(sid, total)
            seq = _Sequence(
                sid, toks, max_new, eos, on_token, rep.params,
                rep.params_host, pages,
                deadline=(None if deadline_s is None
                          else time.monotonic() + deadline_s),
                priority=priority)
            rep.queue.append(seq)
            self._outstanding += 1
            self._n_admitted += 1
            self._reg_active.set(self._outstanding)
            self._cond.notify_all()
        self._reg_admitted.inc()
        events.emit("decode_admit", sid=sid, prompt_len=len(toks),
                    max_new=max_new, replica=rep.index,
                    pages=len(pages))
        return Generation(self, seq)

    def generate(self, tokens, max_new_tokens=None, eos_id=None,
                 timeout_s=None):
        """Blocking convenience: submit one prompt, wait for the doc."""
        return self.submit_generate(
            tokens, max_new_tokens=max_new_tokens,
            eos_id=eos_id).result(timeout=timeout_s)

    def cancel(self, generation):
        """Cancel a generation: reclaim its pages and resolve its
        future with ``finish="cancelled"`` (tokens so far).  -> True if
        the cancel landed before completion."""
        seq = generation._seq
        dequeued = False
        with self._cond:
            if seq.future.done() or seq.cancelled or seq.finished:
                # ``finished`` closes the race against _sequence_done:
                # the scheduler already accounted the exit (pages
                # reclaimed) and is about to resolve the future —
                # nothing is left to cancel, and marking ``cancelled``
                # here would be a lie the next pass can't act on
                return False
            seq.cancelled = True
            # still queued on some replica? finish it here, never
            # occupying a slot
            for rep in self._replicas:
                if seq in rep.queue:
                    rep.queue.remove(seq)
                    self._finish_locked(rep, seq, "cancelled")
                    dequeued = True
                    break
            self._cond.notify_all()
        if dequeued:
            events.emit("decode_cancel", sid=seq.sid,
                        generated=len(seq.generated()))
            self._resolve(seq, "cancelled")
        return True  # active: the scheduler retires it next iteration

    # -- scheduler ------------------------------------------------------
    def _resolve(self, seq, finish, error=None):
        """Resolve a sequence's future OUTSIDE the lock."""
        if error is not None:
            seq.future.set_exception(error)
        else:
            seq.future.set_result(seq.result_doc(finish))

    def _finish_locked(self, rep, seq, finish):
        """Account one sequence's exit (caller holds the lock):
        reclaim pages, bump counters.  The single reclamation seam for
        complete/cancel/error/deadline — zero leaked pages by
        construction."""
        rep.cache.free(seq.sid)
        self._account_exit_locked(seq, finish)

    def _account_exit_locked(self, seq, finish):
        """The bookkeeping half of an exit — callers (quarantine)
        whose pages were already reclaimed on the dead replica use
        this directly."""
        seq.finished = True
        self._outstanding -= 1
        if finish == "error":
            self._n_errors += 1
            self._reg_errors.inc()
        elif finish == "cancelled":
            self._n_cancelled += 1
            self._reg_cancelled.inc()
        elif finish == "deadline":
            # a deadline expiry is the CALLER's budget running out —
            # the future resolves with the tokens produced so far
            # (graceful degradation), counted on its own meter
            self._n_deadline_expired += 1
            self._reg_deadline_expired.inc()
        elif finish == "stopped":
            # a close(drain=False) abort is a rejection, not a model
            # error — rejected-not-lost, same as the door
            self._n_rejected += 1
            self._reg_rejected.inc()
        else:
            self._n_completed += 1
            self._reg_completed.inc()
        self._reg_active.set(self._outstanding)
        self._reg_kv.set(sum(r.cache.used_pages()
                             for r in self._replicas))
        self._cond.notify_all()

    def _emit_token(self, seq, token):
        seq.tokens.append(int(token))
        self._n_tokens += 1
        self._reg_tokens.inc()
        if seq.on_token is not None:
            try:
                seq.on_token(int(token))
            # dklint: ignore[broad-except] a caller's token callback must never kill the scheduler thread
            except Exception as e:
                events.emit("decode_error", sid=seq.sid,
                            where="on_token", error=type(e).__name__)

    def _sequence_done(self, seq, token):
        if seq.eos_id is not None and int(token) == seq.eos_id:
            return "eos"
        if len(seq.generated()) >= seq.max_new:
            return "length"
        return None

    def _prefill(self, rep, seq):
        """Run one admitted prompt through the prefill ladder; emits
        the first generated token (TTFT) or fails the sequence typed.

        A RECOVERED sequence (``seq.tokens`` longer than the prompt)
        replays the same prefill over the prompt only — its prediction
        is a token the stream already delivered, so it is discarded
        and the teacher-forced decode steps replay the rest."""
        rung = self._rung_for(seq.prompt_len, self.prefill_ladder)
        toks = np.zeros((rung,), np.int32)
        toks[:seq.prompt_len] = seq.tokens[:seq.prompt_len]
        scratch = rep.cache.scratch_page
        page_idx = np.full((rung,), scratch, np.int32)
        ps = self.page_size
        for t in range(seq.prompt_len):
            page_idx[t] = seq.pages[t // ps]
        page_off = (np.arange(rung, dtype=np.int32) % ps)
        t0 = time.perf_counter()
        tw0 = time.time()
        if events.enabled():
            spans.span_at("serve.queue_wait", seq.ctx, seq.tw, tw0)
        try:
            perf.count_dispatch()
            first, rep.kp, rep.vp = self._prefill_jit(
                seq.params, rep.kp, rep.vp, jnp.asarray(toks),
                jnp.int32(seq.prompt_len), jnp.asarray(page_idx),
                jnp.asarray(page_off))
            first = int(first)
        # dklint: ignore[broad-except] a failed prefill lands TYPED on its own future with pages reclaimed
        except Exception as e:
            with self._cond:
                rep.active.remove(seq)
                self._finish_locked(rep, seq, "error")
            events.emit("decode_error", sid=seq.sid, where="prefill",
                        error=type(e).__name__)
            self._resolve(seq, None, error=e)
            return
        dt = time.perf_counter() - t0
        with self._cond:
            self._shapes.add(("prefill", rung))
            self._ewma_prefill = (
                dt if self._ewma_prefill is None
                else 0.8 * self._ewma_prefill + 0.2 * dt)
        seq.kv_len = seq.prompt_len
        replay = len(seq.tokens) > seq.prompt_len
        if not replay:
            seq.ttft_s = time.monotonic() - seq.t
            seq.t_first = time.time()
            ex = ((seq.ctx.trace_id, seq.ctx.span_id)
                  if seq.ctx is not None else None)
            self._m_ttft.observe(seq.ttft_s, exemplar=ex)
            self._reg_ttft.observe(seq.ttft_s, exemplar=ex)
        if events.enabled():
            spans.span_at("serve.prefill", seq.ctx, tw0, time.time(),
                          rung=rung, replica=rep.index)
        events.emit("decode_prefill", sid=seq.sid, rung=rung,
                    replica=rep.index, duration_s=dt,
                    ttft_s=seq.ttft_s, replay=replay)
        if replay:
            # the first generated token was emitted before the crash;
            # the replayed prediction is that same token (greedy,
            # pinned params) — discard it, the canonical seq.tokens
            # drive the teacher-forced catch-up steps
            return
        self._emit_token(seq, first)
        finish = self._sequence_done(seq, first)
        if finish is not None:
            with self._cond:
                rep.active.remove(seq)
                self._finish_locked(rep, seq, finish)
            events.emit("decode_complete", sid=seq.sid, finish=finish,
                        generated=len(seq.generated()),
                        steps=seq.steps)
            self._resolve(seq, finish)

    def _step_group(self, rep, group):
        """One decode step for ``group`` (same pinned params), padded
        to a decode-ladder rung.  A failed dispatch retries IN PLACE
        (``step_retries`` — safe: pools and ``kv_len`` only advance on
        success); past the retries the replica quarantines when a
        survivor exists (the group migrates and replays), else it
        fails exactly this group's sequences, typed, pages reclaimed.

        The input token is ``seq.tokens[seq.kv_len]`` — the last token
        in steady state, a teacher-forced KNOWN token while a
        recovered sequence catches back up (its predictions are
        discarded until ``kv_len`` reaches the frontier, so streams
        never see a duplicate)."""
        rung = self._rung_for(len(group), self.decode_ladder)
        scratch = rep.cache.scratch_page
        ps = self.page_size
        pmax = self.max_pages_per_seq
        toks = np.zeros((rung,), np.int32)
        positions = np.zeros((rung,), np.int32)
        tables = np.zeros((rung, pmax), np.int32)
        wpage = np.full((rung,), scratch, np.int32)
        woff = np.zeros((rung,), np.int32)
        lengths = np.zeros((rung,), np.int32)
        for i, seq in enumerate(group):
            toks[i] = seq.tokens[seq.kv_len]
            positions[i] = seq.kv_len
            tables[i, :len(seq.pages)] = seq.pages
            wpage[i] = seq.pages[seq.kv_len // ps]
            woff[i] = seq.kv_len % ps
            lengths[i] = seq.kv_len + 1
        t0 = time.perf_counter()
        err = None
        for attempt in range(1 + self.step_retries):
            try:
                fault_point("decode.step")
                perf.count_dispatch()
                nxt, rep.kp, rep.vp = self._decode_jit(
                    group[0].params, rep.kp, rep.vp, jnp.asarray(toks),
                    jnp.asarray(positions), jnp.asarray(tables),
                    jnp.asarray(wpage), jnp.asarray(woff),
                    jnp.asarray(lengths))
                nxt = np.asarray(nxt)
                err = None
                break
            # dklint: ignore[broad-except] a failed step retries in place, then quarantines or lands TYPED
            except Exception as e:
                err = e
                if attempt < self.step_retries:
                    events.emit("decode_error", where="step_retry",
                                n=len(group), replica=rep.index,
                                attempt=attempt,
                                error=type(e).__name__)
        if err is not None:
            with self._cond:
                survivors = [r for r in self._live_replicas_locked()
                             if r is not rep]
            if survivors:
                # a peer can hold this work: quarantine this replica,
                # migrate + replay — the futures never see the failure
                raise _ReplicaDead(err)
            with self._cond:
                for seq in group:
                    rep.active.remove(seq)
                    self._finish_locked(rep, seq, "error")
            events.emit("decode_error", where="step", n=len(group),
                        replica=rep.index, error=type(err).__name__)
            for seq in group:
                self._resolve(seq, None, error=err)
            return
        dt = time.perf_counter() - t0
        rep.steps += 1
        self._m_step.observe(dt)
        self._reg_step.observe(dt)
        with self._cond:
            self._shapes.add(("decode", rung))
            self._ewma_step = (dt if self._ewma_step is None
                               else 0.8 * self._ewma_step + 0.2 * dt)
        events.emit("decode_step", replica=rep.index, rung=rung,
                    n=len(group), duration_s=dt)
        finished = []
        for i, seq in enumerate(group):
            seq.kv_len += 1
            seq.steps += 1
            if seq.kv_len < len(seq.tokens):
                # replay catch-up: this prediction is a token the
                # stream already delivered before the crash — discard
                continue
            self._emit_token(seq, int(nxt[i]))
            finish = self._sequence_done(seq, int(nxt[i]))
            if finish is not None:
                finished.append((seq, finish))
        if finished:
            with self._cond:
                for seq, finish in finished:
                    rep.active.remove(seq)
                    self._finish_locked(rep, seq, finish)
            for seq, finish in finished:
                events.emit("decode_complete", sid=seq.sid,
                            finish=finish,
                            generated=len(seq.generated()),
                            steps=seq.steps)
                self._resolve(seq, finish)

    def _worker_main(self, rep):
        """Thread body: the scheduler loop plus the crash boundary.
        ANY escape — the :class:`_ReplicaDead` signal (kill seam, step
        failure past retries) or an unexpected scheduler bug —
        quarantines the replica so its sequences migrate or resolve
        typed instead of hanging on a silently dead thread."""
        try:
            self._worker_loop(rep)
        except _ReplicaDead as e:
            self._quarantine(rep, e.cause)
        # dklint: ignore[broad-except] a crashed worker quarantines its replica; sequences migrate or land typed, never hang
        except Exception as e:
            self._quarantine(rep, e)

    def _worker_loop(self, rep):
        while True:
            dropped = []
            with self._cond:
                # pending orphans hold the park open: an idle replica
                # has its whole (homogeneous) pool free, so the next
                # placement pass below always lands them
                while (not rep.queue and not rep.active
                       and not self._orphans
                       and not self._stopped and not rep.retiring
                       and not rep.killed):
                    # the scheduler's idle park: deliberately unbounded
                    # — every admit, cancel and both lifecycle exits
                    # notify this cond, and the predicate re-checks
                    # stop/retire/kill on wake
                    # dklint: ignore[unbounded-wait] idle park; admission and lifecycle exits notify this cond
                    self._cond.wait()
                if self._stopped:
                    break
                if rep.killed:
                    raise _ReplicaDead(Overloaded("replica_lost"))
                if rep.retiring and not rep.queue and not rep.active:
                    break
                o_migrated, o_dropped = \
                    self._try_place_orphans_locked()
                # retire cancelled and deadline-expired actives, refill
                # free slots — the continuous-batching seam: between
                # iterations, never a batch barrier.  An expired
                # deadline frees the slot HERE, between steps.
                now = time.monotonic()
                for seq in list(rep.active):
                    fin = ("cancelled" if seq.cancelled else
                           "deadline" if seq.deadline is not None
                           and now > seq.deadline else None)
                    if fin is not None:
                        rep.active.remove(seq)
                        self._finish_locked(rep, seq, fin)
                        dropped.append((seq, fin))
                while rep.queue and len(rep.active) < self.max_slots:
                    seq = rep.queue.popleft()
                    fin = ("cancelled" if seq.cancelled else
                           "deadline" if seq.deadline is not None
                           and now > seq.deadline else None)
                    if fin is not None:
                        self._finish_locked(rep, seq, fin)
                        dropped.append((seq, fin))
                        continue
                    rep.active.append(seq)
                # prefill candidates by state, not by admission order:
                # a recovered sequence re-enters here with kv_len == 0
                # and replays exactly like a fresh admission
                prefills = [s for s in rep.active if s.kv_len == 0]
            for seq, target in o_migrated:
                self._reg_recovered.inc()
                events.emit("decode_recover", sid=seq.sid, src=None,
                            dst=target.index,
                            generated=len(seq.generated()),
                            recoveries=seq.recoveries)
            dropped.extend(o_dropped)
            for seq, fin in dropped:
                if fin == "cancelled":
                    events.emit("decode_cancel", sid=seq.sid,
                                generated=len(seq.generated()))
                else:
                    events.emit("decode_deadline", sid=seq.sid,
                                phase="expiry",
                                generated=len(seq.generated()))
                self._resolve(seq, fin)
            for seq in prefills:
                self._prefill(rep, seq)
                if rep.killed:
                    raise _ReplicaDead(Overloaded("replica_lost"))
            with self._cond:
                # group by pinned params generation: a hot reload means
                # at most a couple of groups until old sequences drain
                groups = {}
                for seq in rep.active:
                    if seq.kv_len == 0:
                        continue  # not prefilled yet: next pass
                    groups.setdefault(id(seq.params), []).append(seq)
                work = list(groups.values())
            for group in work:
                self._step_group(rep, group)
                if rep.killed:
                    raise _ReplicaDead(Overloaded("replica_lost"))
            self._maybe_self_check()

    # -- survivability: quarantine + sequence-level recovery ------------
    def kill_replica(self, index):
        """Chaos seam: crash one replica worker (the thread analogue
        of SIGKILL on a replica process).  The worker observes the
        flag at its next seam, quarantines the replica — pages freed,
        in-flight sequences re-admitted onto survivors and replayed —
        and exits.  Refused (``ValueError``) for the LAST live
        replica: whole-pod loss is the job scheduler's problem, not a
        survivable event."""
        with self._cond:
            rep = next((r for r in self._replicas
                        if r.index == int(index)), None)
            if rep is None or rep.dead or rep.killed:
                raise ValueError(
                    f"kill_replica({index}): no such live replica")
            live = self._live_replicas_locked()
            if rep in live and len(live) <= 1:
                raise ValueError(
                    "kill_replica: refusing to kill the last live "
                    "replica (whole-pod loss is out of scope)")
            rep.killed = True
            self._cond.notify_all()
        return rep.index

    def _place_locked(self, seq):
        """Re-admission placement (caller holds the lock): the
        surviving replica with the most free pages that can hold the
        sequence's WORST-CASE reservation — the same door contract as
        submit_generate.  -> the replica, or None when nowhere fits."""
        total = seq.prompt_len + seq.max_new
        live = [r for r in self._live_replicas_locked()]
        live.sort(key=lambda r: -r.cache.stats()["free_pages"])
        for rep in live:
            try:
                seq.pages = rep.cache.alloc(seq.sid, total)
            except PagesExhausted:
                continue
            return rep
        return None

    def _fits_somewhere_locked(self, seq):
        """Could ANY live replica's whole pool hold this sequence's
        worst-case reservation?  If yes, a full-but-alive pool is a
        capacity wait, not a loss."""
        total = seq.prompt_len + seq.max_new
        return any(r.cache.pages_for(total) <= r.cache.num_pages
                   for r in self._live_replicas_locked())

    def _try_place_orphans_locked(self):
        """Place pending orphans — recovered sequences waiting for
        survivor capacity (caller holds the lock).  They hold NO
        pages while pending; placement reserves worst-case, exactly
        like admission.  -> (migrated, dropped) pairs for the caller
        to emit events / resolve futures OUTSIDE the lock."""
        migrated, dropped = [], []
        if not self._orphans:
            return migrated, dropped
        now = time.monotonic()
        still = []
        for seq in self._orphans:
            fin = ("cancelled" if seq.cancelled else
                   "deadline" if seq.deadline is not None
                   and now > seq.deadline else None)
            if fin is not None:
                self._account_exit_locked(seq, fin)
                dropped.append((seq, fin))
                continue
            target = self._place_locked(seq)
            if target is None:
                still.append(seq)
                continue
            seq.kv_len = 0          # replay regenerates the KV
            seq.recoveries += 1
            seq.params = target.pin(seq.params_host)
            target.queue.append(seq)
            self._n_recovered += 1
            migrated.append((seq, target))
        self._orphans[:] = still
        if migrated:
            self._cond.notify_all()
        return migrated, dropped

    def _quarantine(self, rep, cause):
        """Take a crashed replica out of service and carry its
        sequences over: free every page it held, re-admit each
        in-flight sequence onto a survivor (``kv_len`` reset — the
        replay machinery regenerates its KV from the canonical
        tokens), park what fits a survivor's pool but not its current
        free list (placed as capacity frees), and resolve typed only
        what no survivor could EVER hold.  Futures never hang; pages
        never leak."""
        with self._cond:
            rep.killed = True
            rep.dead = True
            rep.retiring = True     # out of _pick_replica rotation
            orphans = list(rep.active) + list(rep.queue)
            del rep.active[:]
            rep.queue.clear()
            for seq in orphans:
                rep.cache.free(seq.sid)
            self._n_quarantines += 1
            self._cond.notify_all()
        self._reg_quarantines.inc()
        events.emit("decode_quarantine", replica=rep.index,
                    orphans=len(orphans), cause=type(cause).__name__)
        recover_err = None
        try:
            fault_point("decode.recover")
        # dklint: ignore[broad-except] a failed recovery resolves every orphan TYPED — never a hang
        except Exception as e:
            recover_err = e
        migrated = []
        resolved = []
        with self._cond:
            for seq in orphans:
                if seq.cancelled:
                    self._account_exit_locked(seq, "cancelled")
                    resolved.append((seq, "cancelled", None))
                    continue
                target = (None if recover_err is not None
                          else self._place_locked(seq))
                if target is None:
                    if recover_err is None \
                            and self._fits_somewhere_locked(seq):
                        # survivors exist but are full RIGHT NOW: the
                        # sequence was already admitted (door contract
                        # honoured once), so it WAITS for capacity
                        # instead of failing — futures never see a
                        # survivable crash
                        self._orphans.append(seq)
                        continue
                    # no survivor can EVER hold it (or recovery itself
                    # is the injected failure): typed, never hung
                    err = recover_err if recover_err is not None \
                        else cause
                    if not isinstance(err, BaseException):
                        err = Overloaded("replica_lost")
                    self._account_exit_locked(seq, "error")
                    resolved.append((seq, None, err))
                    continue
                seq.kv_len = 0          # replay regenerates the KV
                seq.recoveries += 1
                seq.params = target.pin(seq.params_host)
                target.queue.append(seq)
                migrated.append((seq, target))
                self._n_recovered += 1
            self._cond.notify_all()
        for seq, target in migrated:
            self._reg_recovered.inc()
            events.emit("decode_recover", sid=seq.sid,
                        src=rep.index, dst=target.index,
                        generated=len(seq.generated()),
                        recoveries=seq.recoveries)
        for seq, fin, err in resolved:
            if fin == "cancelled":
                events.emit("decode_cancel", sid=seq.sid,
                            generated=len(seq.generated()))
            else:
                events.emit("decode_error", sid=seq.sid,
                            where="quarantine",
                            error=type(err).__name__)
            self._resolve(seq, fin, error=err)

    def _maybe_self_check(self):
        now = time.monotonic()
        with self._cond:
            if now < self._next_self_check:
                return
            self._next_self_check = now + self._self_check_interval
        self.self_check()

    def self_check(self):
        """Reconcile every allocator against the sequences the
        scheduler actually holds — the periodic backstop behind
        :meth:`assert_no_leaks`.  An allocation owned by NO queued or
        active sequence is a leak: reclaimed here, counted on
        ``decode.kv_leaked``, and reported so the gate fails loudly
        instead of the pool quietly shrinking.  -> pages reclaimed."""
        leaked = 0
        stale = []
        with self._cond:
            for rep in self._replicas:
                owned = {s.sid for s in rep.active}
                owned.update(s.sid for s in rep.queue)
                for sid in rep.cache.sequence_ids():
                    if sid not in owned:
                        n = rep.cache.free(sid)
                        leaked += n
                        stale.append((rep.index, sid, n))
            if leaked:
                self._n_kv_leaked += leaked
        for rep_index, sid, n in stale:
            self._reg_kv_leaked.inc(n)
            events.emit("decode_kv_leak", replica=rep_index, sid=sid,
                        pages=n)
        return leaked

    # -- hot reload -----------------------------------------------------
    def set_params(self, state, step=None):
        """Swap every replica's params reference.  In-flight sequences
        keep their pinned params (finish on what they started with);
        sequences admitted after this call see the new ones — zero
        dropped mid-decode sequences, the blue/green contract."""
        params = (state["params"]
                  if isinstance(state, dict) and "params" in state
                  else state)
        for rep in self._replicas:
            rep.put_params(params)
        self._host_params = params
        self.reload_count += 1
        metrics.counter("serve.reloads").inc()
        events.emit("serve_reload", step=step, role="decode",
                    replicas=len(self._replicas))

    # -- elastic replica set --------------------------------------------
    def resize(self, n):
        """Grow or shrink the replica set (the autoscaler's actuation
        seam).  Grow: fresh replicas with fresh KV pools on the
        construction device list.  Shrink: retired replicas stop
        admitting, finish every sequence they hold, then exit (nothing
        admitted is ever dropped).  -> the new live replica count."""
        n = int(n)
        if n < 1:
            raise ValueError(f"resize({n}): must keep >= 1 replica")
        started = []
        with self._cond:
            if self._stopped or self._draining:
                raise Overloaded(
                    "stopped" if self._stopped else "draining")
            live = [r for r in self._replicas if not r.retiring]
            cur = len(live)
            if n < cur:
                for rep in live[n:]:
                    rep.retiring = True
                self._rr = 0
                self._cond.notify_all()
            elif n > cur:
                for _ in range(n - cur):
                    idx = self._next_replica_index
                    self._next_replica_index += 1
                    rep = self._make_replica(idx)
                    self._replicas.append(rep)
                    t = threading.Thread(
                        target=self._worker_main, args=(rep,),
                        daemon=True, name=f"dk-decode-worker-{idx}")
                    self._workers.append(t)
                    started.append(t)
        for t in started:
            t.start()
        return n

    # -- lifecycle ------------------------------------------------------
    def drain(self, timeout_s=None):
        """Stop admission (typed rejection), let every admitted
        sequence decode to completion, then stop the schedulers.
        Nothing admitted is ever dropped.  -> delivery counts."""
        t0 = time.perf_counter()
        with self._cond:
            self._draining = True
            backlog = self._outstanding
            self._cond.notify_all()
        events.emit("serve_drain_begin", backlog=backlog,
                    role="decode")
        deadline = (None if timeout_s is None
                    else time.monotonic() + timeout_s)
        with self._cond:
            while self._outstanding:
                remaining = (None if deadline is None
                             else deadline - time.monotonic())
                if remaining is not None and remaining <= 0:
                    raise TimeoutError(
                        f"drain: {self._outstanding} sequences still "
                        f"in flight after {timeout_s}s")
                self._cond.wait(remaining)
        self._shutdown_threads()
        out = {"delivered": self._n_completed,
               "errored": self._n_errors,
               "rejected": self._n_rejected,
               "cancelled": self._n_cancelled,
               "duration_s": time.perf_counter() - t0}
        events.emit("decode_drain", **out)
        return out

    def _shutdown_threads(self):
        with self._cond:
            first = not self._stopped
            self._stopped = True
            self._cond.notify_all()
        if not first:
            self._drained.wait(timeout=10)
            return
        for t in self._workers:
            if t is not threading.current_thread():
                t.join(timeout=10)
        self._drained.set()

    def close(self, drain=True, timeout_s=None):
        """Stop the engine.  ``drain=True`` finishes the backlog;
        ``drain=False`` fails unresolved sequences with a typed
        :class:`Overloaded` and reclaims their pages (never a silent
        drop, never a leaked page)."""
        if self._stopped:
            return
        if drain:
            self.drain(timeout_s=timeout_s)
            return
        with self._cond:
            self._draining = True
        self._shutdown_threads()
        orphans = []
        with self._cond:
            for rep in self._replicas:
                for seq in list(rep.queue) + list(rep.active):
                    orphans.append((rep, seq))
                rep.queue.clear()
                del rep.active[:]
            for rep, seq in orphans:
                self._finish_locked(rep, seq, "stopped")
            pending = list(self._orphans)
            self._orphans[:] = []
            for seq in pending:   # page-less: bookkeeping half only
                self._account_exit_locked(seq, "stopped")
        for _, seq in orphans:
            self._resolve(seq, None, error=Overloaded("stopped"))
        for seq in pending:
            self._resolve(seq, None, error=Overloaded("stopped"))

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    @property
    def draining(self):
        return self._draining

    @property
    def running(self):
        return not self._stopped

    def kv_stats(self):
        """Aggregate + per-replica page-pool accounting."""
        per = [r.cache.stats() for r in self._replicas
               if not r.retiring]
        total = sum(p["num_pages"] for p in per)
        used = sum(p["used_pages"] for p in per)
        return {
            "num_pages": total,
            "used_pages": used,
            "peak_pages": sum(p["peak_pages"] for p in per),
            "occupancy": (used / total) if total else 0.0,
            "sequences": sum(p["sequences"] for p in per),
            "replicas": per,
        }

    def assert_no_leaks(self):
        """Every replica's allocator balances and, when idle, holds
        zero pages — the chaos sweep / gate invariant."""
        for rep in self._replicas:
            rep.cache.assert_balanced()
        with self._cond:
            idle = self._outstanding == 0
        if idle:
            for rep in self._replicas:
                used = rep.cache.used_pages()
                if used:
                    raise AssertionError(
                        f"replica {rep.index} leaked {used} KV pages "
                        "with no sequence outstanding")

    def stats(self):
        """JSON-ready engine counters — the ``/metricsz`` payload core
        (same retrace contract as ``ServingEngine.stats``)."""
        with self._cond:
            queued = sum(len(r.queue) for r in self._replicas)
            active = sum(len(r.active) for r in self._replicas)
            outstanding = self._outstanding
            shapes = sorted(self._shapes)
            live = len(self._live_replicas_locked())
            dead = sum(1 for r in self._replicas if r.dead)
        return {
            "replicas": live,
            "prefill_ladder": list(self.prefill_ladder),
            "decode_ladder": list(self.decode_ladder),
            "page_size": self.page_size,
            "queued": queued,
            "active": active,
            "pending": queued,
            "outstanding": outstanding,
            "admitted": self._n_admitted,
            "completed": self._n_completed,
            "rejected": self._n_rejected,
            "errors": self._n_errors,
            "cancelled": self._n_cancelled,
            "tokens": self._n_tokens,
            "quarantines": self._n_quarantines,
            "recovered": self._n_recovered,
            "shed": self._n_shed,
            "deadline_infeasible": self._n_deadline_infeasible,
            "deadline_expired": self._n_deadline_expired,
            "kv_leaked": self._n_kv_leaked,
            "orphans_pending": len(self._orphans),
            "replicas_dead": dead,
            "reloads": self.reload_count,
            "shapes_dispatched": shapes,
            # the no-retrace bound: prefill rungs + decode rungs ever
            # dispatched (executables are shapes x replica devices on
            # top, both factors fixed)
            "retrace_count": len(shapes),
            "retrace_bound": (len(self.prefill_ladder)
                              + len(self.decode_ladder)),
            "draining": self._draining,
            "kv": self.kv_stats(),
            "ttft_s": self._m_ttft.summary(),
            "step_s": self._m_step.summary(),
        }
