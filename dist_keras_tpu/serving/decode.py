"""Continuous-batching autoregressive decode engine over paged KV.

``ServingEngine`` packs fixed-shape classifier-style forward passes;
this engine serves the LLM-shaped workload the rest of the repo was
built for (causal ``models/transformer.py``, the flash/paged Pallas
kernels): token-level scheduling with per-sequence futures, no batch
barrier.

Design points (each mirrors an existing engine contract):

- **Continuous batching.**  A per-replica scheduler thread runs one
  decode iteration at a time over the replica's ACTIVE sequence set;
  between iterations it admits queued sequences into free slots and
  retires finished ones — a short sequence exits early and its slot
  refills on the very next iteration, never waiting for neighbours
  (the continuous-batching line of work in PAPERS.md).
- **Prefill / decode phase split, both ladder-bounded.**  A sequence's
  prompt runs ONCE through a fixed-shape prefill ladder (padded like
  the serving batch ladder); every subsequent token runs through a
  fixed ladder of decode SLOT counts.  Dispatched executable shapes
  are therefore bounded by ``len(prefill_ladder) + len(decode_ladder)``
  (x replica devices, inherent) — the same no-retrace contract
  ``ServingEngine.stats()["retrace_count"]`` verifies, reported the
  same way.
- **Paged KV.**  Each replica owns one KV pool array of shape
  ``(layers, heads, num_pages + 1, page_size, head_dim)`` and a
  :class:`~dist_keras_tpu.serving.kv_cache.PagedKVCache` allocator.
  Admission reserves a sequence's WORST-CASE page count up front, so
  decode never stalls mid-sequence on KV: exhaustion is a typed
  ``Overloaded(reason="kv_exhausted")`` strictly at the door (rejected,
  not lost), and completion/cancel/error all reclaim through the one
  allocator path (zero leaked pages — the chaos tests assert it).
- **Hot reload never drops a sequence.**  ``submit_generate`` pins the
  replica's CURRENT params reference into the sequence; a
  ``set_params`` (CheckpointWatcher promotion, blue/green cutover)
  swaps the replica reference only — in-flight sequences finish on the
  params they started with, decode iterations simply group active
  sequences by params generation (at most a couple in flight).
- **Typed errors, never hangs.**  The ``decode.admit`` /
  ``decode.kv_alloc`` / ``decode.step`` fault points cover admission,
  page reservation and the step dispatch; any failure lands typed on
  the affected sequences' futures with their pages reclaimed.

Observability: ``decode_*`` events at every seam, ``decode.*``
registry metrics (TTFT and step-time histograms carry trace
exemplars), and with tracing on each request's trace gains
``serve.queue_wait`` + ``serve.prefill`` spans stamped from the
scheduler thread — time-to-first-token is attributable per request.
The ``generate_ttft`` / ``generate_tokens`` SLO objectives read these
surfaces (``observability/slo.py``).
"""

from __future__ import annotations

import collections
import itertools
import threading
import time
from concurrent.futures import Future

import numpy as np

import jax
import jax.numpy as jnp

from dist_keras_tpu.models.transformer import layer_norm
from dist_keras_tpu.observability import events, metrics, perf, spans
from dist_keras_tpu.ops.pallas.decode_attention import (
    paged_attention_auto,
)
from dist_keras_tpu.ops.pallas.flash_attention import (
    attention_auto,
    use_pallas,
)
from dist_keras_tpu.resilience.faults import fault_point
from dist_keras_tpu.serving.engine import Overloaded
from dist_keras_tpu.serving.kv_cache import PagedKVCache, PagesExhausted
from dist_keras_tpu.utils.serialization import (
    deserialize_model,
    serialize_model,
)


class _Sequence:
    """One admitted generation: host-side state the scheduler owns."""

    __slots__ = ("sid", "tokens", "prompt_len", "max_new", "eos_id",
                 "future", "on_token", "t", "tw", "ctx", "params",
                 "pages", "kv_len", "steps", "cancelled", "ttft_s",
                 "t_first")

    def __init__(self, sid, tokens, max_new, eos_id, on_token, params,
                 pages):
        self.sid = sid
        self.tokens = list(tokens)
        self.prompt_len = len(tokens)
        self.max_new = max_new
        self.eos_id = eos_id
        self.future = Future()
        self.on_token = on_token
        self.t = time.monotonic()
        self.tw = time.time()
        self.ctx = spans.capture()
        self.params = params      # pinned: reloads never touch us
        self.pages = pages
        self.kv_len = 0           # KV positions written so far
        self.steps = 0            # decode iterations consumed
        self.cancelled = False
        self.ttft_s = None
        self.t_first = None

    def generated(self):
        return self.tokens[self.prompt_len:]

    def result_doc(self, finish):
        return {
            "tokens": list(self.tokens),
            "generated": self.generated(),
            "prompt_len": self.prompt_len,
            "steps": self.steps,
            "ttft_s": self.ttft_s,
            "finish": finish,
        }


class Generation:
    """Caller-side handle: a future plus a cancel seam (cancel reclaims
    the sequence's KV pages; the future resolves with
    ``finish="cancelled"`` and the tokens produced so far)."""

    def __init__(self, engine, seq):
        self._engine = engine
        self._seq = seq
        self.future = seq.future

    def result(self, timeout=None):
        return self.future.result(timeout=timeout)

    def cancel(self):
        return self._engine.cancel(self)

    def done(self):
        return self.future.done()


class _DecodeReplica:
    """One replica: pinned device, params swap point, its KV pool."""

    def __init__(self, index, device, params, cache, kp, vp):
        self.index = index
        self.device = device
        self.params = (jax.device_put(params, device)
                       if device is not None else params)
        self.cache = cache
        self.kp = kp
        self.vp = vp
        self.queue = collections.deque()
        self.active = []
        self.retiring = False
        self.steps = 0

    def put_params(self, params):
        self.params = (jax.device_put(params, self.device)
                       if self.device is not None else params)


class DecodeEngine:
    """Continuous-batching decode over the causal Transformer.

    Args:
      keras_model: a ``models.transformer.Transformer`` (or anything
        the serialization layer round-trips to one).  Decode needs
        token in == logit out, so the config must have
        ``input_dim == n_classes`` (the vocabulary); MoE configs are
        rejected.
      replicas: replica count (default: one per visible device).
      prefill_ladder: ascending fixed PROMPT shapes; a prompt runs
        padded to the smallest rung that fits (``ValueError`` past the
        largest — the front end's 400).
      decode_ladder: ascending fixed SLOT counts for decode steps; the
        largest rung is the per-replica concurrency cap.
      page_size: KV positions per page.
      num_pages: pool pages per replica.  Default sizes the pool so a
        full slot set of maximum-length sequences fits.
      max_queue: admission bound on admitted-but-unresolved sequences.
      max_new_default: ``max_new_tokens`` when a request omits it.
      eos_id: default stop token (None = length-only stopping).
      devices: explicit device list (default ``jax.devices()``).
    """

    def __init__(self, keras_model, replicas=None,
                 prefill_ladder=(16, 64), decode_ladder=(1, 4, 8),
                 page_size=8, num_pages=None, max_queue=256,
                 max_new_default=16, eos_id=None, devices=None):
        self.serialized = serialize_model(keras_model)
        model = deserialize_model(self.serialized)
        cfg = getattr(model, "cfg", None)
        if cfg is None:
            raise ValueError(
                "DecodeEngine needs the causal Transformer model "
                "contract (a cfg dict); got "
                f"{type(model).__name__}")
        if cfg.get("moe_experts", 0):
            raise ValueError("MoE configs are not decodable here")
        if cfg["input_dim"] != cfg["n_classes"]:
            raise ValueError(
                "causal decode needs token-in == logit-out: "
                f"input_dim={cfg['input_dim']} != "
                f"n_classes={cfg['n_classes']}")
        self.cfg = cfg
        self.vocab = int(cfg["n_classes"])
        self.seq_len = int(cfg["seq_len"])
        self._host_params = model.params

        ladder = sorted(set(int(b) for b in prefill_ladder))
        if not ladder or ladder[0] < 1 or ladder[-1] > self.seq_len:
            raise ValueError(
                f"prefill_ladder {prefill_ladder!r} must hold positive "
                f"ints <= seq_len ({self.seq_len})")
        self.prefill_ladder = tuple(ladder)
        slots = sorted(set(int(b) for b in decode_ladder))
        if not slots or slots[0] < 1:
            raise ValueError(
                f"decode_ladder {decode_ladder!r} must hold positive "
                "ints")
        self.decode_ladder = tuple(slots)
        self.max_slots = slots[-1]
        self.max_queue = int(max_queue)
        self.max_new_default = int(max_new_default)
        self.eos_id = eos_id if eos_id is None else int(eos_id)
        self.page_size = int(page_size)
        self.max_pages_per_seq = -(-self.seq_len // self.page_size)
        if num_pages is None:
            num_pages = self.max_slots * self.max_pages_per_seq
        self.num_pages = int(num_pages)

        d, h = cfg["d_model"], cfg["n_heads"]
        self._dh = d // h
        self._heads = h
        self._layers = int(cfg["n_layers"])
        # donation keeps the pool update in place on TPU; CPU jax would
        # warn-and-copy, so only donate where donation is real
        donate = (1, 2) if use_pallas() else ()
        self._prefill_jit = jax.jit(self._prefill_fn,
                                    donate_argnums=donate)
        self._decode_jit = jax.jit(self._decode_fn,
                                   donate_argnums=donate)

        if devices is None:
            devices = jax.devices()
        n = int(replicas) if replicas is not None else len(devices)
        if n < 1:
            raise ValueError(f"replicas={replicas} must be >= 1")
        self._devices = list(devices) if devices else []
        self._next_replica_index = n
        self._seq_ids = itertools.count()
        self._replicas = [self._make_replica(i) for i in range(n)]

        self._cond = threading.Condition()
        self._outstanding = 0
        self._draining = False
        self._stopped = False
        self._drained = threading.Event()
        self._rr = 0
        self._shapes = set()      # (phase, rung) dispatched
        self.reload_count = 0

        # engine-local instruments + the shared process registry (the
        # same split ServingEngine documents: per-engine truths vs
        # process-wide aggregates)
        self._m_ttft = metrics.Histogram("decode.ttft_s")
        self._m_step = metrics.Histogram("decode.step_s")
        self._n_admitted = 0
        self._n_completed = 0
        self._n_rejected = 0
        self._n_errors = 0
        self._n_cancelled = 0
        self._n_tokens = 0
        self._reg_admitted = metrics.counter("decode.admitted")
        self._reg_completed = metrics.counter("decode.completed")
        self._reg_rejected = metrics.counter("decode.rejected")
        self._reg_errors = metrics.counter("decode.errors")
        self._reg_cancelled = metrics.counter("decode.cancelled")
        self._reg_tokens = metrics.counter("decode.tokens")
        self._reg_ttft = metrics.histogram("decode.ttft_s")
        self._reg_step = metrics.histogram("decode.step_s")
        self._reg_active = metrics.gauge("decode.active")
        self._reg_kv = metrics.gauge("decode.kv_used_pages")
        perf.install()  # retrace listener: the ladder bound, verified

        self._workers = [threading.Thread(
            target=self._worker_loop, args=(rep,), daemon=True,
            name=f"dk-decode-worker-{rep.index}")
            for rep in self._replicas]
        for t in self._workers:
            t.start()

    # -- model math (jitted once per ladder rung) -----------------------
    def _make_replica(self, index):
        devs = self._devices
        device = devs[index % len(devs)] if devs else None
        cache = PagedKVCache(self.num_pages, self.page_size)
        shape = (self._layers, self._heads, self.num_pages + 1,
                 self.page_size, self._dh)
        kp = jnp.zeros(shape, jnp.float32)
        vp = jnp.zeros(shape, jnp.float32)
        if device is not None:
            kp = jax.device_put(kp, device)
            vp = jax.device_put(vp, device)
        return _DecodeReplica(index, device, self._host_params, cache,
                              kp, vp)

    def _prefill_fn(self, params, kp, vp, tokens, length, page_idx,
                    page_off):
        """One padded prompt -> (first generated token, updated pools).

        ``tokens (T,) int32`` padded to a prefill rung; positions past
        ``length`` write their K/V to the scratch page (``page_idx``
        routes them there) and never influence position ``length - 1``
        under the causal mask."""
        t = tokens.shape[0]
        x = jax.nn.one_hot(tokens, self.vocab, dtype=kp.dtype)
        hs = (x @ params["proj"] + params["pos"][:t])[None]
        for li, blk in enumerate(params["blocks"]):
            y = layer_norm(blk["ln1"], hs)
            q = jnp.einsum("btd,dhk->bthk", y, blk["wq"])
            k = jnp.einsum("btd,dhk->bthk", y, blk["wk"])
            v = jnp.einsum("btd,dhk->bthk", y, blk["wv"])
            # scalar layer + page arrays are non-adjacent advanced
            # indices: the update's broadcast dims lead -> (T, H, dh)
            kp = kp.at[li, :, page_idx, page_off, :].set(k[0])
            vp = vp.at[li, :, page_idx, page_off, :].set(v[0])
            a = attention_auto(q, k, v, causal=True)
            hs = hs + jnp.einsum("bthk,hkd->btd", a, blk["wo"])
            y = layer_norm(blk["ln2"], hs)
            u = jax.nn.gelu(y @ blk["w1"] + blk["b1"])
            hs = hs + u @ blk["w2"] + blk["b2"]
        hf = layer_norm(params["ln_f"], hs)[0, length - 1]
        logits = hf @ params["head"]["kernel"] + params["head"]["bias"]
        return jnp.argmax(logits).astype(jnp.int32), kp, vp

    def _decode_fn(self, params, kp, vp, tokens, positions, page_tables,
                   write_page, write_off, lengths):
        """One token step for a padded slot set -> (next tokens,
        updated pools).  Padding slots carry ``length == 0`` and write
        to the scratch page; the paged attention's dead-row guard
        makes their output exact zeros (then discarded)."""
        x = (jax.nn.one_hot(tokens, self.vocab, dtype=kp.dtype)
             @ params["proj"] + params["pos"][positions])
        hs = x
        for li, blk in enumerate(params["blocks"]):
            y = layer_norm(blk["ln1"], hs)
            q = jnp.einsum("sd,dhk->shk", y, blk["wq"])
            k = jnp.einsum("sd,dhk->shk", y, blk["wk"])
            v = jnp.einsum("sd,dhk->shk", y, blk["wv"])
            kp = kp.at[li, :, write_page, write_off, :].set(k)
            vp = vp.at[li, :, write_page, write_off, :].set(v)
            a = paged_attention_auto(q, kp[li], vp[li], page_tables,
                                     lengths)
            hs = hs + jnp.einsum("shk,hkd->sd", a, blk["wo"])
            y = layer_norm(blk["ln2"], hs)
            u = jax.nn.gelu(y @ blk["w1"] + blk["b1"])
            hs = hs + u @ blk["w2"] + blk["b2"]
        hf = layer_norm(params["ln_f"], hs)
        logits = hf @ params["head"]["kernel"] + params["head"]["bias"]
        return jnp.argmax(logits, axis=-1).astype(jnp.int32), kp, vp

    # -- admission ------------------------------------------------------
    def _rung_for(self, n, ladder):
        for b in ladder:
            if n <= b:
                return b
        return None

    def _pick_replica(self, needed_pages):
        """Most free pages wins (KV is the scarce resource), round-robin
        on ties; retiring replicas are out of rotation.  Caller holds
        the lock."""
        live = [r for r in self._replicas if not r.retiring]
        if not live:
            return None, 0
        frees = [r.cache.stats()["free_pages"] for r in live]
        best = max(frees)
        order = range(self._rr, self._rr + len(live))
        for i in order:
            i %= len(live)
            if frees[i] == best:
                self._rr = (i + 1) % len(live)
                return (live[i] if best >= needed_pages else None), best
        return None, best  # pragma: no cover - unreachable

    def submit_generate(self, tokens, max_new_tokens=None, eos_id=None,
                        on_token=None):
        """Admit one prompt; -> :class:`Generation` whose future
        resolves to the result doc (tokens, ttft_s, finish reason).
        Raises :class:`Overloaded` at the door (``queue_full`` /
        ``kv_exhausted`` / ``draining`` / ``stopped``) and
        ``ValueError`` for malformed prompts — rejected, never lost."""
        fault_point("decode.admit")
        toks = [int(t) for t in tokens]
        if not toks:
            raise ValueError("empty prompt")
        if any(t < 0 or t >= self.vocab for t in toks):
            raise ValueError(
                f"prompt tokens must be in [0, {self.vocab})")
        max_new = (self.max_new_default if max_new_tokens is None
                   else int(max_new_tokens))
        if max_new < 1:
            raise ValueError(f"max_new_tokens={max_new} must be >= 1")
        rung = self._rung_for(len(toks), self.prefill_ladder)
        if rung is None:
            raise ValueError(
                f"prompt length {len(toks)} exceeds the prefill "
                f"ladder (max {self.prefill_ladder[-1]})")
        total = len(toks) + max_new
        if total > self.seq_len:
            raise ValueError(
                f"prompt + max_new_tokens = {total} exceeds the "
                f"model's seq_len ({self.seq_len})")
        eos = self.eos_id if eos_id is None else int(eos_id)
        with self._cond:
            if self._draining or self._stopped:
                self._n_rejected += 1
                self._reg_rejected.inc()
                raise Overloaded(
                    "draining" if self._draining else "stopped")
            if self._outstanding >= self.max_queue:
                self._n_rejected += 1
                self._reg_rejected.inc()
                raise Overloaded("queue_full",
                                 pending=self._outstanding,
                                 capacity=self.max_queue)
            sid = next(self._seq_ids)
            needed = max(1, -(-total // self.page_size))
            rep, best_free = self._pick_replica(needed)
            if rep is None:
                self._n_rejected += 1
                self._reg_rejected.inc()
                raise Overloaded("kv_exhausted", pending=needed,
                                 capacity=best_free)
            # the allocator's own fault point (decode.kv_alloc) fires
            # inside; a raise here admits nothing and leaks nothing
            pages = rep.cache.alloc(sid, total)
            seq = _Sequence(sid, toks, max_new, eos, on_token,
                            rep.params, pages)
            rep.queue.append(seq)
            self._outstanding += 1
            self._n_admitted += 1
            self._reg_active.set(self._outstanding)
            self._cond.notify_all()
        self._reg_admitted.inc()
        events.emit("decode_admit", sid=sid, prompt_len=len(toks),
                    max_new=max_new, replica=rep.index,
                    pages=len(pages))
        return Generation(self, seq)

    def generate(self, tokens, max_new_tokens=None, eos_id=None,
                 timeout_s=None):
        """Blocking convenience: submit one prompt, wait for the doc."""
        return self.submit_generate(
            tokens, max_new_tokens=max_new_tokens,
            eos_id=eos_id).result(timeout=timeout_s)

    def cancel(self, generation):
        """Cancel a generation: reclaim its pages and resolve its
        future with ``finish="cancelled"`` (tokens so far).  -> True if
        the cancel landed before completion."""
        seq = generation._seq
        dequeued = False
        with self._cond:
            if seq.future.done() or seq.cancelled:
                return False
            seq.cancelled = True
            # still queued on some replica? finish it here, never
            # occupying a slot
            for rep in self._replicas:
                if seq in rep.queue:
                    rep.queue.remove(seq)
                    self._finish_locked(rep, seq, "cancelled")
                    dequeued = True
                    break
            self._cond.notify_all()
        if dequeued:
            events.emit("decode_cancel", sid=seq.sid,
                        generated=len(seq.generated()))
            self._resolve(seq, "cancelled")
        return True  # active: the scheduler retires it next iteration

    # -- scheduler ------------------------------------------------------
    def _resolve(self, seq, finish, error=None):
        """Resolve a sequence's future OUTSIDE the lock."""
        if error is not None:
            seq.future.set_exception(error)
        else:
            seq.future.set_result(seq.result_doc(finish))

    def _finish_locked(self, rep, seq, finish):
        """Account one sequence's exit (caller holds the lock):
        reclaim pages, bump counters.  The single reclamation seam for
        complete/cancel/error — zero leaked pages by construction."""
        rep.cache.free(seq.sid)
        self._outstanding -= 1
        if finish == "error":
            self._n_errors += 1
            self._reg_errors.inc()
        elif finish == "cancelled":
            self._n_cancelled += 1
            self._reg_cancelled.inc()
        elif finish == "stopped":
            # a close(drain=False) abort is a rejection, not a model
            # error — rejected-not-lost, same as the door
            self._n_rejected += 1
            self._reg_rejected.inc()
        else:
            self._n_completed += 1
            self._reg_completed.inc()
        self._reg_active.set(self._outstanding)
        self._reg_kv.set(sum(r.cache.used_pages()
                             for r in self._replicas))
        self._cond.notify_all()

    def _emit_token(self, seq, token):
        seq.tokens.append(int(token))
        self._n_tokens += 1
        self._reg_tokens.inc()
        if seq.on_token is not None:
            try:
                seq.on_token(int(token))
            # dklint: ignore[broad-except] a caller's token callback must never kill the scheduler thread
            except Exception as e:
                events.emit("decode_error", sid=seq.sid,
                            where="on_token", error=type(e).__name__)

    def _sequence_done(self, seq, token):
        if seq.eos_id is not None and int(token) == seq.eos_id:
            return "eos"
        if len(seq.generated()) >= seq.max_new:
            return "length"
        return None

    def _prefill(self, rep, seq):
        """Run one admitted prompt through the prefill ladder; emits
        the first generated token (TTFT) or fails the sequence typed."""
        rung = self._rung_for(seq.prompt_len, self.prefill_ladder)
        toks = np.zeros((rung,), np.int32)
        toks[:seq.prompt_len] = seq.tokens
        scratch = rep.cache.scratch_page
        page_idx = np.full((rung,), scratch, np.int32)
        ps = self.page_size
        for t in range(seq.prompt_len):
            page_idx[t] = seq.pages[t // ps]
        page_off = (np.arange(rung, dtype=np.int32) % ps)
        t0 = time.perf_counter()
        tw0 = time.time()
        if events.enabled():
            spans.span_at("serve.queue_wait", seq.ctx, seq.tw, tw0)
        try:
            perf.count_dispatch()
            first, rep.kp, rep.vp = self._prefill_jit(
                seq.params, rep.kp, rep.vp, jnp.asarray(toks),
                jnp.int32(seq.prompt_len), jnp.asarray(page_idx),
                jnp.asarray(page_off))
            first = int(first)
        # dklint: ignore[broad-except] a failed prefill lands TYPED on its own future with pages reclaimed
        except Exception as e:
            with self._cond:
                rep.active.remove(seq)
                self._finish_locked(rep, seq, "error")
            events.emit("decode_error", sid=seq.sid, where="prefill",
                        error=type(e).__name__)
            self._resolve(seq, None, error=e)
            return
        dt = time.perf_counter() - t0
        with self._cond:
            self._shapes.add(("prefill", rung))
        seq.kv_len = seq.prompt_len
        seq.ttft_s = time.monotonic() - seq.t
        seq.t_first = time.time()
        ex = ((seq.ctx.trace_id, seq.ctx.span_id)
              if seq.ctx is not None else None)
        self._m_ttft.observe(seq.ttft_s, exemplar=ex)
        self._reg_ttft.observe(seq.ttft_s, exemplar=ex)
        if events.enabled():
            spans.span_at("serve.prefill", seq.ctx, tw0, time.time(),
                          rung=rung, replica=rep.index)
        events.emit("decode_prefill", sid=seq.sid, rung=rung,
                    replica=rep.index, duration_s=dt,
                    ttft_s=seq.ttft_s)
        self._emit_token(seq, first)
        finish = self._sequence_done(seq, first)
        if finish is not None:
            with self._cond:
                rep.active.remove(seq)
                self._finish_locked(rep, seq, finish)
            events.emit("decode_complete", sid=seq.sid, finish=finish,
                        generated=len(seq.generated()),
                        steps=seq.steps)
            self._resolve(seq, finish)

    def _step_group(self, rep, group):
        """One decode step for ``group`` (same pinned params), padded
        to a decode-ladder rung.  A failing step fails exactly this
        group's sequences, typed, pages reclaimed."""
        rung = self._rung_for(len(group), self.decode_ladder)
        scratch = rep.cache.scratch_page
        ps = self.page_size
        pmax = self.max_pages_per_seq
        toks = np.zeros((rung,), np.int32)
        positions = np.zeros((rung,), np.int32)
        tables = np.zeros((rung, pmax), np.int32)
        wpage = np.full((rung,), scratch, np.int32)
        woff = np.zeros((rung,), np.int32)
        lengths = np.zeros((rung,), np.int32)
        for i, seq in enumerate(group):
            toks[i] = seq.tokens[-1]
            positions[i] = seq.kv_len
            tables[i, :len(seq.pages)] = seq.pages
            wpage[i] = seq.pages[seq.kv_len // ps]
            woff[i] = seq.kv_len % ps
            lengths[i] = seq.kv_len + 1
        t0 = time.perf_counter()
        try:
            fault_point("decode.step")
            perf.count_dispatch()
            nxt, rep.kp, rep.vp = self._decode_jit(
                group[0].params, rep.kp, rep.vp, jnp.asarray(toks),
                jnp.asarray(positions), jnp.asarray(tables),
                jnp.asarray(wpage), jnp.asarray(woff),
                jnp.asarray(lengths))
            nxt = np.asarray(nxt)
        # dklint: ignore[broad-except] a failed step lands TYPED on every future in the group, pages reclaimed
        except Exception as e:
            with self._cond:
                for seq in group:
                    rep.active.remove(seq)
                    self._finish_locked(rep, seq, "error")
            events.emit("decode_error", where="step", n=len(group),
                        replica=rep.index, error=type(e).__name__)
            for seq in group:
                self._resolve(seq, None, error=e)
            return
        dt = time.perf_counter() - t0
        rep.steps += 1
        self._m_step.observe(dt)
        self._reg_step.observe(dt)
        with self._cond:
            self._shapes.add(("decode", rung))
        events.emit("decode_step", replica=rep.index, rung=rung,
                    n=len(group), duration_s=dt)
        finished = []
        for i, seq in enumerate(group):
            seq.kv_len += 1
            seq.steps += 1
            self._emit_token(seq, int(nxt[i]))
            finish = self._sequence_done(seq, int(nxt[i]))
            if finish is not None:
                finished.append((seq, finish))
        if finished:
            with self._cond:
                for seq, finish in finished:
                    rep.active.remove(seq)
                    self._finish_locked(rep, seq, finish)
            for seq, finish in finished:
                events.emit("decode_complete", sid=seq.sid,
                            finish=finish,
                            generated=len(seq.generated()),
                            steps=seq.steps)
                self._resolve(seq, finish)

    def _worker_loop(self, rep):
        while True:
            admitted = []
            with self._cond:
                while (not rep.queue and not rep.active
                       and not self._stopped and not rep.retiring):
                    # the scheduler's idle park: deliberately unbounded
                    # — every admit, cancel and both lifecycle exits
                    # notify this cond, and the predicate re-checks
                    # stop/retire on wake
                    # dklint: ignore[unbounded-wait] idle park; admission and lifecycle exits notify this cond
                    self._cond.wait()
                if self._stopped:
                    break
                if rep.retiring and not rep.queue and not rep.active:
                    break
                # retire cancelled actives, refill free slots — the
                # continuous-batching seam: between iterations, never
                # a batch barrier
                cancelled = [s for s in rep.active if s.cancelled]
                for seq in cancelled:
                    rep.active.remove(seq)
                    self._finish_locked(rep, seq, "cancelled")
                while rep.queue and len(rep.active) < self.max_slots:
                    seq = rep.queue.popleft()
                    if seq.cancelled:
                        self._finish_locked(rep, seq, "cancelled")
                        cancelled.append(seq)
                        continue
                    rep.active.append(seq)
                    admitted.append(seq)
            for seq in cancelled:
                events.emit("decode_cancel", sid=seq.sid,
                            generated=len(seq.generated()))
                self._resolve(seq, "cancelled")
            for seq in admitted:
                self._prefill(rep, seq)
            with self._cond:
                # group by pinned params generation: a hot reload means
                # at most a couple of groups until old sequences drain
                groups = {}
                for seq in rep.active:
                    groups.setdefault(id(seq.params), []).append(seq)
                work = list(groups.values())
            for group in work:
                self._step_group(rep, group)

    # -- hot reload -----------------------------------------------------
    def set_params(self, state, step=None):
        """Swap every replica's params reference.  In-flight sequences
        keep their pinned params (finish on what they started with);
        sequences admitted after this call see the new ones — zero
        dropped mid-decode sequences, the blue/green contract."""
        params = (state["params"]
                  if isinstance(state, dict) and "params" in state
                  else state)
        for rep in self._replicas:
            rep.put_params(params)
        self._host_params = params
        self.reload_count += 1
        metrics.counter("serve.reloads").inc()
        events.emit("serve_reload", step=step, role="decode",
                    replicas=len(self._replicas))

    # -- elastic replica set --------------------------------------------
    def resize(self, n):
        """Grow or shrink the replica set (the autoscaler's actuation
        seam).  Grow: fresh replicas with fresh KV pools on the
        construction device list.  Shrink: retired replicas stop
        admitting, finish every sequence they hold, then exit (nothing
        admitted is ever dropped).  -> the new live replica count."""
        n = int(n)
        if n < 1:
            raise ValueError(f"resize({n}): must keep >= 1 replica")
        started = []
        with self._cond:
            if self._stopped or self._draining:
                raise Overloaded(
                    "stopped" if self._stopped else "draining")
            live = [r for r in self._replicas if not r.retiring]
            cur = len(live)
            if n < cur:
                for rep in live[n:]:
                    rep.retiring = True
                self._rr = 0
                self._cond.notify_all()
            elif n > cur:
                for _ in range(n - cur):
                    idx = self._next_replica_index
                    self._next_replica_index += 1
                    rep = self._make_replica(idx)
                    self._replicas.append(rep)
                    t = threading.Thread(
                        target=self._worker_loop, args=(rep,),
                        daemon=True, name=f"dk-decode-worker-{idx}")
                    self._workers.append(t)
                    started.append(t)
        for t in started:
            t.start()
        return n

    # -- lifecycle ------------------------------------------------------
    def drain(self, timeout_s=None):
        """Stop admission (typed rejection), let every admitted
        sequence decode to completion, then stop the schedulers.
        Nothing admitted is ever dropped.  -> delivery counts."""
        t0 = time.perf_counter()
        with self._cond:
            self._draining = True
            backlog = self._outstanding
            self._cond.notify_all()
        events.emit("serve_drain_begin", backlog=backlog,
                    role="decode")
        deadline = (None if timeout_s is None
                    else time.monotonic() + timeout_s)
        with self._cond:
            while self._outstanding:
                remaining = (None if deadline is None
                             else deadline - time.monotonic())
                if remaining is not None and remaining <= 0:
                    raise TimeoutError(
                        f"drain: {self._outstanding} sequences still "
                        f"in flight after {timeout_s}s")
                self._cond.wait(remaining)
        self._shutdown_threads()
        out = {"delivered": self._n_completed,
               "errored": self._n_errors,
               "rejected": self._n_rejected,
               "cancelled": self._n_cancelled,
               "duration_s": time.perf_counter() - t0}
        events.emit("decode_drain", **out)
        return out

    def _shutdown_threads(self):
        with self._cond:
            first = not self._stopped
            self._stopped = True
            self._cond.notify_all()
        if not first:
            self._drained.wait(timeout=10)
            return
        for t in self._workers:
            if t is not threading.current_thread():
                t.join(timeout=10)
        self._drained.set()

    def close(self, drain=True, timeout_s=None):
        """Stop the engine.  ``drain=True`` finishes the backlog;
        ``drain=False`` fails unresolved sequences with a typed
        :class:`Overloaded` and reclaims their pages (never a silent
        drop, never a leaked page)."""
        if self._stopped:
            return
        if drain:
            self.drain(timeout_s=timeout_s)
            return
        with self._cond:
            self._draining = True
        self._shutdown_threads()
        orphans = []
        with self._cond:
            for rep in self._replicas:
                for seq in list(rep.queue) + list(rep.active):
                    orphans.append((rep, seq))
                rep.queue.clear()
                del rep.active[:]
            for rep, seq in orphans:
                self._finish_locked(rep, seq, "stopped")
        for _, seq in orphans:
            self._resolve(seq, None, error=Overloaded("stopped"))

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    @property
    def draining(self):
        return self._draining

    @property
    def running(self):
        return not self._stopped

    def kv_stats(self):
        """Aggregate + per-replica page-pool accounting."""
        per = [r.cache.stats() for r in self._replicas
               if not r.retiring]
        total = sum(p["num_pages"] for p in per)
        used = sum(p["used_pages"] for p in per)
        return {
            "num_pages": total,
            "used_pages": used,
            "peak_pages": sum(p["peak_pages"] for p in per),
            "occupancy": (used / total) if total else 0.0,
            "sequences": sum(p["sequences"] for p in per),
            "replicas": per,
        }

    def assert_no_leaks(self):
        """Every replica's allocator balances and, when idle, holds
        zero pages — the chaos sweep / gate invariant."""
        for rep in self._replicas:
            rep.cache.assert_balanced()
        with self._cond:
            idle = self._outstanding == 0
        if idle:
            for rep in self._replicas:
                used = rep.cache.used_pages()
                if used:
                    raise AssertionError(
                        f"replica {rep.index} leaked {used} KV pages "
                        "with no sequence outstanding")

    def stats(self):
        """JSON-ready engine counters — the ``/metricsz`` payload core
        (same retrace contract as ``ServingEngine.stats``)."""
        with self._cond:
            queued = sum(len(r.queue) for r in self._replicas)
            active = sum(len(r.active) for r in self._replicas)
            outstanding = self._outstanding
            shapes = sorted(self._shapes)
            live = sum(1 for r in self._replicas if not r.retiring)
        return {
            "replicas": live,
            "prefill_ladder": list(self.prefill_ladder),
            "decode_ladder": list(self.decode_ladder),
            "page_size": self.page_size,
            "queued": queued,
            "active": active,
            "pending": queued,
            "outstanding": outstanding,
            "admitted": self._n_admitted,
            "completed": self._n_completed,
            "rejected": self._n_rejected,
            "errors": self._n_errors,
            "cancelled": self._n_cancelled,
            "tokens": self._n_tokens,
            "reloads": self.reload_count,
            "shapes_dispatched": shapes,
            # the no-retrace bound: prefill rungs + decode rungs ever
            # dispatched (executables are shapes x replica devices on
            # top, both factors fixed)
            "retrace_count": len(shapes),
            "retrace_bound": (len(self.prefill_ladder)
                              + len(self.decode_ladder)),
            "draining": self._draining,
            "kv": self.kv_stats(),
            "ttft_s": self._m_ttft.summary(),
            "step_s": self._m_step.summary(),
        }
