"""CPU-runnable serving benchmark — sustained QPS + latency percentiles.

Offers a FIXED request rate at the engine (a paced scheduler thread
submits; completion callbacks stamp per-request latency) and reports
what the engine actually sustained: achieved QPS, p50/p99/max latency,
rejections, fill ratio, and the retrace bound.  Offered-load (rather
than closed-loop) measurement is what serving SLOs are written against:
a closed loop self-throttles to the server's speed and hides queueing
delay entirely.

Runs anywhere — the model is tiny and ``JAX_PLATFORMS=cpu`` suffices —
which is the point: ``bench.py`` invokes this in a CPU-pinned
subprocess, so BENCH rounds report a real serving number even when the
device backend probe times out (the all-null BENCH failure mode).

CLI: ``python -m dist_keras_tpu.serving.bench [--qps N] [--seconds S]``
prints one JSON record on the last stdout line (the bench driver
contract).
"""

from __future__ import annotations

import argparse
import json
import sys
import threading
import time

import numpy as np


def _percentile(xs, q):
    return float(np.percentile(np.asarray(xs, dtype=np.float64), q))


def run_serving_benchmark(offered_qps=400.0, duration_s=4.0,
                          feature_dim=32, hidden=(64,), num_classes=10,
                          batch_ladder=(1, 8, 32, 64), replicas=1,
                          max_latency_s=0.005, max_queue=4096,
                          warmup=True, seed=0):
    """Run one offered-load measurement; -> JSON-ready record dict."""
    # imports deferred so `--help` and a wedged backend never touch jax
    from dist_keras_tpu.models import mnist_mlp
    from dist_keras_tpu.serving.engine import Overloaded, ServingEngine

    model = mnist_mlp(hidden=tuple(hidden), input_dim=int(feature_dim),
                      num_classes=int(num_classes))
    engine = ServingEngine(model, replicas=replicas,
                           batch_ladder=batch_ladder,
                           max_latency_s=max_latency_s,
                           max_queue=max_queue)
    rng = np.random.default_rng(seed)
    rows = rng.normal(size=(256, int(feature_dim))).astype(np.float32)

    if warmup:
        # pre-compile every rung so the measurement window holds zero
        # compiles (a production engine warms the ladder at deploy time
        # the same way)
        for rung in engine.batch_ladder:
            engine.predict(rows[:rung], timeout_s=120)

    latencies = []
    lat_lock = threading.Lock()
    rejected = [0]
    submitted = [0]

    def _submit_one(i):
        t0 = time.monotonic()

        def _done(fut):
            if fut.exception() is None:
                with lat_lock:
                    latencies.append(time.monotonic() - t0)
        try:
            fut = engine.submit(rows[i % len(rows)])
        except Overloaded:
            rejected[0] += 1
        else:
            submitted[0] += 1
            fut.add_done_callback(_done)

    interval = 1.0 / float(offered_qps)
    t_start = time.monotonic()
    next_t = t_start
    i = 0
    while True:
        now = time.monotonic()
        if now - t_start >= duration_s:
            break
        if now < next_t:
            time.sleep(min(next_t - now, 0.005))
            continue
        # catch up without sleeping when the scheduler fell behind —
        # the offered load stays the load, not "what we got around to"
        _submit_one(i)
        i += 1
        next_t += interval
    # deliver the tail before reading the clocks
    engine.drain(timeout_s=60)
    wall = time.monotonic() - t_start
    stats = engine.stats()
    record = {
        "offered_qps": float(offered_qps),
        "duration_s": round(wall, 3),
        "submitted": submitted[0],
        "completed": len(latencies),
        "rejected": rejected[0],
        "achieved_qps": round(len(latencies) / wall, 1) if wall else None,
        "p50_ms": (round(_percentile(latencies, 50) * 1e3, 3)
                   if latencies else None),
        "p99_ms": (round(_percentile(latencies, 99) * 1e3, 3)
                   if latencies else None),
        "max_ms": (round(max(latencies) * 1e3, 3) if latencies else None),
        "mean_fill_ratio": (round(stats["fill_ratio"]["mean"], 4)
                            if stats["fill_ratio"]["mean"] is not None
                            else None),
        "batches": stats["batches"],
        "replicas": stats["replicas"],
        "batch_ladder": stats["batch_ladder"],
        "retrace_count": stats["retrace_count"],
        "retrace_bound": stats["retrace_bound"],
        "errors": stats["errors"],
    }
    return record


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--qps", type=float, default=400.0)
    ap.add_argument("--seconds", type=float, default=4.0)
    ap.add_argument("--replicas", type=int, default=1)
    ap.add_argument("--feature-dim", type=int, default=32)
    args = ap.parse_args(argv)
    record = run_serving_benchmark(offered_qps=args.qps,
                                   duration_s=args.seconds,
                                   replicas=args.replicas,
                                   feature_dim=args.feature_dim)
    print(json.dumps(record))
    return 0


if __name__ == "__main__":
    sys.exit(main())
