"""CPU-runnable serving benchmark — sustained QPS + latency percentiles.

Offers a FIXED request rate at the engine (a paced scheduler thread
submits; completion callbacks stamp per-request latency) and reports
what the engine actually sustained: achieved QPS, p50/p99/max latency,
rejections, fill ratio, and the retrace bound.  Offered-load (rather
than closed-loop) measurement is what serving SLOs are written against:
a closed loop self-throttles to the server's speed and hides queueing
delay entirely.

Runs anywhere — the model is tiny and ``JAX_PLATFORMS=cpu`` suffices —
which is the point: ``bench.py`` invokes this in a CPU-pinned
subprocess, so BENCH rounds report a real serving number even when the
device backend probe times out (the all-null BENCH failure mode).

CLI: ``python -m dist_keras_tpu.serving.bench [--qps N] [--seconds S]``
prints one JSON record on the last stdout line (the bench driver
contract).  ``--decode`` switches to the decode-serving measurement
(paced open-loop generation requests against a
:class:`~.decode.DecodeEngine`): tokens/sec, time-to-first-token
p50/p99, and KV-page occupancy.
"""

from __future__ import annotations

import argparse
import json
import sys
import threading
import time

import numpy as np


def _percentile(xs, q):
    return float(np.percentile(np.asarray(xs, dtype=np.float64), q))


def run_serving_benchmark(offered_qps=400.0, duration_s=4.0,
                          feature_dim=32, hidden=(64,), num_classes=10,
                          batch_ladder=(1, 8, 32, 64), replicas=1,
                          max_latency_s=0.005, max_queue=4096,
                          warmup=True, seed=0):
    """Run one offered-load measurement; -> JSON-ready record dict."""
    # imports deferred so `--help` and a wedged backend never touch jax
    from dist_keras_tpu.models import mnist_mlp
    from dist_keras_tpu.serving.engine import Overloaded, ServingEngine

    model = mnist_mlp(hidden=tuple(hidden), input_dim=int(feature_dim),
                      num_classes=int(num_classes))
    engine = ServingEngine(model, replicas=replicas,
                           batch_ladder=batch_ladder,
                           max_latency_s=max_latency_s,
                           max_queue=max_queue)
    rng = np.random.default_rng(seed)
    rows = rng.normal(size=(256, int(feature_dim))).astype(np.float32)

    if warmup:
        # pre-compile every rung so the measurement window holds zero
        # compiles (a production engine warms the ladder at deploy time
        # the same way)
        for rung in engine.batch_ladder:
            engine.predict(rows[:rung], timeout_s=120)

    latencies = []
    lat_lock = threading.Lock()
    rejected = [0]
    submitted = [0]

    def _submit_one(i):
        t0 = time.monotonic()

        def _done(fut):
            if fut.exception() is None:
                with lat_lock:
                    latencies.append(time.monotonic() - t0)
        try:
            fut = engine.submit(rows[i % len(rows)])
        except Overloaded:
            rejected[0] += 1
        else:
            submitted[0] += 1
            fut.add_done_callback(_done)

    interval = 1.0 / float(offered_qps)
    t_start = time.monotonic()
    next_t = t_start
    i = 0
    while True:
        now = time.monotonic()
        if now - t_start >= duration_s:
            break
        if now < next_t:
            time.sleep(min(next_t - now, 0.005))
            continue
        # catch up without sleeping when the scheduler fell behind —
        # the offered load stays the load, not "what we got around to"
        _submit_one(i)
        i += 1
        next_t += interval
    # deliver the tail before reading the clocks
    engine.drain(timeout_s=60)
    wall = time.monotonic() - t_start
    stats = engine.stats()
    record = {
        "offered_qps": float(offered_qps),
        "duration_s": round(wall, 3),
        "submitted": submitted[0],
        "completed": len(latencies),
        "rejected": rejected[0],
        "achieved_qps": round(len(latencies) / wall, 1) if wall else None,
        "p50_ms": (round(_percentile(latencies, 50) * 1e3, 3)
                   if latencies else None),
        "p99_ms": (round(_percentile(latencies, 99) * 1e3, 3)
                   if latencies else None),
        "max_ms": (round(max(latencies) * 1e3, 3) if latencies else None),
        "mean_fill_ratio": (round(stats["fill_ratio"]["mean"], 4)
                            if stats["fill_ratio"]["mean"] is not None
                            else None),
        "batches": stats["batches"],
        "replicas": stats["replicas"],
        "batch_ladder": stats["batch_ladder"],
        "retrace_count": stats["retrace_count"],
        "retrace_bound": stats["retrace_bound"],
        "errors": stats["errors"],
    }
    return record


def run_decode_benchmark(offered_rps=40.0, duration_s=4.0, vocab=64,
                         seq_len=64, d_model=32, n_heads=2, n_layers=2,
                         prefill_ladder=(8, 16), decode_ladder=(1, 4, 8),
                         page_size=8, max_new=12, replicas=1,
                         max_queue=4096, warmup=True, seed=0):
    """One paced open-loop decode-serving measurement; -> JSON-ready
    record: tokens/sec sustained, TTFT p50/p99 (the ``generate_ttft``
    SLO's distribution), sequence latency p50/p99, KV-page occupancy
    (live + peak), rejections by kind, and the prefill+decode retrace
    bound.  Offered-load for the same reason as the predict bench: a
    closed loop would self-throttle to the engine's speed and hide the
    admission queue entirely."""
    from dist_keras_tpu.models.transformer import (
        Transformer,
        transformer_config,
    )
    from dist_keras_tpu.serving.decode import DecodeEngine
    from dist_keras_tpu.serving.engine import Overloaded

    cfg = transformer_config(input_dim=int(vocab), seq_len=int(seq_len),
                             d_model=int(d_model), n_heads=int(n_heads),
                             n_layers=int(n_layers),
                             n_classes=int(vocab))
    engine = DecodeEngine(Transformer(cfg), replicas=int(replicas),
                          prefill_ladder=tuple(prefill_ladder),
                          decode_ladder=tuple(decode_ladder),
                          page_size=int(page_size),
                          max_queue=int(max_queue))
    rng = np.random.default_rng(seed)
    prompts = [rng.integers(0, vocab, size=n).tolist()
               for n in rng.integers(2, prefill_ladder[-1] + 1,
                                     size=64)]

    if warmup:
        # warm every prefill rung and the decode ladder's small rungs
        # so the measurement window holds zero compiles
        for rung in engine.prefill_ladder:
            engine.generate(list(range(1, min(rung, vocab - 1) + 1))
                            [:rung], max_new_tokens=2, timeout_s=300)

    ttfts = []
    seq_lats = []
    tokens_done = [0]
    lat_lock = threading.Lock()
    rejected = {"kv_exhausted": 0, "queue_full": 0}
    submitted = [0]

    def _submit_one(i):
        t0 = time.monotonic()

        def _done(fut):
            if fut.exception() is None:
                doc = fut.result()  # dklint: ignore[unbounded-wait] done-callbacks run only after resolution
                with lat_lock:
                    seq_lats.append(time.monotonic() - t0)
                    if doc["ttft_s"] is not None:
                        ttfts.append(doc["ttft_s"])
                    tokens_done[0] += len(doc["generated"])
        try:
            gen = engine.submit_generate(prompts[i % len(prompts)],
                                         max_new_tokens=max_new)
        except Overloaded as e:
            rejected[e.reason] = rejected.get(e.reason, 0) + 1
        else:
            submitted[0] += 1
            gen.future.add_done_callback(_done)

    interval = 1.0 / float(offered_rps)
    t_start = time.monotonic()
    next_t = t_start
    occupancy_peak = 0.0
    i = 0
    while True:
        now = time.monotonic()
        if now - t_start >= duration_s:
            break
        if now < next_t:
            time.sleep(min(next_t - now, 0.005))
            continue
        _submit_one(i)
        if i % 8 == 0:
            occupancy_peak = max(occupancy_peak,
                                 engine.kv_stats()["occupancy"])
        i += 1
        next_t += interval
    engine.drain(timeout_s=120)
    wall = time.monotonic() - t_start
    stats = engine.stats()
    kv = stats["kv"]
    return {
        "mode": "decode",
        "offered_rps": float(offered_rps),
        "duration_s": round(wall, 3),
        "submitted": submitted[0],
        "completed": len(seq_lats),
        "rejected": int(sum(rejected.values())),
        "rejected_kv": rejected.get("kv_exhausted", 0),
        "tokens": tokens_done[0],
        "tokens_per_s": (round(tokens_done[0] / wall, 1)
                         if wall else None),
        "ttft_p50_ms": (round(_percentile(ttfts, 50) * 1e3, 3)
                        if ttfts else None),
        "ttft_p99_ms": (round(_percentile(ttfts, 99) * 1e3, 3)
                        if ttfts else None),
        "seq_p50_ms": (round(_percentile(seq_lats, 50) * 1e3, 3)
                       if seq_lats else None),
        "seq_p99_ms": (round(_percentile(seq_lats, 99) * 1e3, 3)
                       if seq_lats else None),
        "kv_occupancy_peak": round(max(
            occupancy_peak, kv["peak_pages"] / kv["num_pages"]
            if kv["num_pages"] else 0.0), 4),
        "kv_pages": kv["num_pages"],
        "replicas": stats["replicas"],
        "prefill_ladder": stats["prefill_ladder"],
        "decode_ladder": stats["decode_ladder"],
        "retrace_count": stats["retrace_count"],
        "retrace_bound": stats["retrace_bound"],
        "errors": stats["errors"],
    }


def run_survivability_benchmark(offered_rps=60.0, duration_s=4.0,
                                vocab=64, seq_len=64, d_model=32,
                                n_heads=2, n_layers=2,
                                prefill_ladder=(8, 16),
                                decode_ladder=(1, 4, 8), page_size=8,
                                max_new=12, replicas=2,
                                max_queue=4096, batch_every=3,
                                seed=0):
    """Decode survivability under pressure: paced open-loop generation
    against a multi-replica engine at roughly 2x the single-replica
    comfortable rate (every ``batch_every``-th request
    ``priority="batch"``), with replica 0 KILLED a third of the way
    in.  -> JSON-ready record: the recovered-sequence latency tax
    (recovered p50 vs undisturbed p50 — replay is not free, and this
    row says what it costs), interactive sequence-latency p99 across
    the kill, the brownout shed rate for batch work, and the
    survivability ledger (quarantines, recoveries, zero errors, zero
    leaked pages)."""
    from dist_keras_tpu.models.transformer import (
        Transformer,
        transformer_config,
    )
    from dist_keras_tpu.serving.decode import DecodeEngine
    from dist_keras_tpu.serving.engine import Overloaded

    cfg = transformer_config(input_dim=int(vocab), seq_len=int(seq_len),
                             d_model=int(d_model), n_heads=int(n_heads),
                             n_layers=int(n_layers),
                             n_classes=int(vocab))
    engine = DecodeEngine(Transformer(cfg),
                          replicas=max(2, int(replicas)),
                          prefill_ladder=tuple(prefill_ladder),
                          decode_ladder=tuple(decode_ladder),
                          page_size=int(page_size),
                          max_queue=int(max_queue))
    rng = np.random.default_rng(seed)
    prompts = [rng.integers(0, vocab, size=n).tolist()
               for n in rng.integers(2, prefill_ladder[-1] + 1,
                                     size=64)]
    for rung in engine.prefill_ladder:  # zero compiles in the window
        engine.generate(list(range(1, min(rung, vocab - 1) + 1))
                        [:rung], max_new_tokens=2, timeout_s=300)

    lat_lock = threading.Lock()
    undisturbed, recovered = [], []
    interactive = []
    rejected = {"kv_exhausted": 0, "queue_full": 0}
    shed = [0]
    batch_offered = [0]
    submitted = [0]

    def _submit_one(i):
        t0 = time.monotonic()
        prio = "batch" if i % int(batch_every) == 0 else "interactive"

        def _done(fut):
            if fut.exception() is None:
                doc = fut.result()  # dklint: ignore[unbounded-wait] done-callbacks run only after resolution
                lat = time.monotonic() - t0
                with lat_lock:
                    (recovered if doc.get("recoveries")
                     else undisturbed).append(lat)
                    if prio == "interactive":
                        interactive.append(lat)
        if prio == "batch":
            batch_offered[0] += 1
        try:
            gen = engine.submit_generate(prompts[i % len(prompts)],
                                         max_new_tokens=max_new,
                                         priority=prio)
        except Overloaded as e:
            if e.reason == "shed_batch":
                shed[0] += 1
            else:
                rejected[e.reason] = rejected.get(e.reason, 0) + 1
        else:
            submitted[0] += 1
            gen.future.add_done_callback(_done)

    # dklint: thread-root=bench.kill_timer
    killer = threading.Timer(float(duration_s) / 3.0,
                             lambda: engine.kill_replica(0))
    killer.daemon = True
    killer.start()
    interval = 1.0 / float(offered_rps)
    t_start = time.monotonic()
    next_t = t_start
    i = 0
    while True:
        now = time.monotonic()
        if now - t_start >= duration_s:
            break
        if now < next_t:
            time.sleep(min(next_t - now, 0.005))
            continue
        _submit_one(i)
        i += 1
        next_t += interval
    killer.cancel()
    engine.drain(timeout_s=120)
    wall = time.monotonic() - t_start
    stats = engine.stats()
    leaked = engine.self_check()
    und_p50 = (_percentile(undisturbed, 50) * 1e3
               if undisturbed else None)
    rec_p50 = (_percentile(recovered, 50) * 1e3
               if recovered else None)
    return {
        "mode": "decode_survivability",
        "offered_rps": float(offered_rps),
        "duration_s": round(wall, 3),
        "submitted": submitted[0],
        "completed": len(undisturbed) + len(recovered),
        "recovered": len(recovered),
        "quarantines": stats["quarantines"],
        "errors": stats["errors"],
        "rejected": int(sum(rejected.values())),
        "shed": shed[0],
        "shed_rate": (round(shed[0] / batch_offered[0], 4)
                      if batch_offered[0] else None),
        "undisturbed_p50_ms": (round(und_p50, 3)
                               if und_p50 is not None else None),
        "recovered_p50_ms": (round(rec_p50, 3)
                             if rec_p50 is not None else None),
        "recovery_tax": (round(rec_p50 / und_p50, 3)
                         if rec_p50 is not None and und_p50
                         else None),
        "interactive_p99_ms": (
            round(_percentile(interactive, 99) * 1e3, 3)
            if interactive else None),
        "kv_leaked_pages": leaked + stats["kv_leaked"],
        "replicas": stats["replicas"],
        "replicas_dead": stats["replicas_dead"],
    }


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--qps", type=float, default=400.0)
    ap.add_argument("--seconds", type=float, default=4.0)
    ap.add_argument("--replicas", type=int, default=1)
    ap.add_argument("--feature-dim", type=int, default=32)
    ap.add_argument("--decode", action="store_true",
                    help="measure decode serving (tokens/sec + TTFT) "
                         "instead of fixed-shape predict")
    ap.add_argument("--survivability", action="store_true",
                    help="measure decode survivability: replica kill "
                         "mid-load, recovery latency tax, brownout "
                         "shed rate")
    ap.add_argument("--rps", type=float, default=40.0,
                    help="offered generation requests/sec (--decode)")
    ap.add_argument("--max-new", type=int, default=12,
                    help="tokens generated per request (--decode)")
    args = ap.parse_args(argv)
    if args.survivability:
        record = run_survivability_benchmark(
            offered_rps=args.rps if args.rps != 40.0 else 60.0,
            duration_s=args.seconds,
            max_new=args.max_new)
    elif args.decode:
        record = run_decode_benchmark(offered_rps=args.rps,
                                      duration_s=args.seconds,
                                      replicas=args.replicas,
                                      max_new=args.max_new)
    else:
        record = run_serving_benchmark(offered_qps=args.qps,
                                       duration_s=args.seconds,
                                       replicas=args.replicas,
                                       feature_dim=args.feature_dim)
    print(json.dumps(record))
    return 0


if __name__ == "__main__":
    sys.exit(main())
