"""Online serving engine — dynamic batching over fixed-shape replicas.

The reference's only online path is the Kafka/Spark-Streaming notebook
(SURVEY.md §2.4): a pull-based micro-batcher with no concurrency story.
This engine is the push-based counterpart production TPU serving needs
(the continuous-batching line of work in PAPERS.md): concurrent callers
``submit()`` individual feature rows, a dynamic batcher packs them into
device batches, and N model replicas (one per device, or per device
group) execute them in parallel.

Design points:

- **Fixed-shape batch ladder.** Requests are packed into the smallest
  rung of ``batch_ladder`` that fits (padded, pad stripped from the
  output — the same ``pack_rows`` helper the streaming predictor uses),
  so a HANDFUL of jitted executables serves all traffic: the number of
  distinct batch shapes ever dispatched is bounded by the ladder size,
  and ragged arrival patterns can never trigger unbounded retraces.
  (Executable count is shapes x replica devices — each device compiles
  its own copy of each rung, which is inherent, bounded, and counted in
  :meth:`ServingEngine.stats`.)
- **Latency-bounded flushes.** A partial batch is flushed after
  ``max_latency_s`` even when the rung is not full, so a trickle of
  traffic still gets timely answers; under load the batcher fills the
  largest rung and the fill ratio approaches 1.
- **Admission control / backpressure.** Admission is BOUNDED on the
  count of admitted-but-unresolved requests (``max_queue`` — queued
  AND batched-in-flight; bounding only the raw queue would let the
  batcher launder unlimited work into replica inboxes); past the bound
  a submit rejects with a typed :class:`Overloaded`, so callers (the
  HTTP front end answers 503) shed load instead of growing an
  unbounded latency tail.  The same typed rejection covers a
  draining/stopped engine, so "rejected, not lost" holds at every
  lifecycle stage.
- **Hot reload.** :meth:`set_params` atomically swaps each replica's
  parameters BETWEEN batches (a replica reads its params reference once
  per batch; a Python reference assignment is atomic under the GIL), so
  a checkpoint promotion rolls into serving with zero dropped in-flight
  requests — see ``serving/reload.py`` for the Checkpointer watcher.
- **Graceful drain.** :meth:`drain` stops admission (typed rejection),
  flushes every pending request immediately (the latency bound no
  longer applies), waits for all in-flight batches to deliver, then
  stops the worker threads.  Nothing admitted is ever dropped.
- **Typed errors, never hangs.** A failing predict (including the
  ``"serve.predict"`` fault point) sets the EXCEPTION on every future
  in that batch — waiters get the error, not a hang.  The
  ``"serve.enqueue"`` fault point covers admission the same way.

Observability: every seam emits — ``serve_enqueue``,
``serve_batch_flush`` (with fill ratio), ``serve_predict`` (with
duration), ``serve_reload``, ``serve_drain`` — and the
``serve.*`` metrics ride the registry snapshots.  With tracing on,
``submit`` captures the caller's span context into the request, and the
batcher/replica threads stamp ``serve.queue_wait`` / ``serve.batch`` /
``serve.exec`` spans into that request's trace — one request is one
connected trace across the thread handoff.  All of it is the usual
zero-cost no-op when ``DK_OBS_DIR`` is unset.
"""

from __future__ import annotations

import collections
import queue
import threading
import time
from concurrent.futures import Future

import numpy as np

import jax
import jax.numpy as jnp

from dist_keras_tpu.data.streaming import pack_rows
from dist_keras_tpu.observability import events, metrics, perf, spans
from dist_keras_tpu.resilience.faults import fault_point
from dist_keras_tpu.utils.serialization import (
    deserialize_model,
    serialize_model,
)


class Overloaded(RuntimeError):
    """Typed admission rejection — queue full, draining, or stopped.

    ``reason`` is one of ``"queue_full"`` / ``"draining"`` /
    ``"stopped"`` — or ``"kv_exhausted"`` from the decode engine, whose
    door additionally reserves worst-case KV pages per sequence;
    ``pending`` / ``capacity`` let a front end answer 503 with real
    numbers.  Requests already admitted are unaffected: rejection is
    strictly at the door, never a drop.
    """

    def __init__(self, reason, pending=None, capacity=None):
        self.reason = str(reason)
        self.pending = pending
        self.capacity = capacity
        super().__init__(
            f"serving engine rejected the request ({self.reason}"
            + (f"; pending={pending}/{capacity}" if pending is not None
               else "") + ")")


# t: monotonic admission instant (queue-wait math); tw: wall-clock twin
# (retro span timestamps); ctx: the submitter's captured trace context —
# the batcher/replica threads stamp their stages into THAT request's
# trace, so one request stays one connected trace across the handoff
_Request = collections.namedtuple("_Request",
                                  ("x", "future", "t", "tw", "ctx"))


class _Replica:
    """One model replica pinned to one device: its params live there and
    its worker thread runs the shared jitted apply against them.  The
    ``params`` attribute is the hot-reload swap point (reference
    assignment; read once per batch)."""

    def __init__(self, index, device, params):
        self.index = index
        self.device = device
        self.params = (jax.device_put(params, device)
                       if device is not None else params)
        self.inbox = queue.Queue()
        self.batches = 0

    def put_params(self, params):
        self.params = (jax.device_put(params, self.device)
                       if self.device is not None else params)


class ServingEngine:
    """Owns the request queue, the dynamic batcher, and N replicas.

    Args:
      keras_model: any model the serialization layer round-trips (native
        Sequential / Transformer / Keras-3 JSON) — same contract as
        ``data.predictors.Predictor``.
      replicas: number of model replicas.  Default: one per visible
        device.  Replicas beyond the device count share devices
        round-robin.
      batch_ladder: ascending fixed batch shapes; the largest rung is
        the max batch per dispatch.
      max_latency_s: flush bound for partial batches.
      max_queue: admission bound on admitted-but-unresolved requests
        (queued + batched in flight).
      devices: explicit device list (default ``jax.devices()``).
      feature_shape: expected per-row shape, enforced AT THE DOOR
        (``ValueError``, the front end's 400).  Default None locks to
        the first admitted row's shape — without this check a public
        endpoint feeding varying-width rows would compile one
        executable per width (unbounded retraces) and a ragged pair
        sharing a batch would fail an innocent neighbour's request.
    """

    def __init__(self, keras_model, replicas=None,
                 batch_ladder=(1, 8, 32, 128), max_latency_s=0.01,
                 max_queue=1024, devices=None, feature_shape=None):
        self.serialized = serialize_model(keras_model)
        ladder = sorted(set(int(b) for b in batch_ladder))
        if not ladder or ladder[0] < 1:
            raise ValueError(f"batch_ladder {batch_ladder!r} must hold "
                             "positive ints")
        self.batch_ladder = tuple(ladder)
        self.max_batch = ladder[-1]
        self.max_latency_s = float(max_latency_s)
        self.max_queue = int(max_queue)
        if self.max_queue < 1:
            raise ValueError(f"max_queue={max_queue} must be >= 1")

        self.feature_shape = (None if feature_shape is None
                              else tuple(feature_shape))
        model = deserialize_model(self.serialized)
        apply_fn = model.apply
        self._host_params = model.params
        # one jitted program shared by every replica; the jit cache keys
        # on (shape, placement), so executables = rungs x devices — both
        # factors bounded by construction
        self._apply = jax.jit(lambda p, x: apply_fn(p, x))

        if devices is None:
            devices = jax.devices()
        n = int(replicas) if replicas is not None else len(devices)
        if n < 1:
            raise ValueError(f"replicas={replicas} must be >= 1")
        # kept for resize(): grown replicas share the same device list
        # round-robin, exactly like construction
        self._devices = list(devices) if devices else []
        self._next_replica_index = n
        self._replicas = [
            _Replica(i, devices[i % len(devices)] if devices else None,
                     self._host_params)
            for i in range(n)]

        self._cond = threading.Condition()
        self._pending = collections.deque()
        self._inflight = 0          # batches dispatched, not yet delivered
        self._outstanding = 0       # requests admitted, not yet resolved
        self._draining = False
        self._stopped = False
        self._drained = threading.Event()
        self._rr = 0                # round-robin tiebreaker
        self._shapes = set()        # rungs actually dispatched (retrace
        #                             bound: len(_shapes) <= len(ladder))
        self.reload_count = 0

        # ENGINE-LOCAL instruments (several engines can coexist in one
        # process — tests, blue/green rollouts — and drain counts must
        # be per-engine truths) ...
        self._m_predict = metrics.Histogram("serve.predict_s")
        self._m_fill = metrics.Histogram("serve.fill_ratio")
        self._m_wait = metrics.Histogram("serve.queue_wait_s")
        self._n_enqueued = 0
        self._n_completed = 0
        self._n_rejected = 0
        self._n_errors = 0
        self._n_batches = 0
        # ... plus the process-wide registry counters every subsystem
        # shares (these ride the epoch/periodic snapshots and aggregate
        # across engines, which is what a process registry means)
        self._reg_enqueued = metrics.counter("serve.enqueued")
        self._reg_completed = metrics.counter("serve.completed")
        self._reg_rejected = metrics.counter("serve.rejected")
        self._reg_errors = metrics.counter("serve.errors")
        self._reg_predict = metrics.histogram("serve.predict_s")
        # live queue-depth gauge: the watchdog's queue-growth rule and
        # the future router both read this as a time series — updated
        # at admission and at every resolution (last engine wins when
        # two coexist, which matches "the serving load on this host")
        self._reg_pending = metrics.gauge("serve.pending")
        perf.install()  # retrace listener: the ladder bound, verified

        self._replica_threads = [threading.Thread(
            target=self._replica_loop, args=(rep,), daemon=True,
            name=f"dk-serve-replica-{rep.index}")
            for rep in self._replicas]
        self._batcher_thread = threading.Thread(
            target=self._batcher_loop, daemon=True, name="dk-serve-batch")
        for t in self._replica_threads + [self._batcher_thread]:
            t.start()

    # -- admission ------------------------------------------------------
    def submit(self, row):
        """Enqueue one feature row; -> ``concurrent.futures.Future``
        resolving to the prediction row (or raising the predict error).
        Raises :class:`Overloaded` at the door — never drops silently."""
        fault_point("serve.enqueue")
        x = np.asarray(row, dtype=np.float32)
        fut = Future()
        with self._cond:
            if self._draining or self._stopped:
                self._n_rejected += 1
                self._reg_rejected.inc()
                raise Overloaded(
                    "draining" if self._draining else "stopped")
            if self._outstanding >= self.max_queue:
                self._n_rejected += 1
                self._reg_rejected.inc()
                raise Overloaded("queue_full",
                                 pending=self._outstanding,
                                 capacity=self.max_queue)
            # shape check AT THE DOOR (bad request, not backpressure):
            # it protects the retrace bound AND the neighbours a ragged
            # row would otherwise drag down inside a shared batch
            if self.feature_shape is None:
                self.feature_shape = x.shape
            elif x.shape != self.feature_shape:
                raise ValueError(
                    f"row shape {x.shape} does not match this engine's "
                    f"feature shape {self.feature_shape} (locked at "
                    "construction or by the first admitted row)")
            self._pending.append(_Request(x, fut, time.monotonic(),
                                          time.time(), spans.capture()))
            self._outstanding += 1
            self._n_enqueued += 1
            pending = len(self._pending)
            # gauge set INSIDE the lock: set outside, a descheduled
            # updater could overwrite a newer depth with its stale one
            # and the serve.pending series would mask a growing queue
            self._reg_pending.set(self._outstanding)
            self._cond.notify()
        self._reg_enqueued.inc()
        # NOTE: the subsystem's only per-request event — with DK_OBS_DIR
        # on it costs one json line per request; the per-batch
        # serve_batch_flush/serve_predict events carry the load signal
        events.emit("serve_enqueue", pending=pending)
        return fut

    def predict(self, rows, timeout_s=None):
        """Convenience: submit every row, gather results into one
        (n, ...) array.  Re-raises the first per-row error."""
        futs = [self.submit(r) for r in rows]
        return np.stack([f.result(timeout=timeout_s) for f in futs])

    # -- batcher --------------------------------------------------------
    def _take_batch(self):
        """Blocking: -> list of requests to pack (<= max rung), or None
        when the engine stopped with nothing left."""
        with self._cond:
            while not self._pending:
                if self._stopped or self._draining:
                    return None
                # the batcher's idle park: deliberately unbounded —
                # every producer (submit) and both lifecycle exits
                # (drain/_shutdown_threads) notify under this cond,
                # and shutdown re-checks _stopped/_draining above, so
                # the wait ends with work or a lifecycle transition,
                # never needs a wake-poll cadence
                # dklint: ignore[unbounded-wait] idle park; all producers and lifecycle exits notify this cond
                self._cond.wait()
            # at least one request: wait up to the latency bound for a
            # full largest rung — unless draining, which flushes NOW
            deadline = time.monotonic() + self.max_latency_s
            while (len(self._pending) < self.max_batch
                   and not self._draining and not self._stopped):
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._cond.wait(remaining)
            take = [self._pending.popleft()
                    for _ in range(min(len(self._pending),
                                       self.max_batch))]
            if take:
                self._inflight += 1
            self._cond.notify_all()
        return take or self._take_batch()

    def _rung_for(self, n):
        for b in self.batch_ladder:
            if n <= b:
                return b
        return self.max_batch  # n == max_batch by construction

    def _pick_replica(self):
        """Least-loaded by inbox depth, round-robin on ties."""
        depths = [r.inbox.qsize() for r in self._replicas]
        best = min(depths)
        order = range(self._rr, self._rr + len(self._replicas))
        for i in order:
            i %= len(self._replicas)
            if depths[i] == best:
                self._rr = (i + 1) % len(self._replicas)
                return self._replicas[i]
        return self._replicas[0]  # pragma: no cover - unreachable

    def _batcher_loop(self):
        while True:
            take = self._take_batch()
            if take is None:
                # draining: keep flushing until the queue is empty, so
                # every admitted request is delivered before shutdown
                with self._cond:
                    if self._pending:
                        continue
                    if self._stopped or self._draining:
                        break
                    continue  # pragma: no cover - spurious wake
            try:
                rung = self._rung_for(len(take))
                x, n = pack_rows([r.x for r in take], rung)
            # dklint: ignore[broad-except] a ragged batch fails ITS OWN futures typed, never the batcher thread
            except Exception as e:
                # a malformed row (ragged shapes across one batch) must
                # fail ITS OWN requests typed — not kill the batcher
                # thread and wedge the whole engine behind unresolvable
                # futures
                with self._cond:
                    self._n_errors += len(take)
                    self._outstanding -= len(take)
                    self._reg_pending.set(self._outstanding)
                    self._inflight -= 1
                    self._cond.notify_all()
                self._reg_errors.inc(len(take))
                events.emit("serve_batch_error", n=len(take),
                            error=type(e).__name__)
                for r in take:
                    r.future.set_exception(e)
                continue
            now = time.monotonic()
            with self._cond:  # stats() iterates _shapes under the lock
                self._shapes.add((rung,) + x.shape[1:])
            for r in take:
                self._m_wait.observe(now - r.t)
            if events.enabled():
                # retro-stamp each request's queue wait into ITS OWN
                # trace (submit wall clock -> this pop) — the first
                # half of the handler->batcher handoff
                noww = time.time()
                for r in take:
                    spans.span_at("serve.queue_wait", r.ctx, r.tw,
                                  noww)
            self._m_fill.observe(n / rung)
            # the batch itself is one span, parented to the first
            # request's trace (its flush event auto-stamps the same ids)
            with spans.resume(take[0].ctx):
                with spans.span("serve.batch", rung=rung, n=n):
                    events.emit("serve_batch_flush", rung=rung, n=n,
                                fill_ratio=n / rung)
                    # pick + put UNDER the admission lock: resize()
                    # retires replicas under the same lock (truncate,
                    # then sentinel), so a batch can never be dispatched
                    # into an inbox whose replica already saw its
                    # sentinel — inbox.put never blocks (unbounded
                    # queue), so holding _cond across it is cheap
                    with self._cond:
                        self._pick_replica().inbox.put((x, take))

    # -- replicas -------------------------------------------------------
    def _replica_loop(self, rep):
        while True:
            # the replica's idle park: deliberately unbounded — the
            # batcher is the only producer and _shutdown_threads joins
            # it FIRST, then posts the None sentinel below, so this
            # get() always ends with work or the shutdown sentinel
            # dklint: ignore[unbounded-wait] sentinel-terminated park; batcher joined before sentinels by _shutdown_threads
            item = rep.inbox.get()
            if item is None:
                break
            x, reqs = item
            t0 = time.perf_counter()
            tw0 = time.time()
            try:
                fault_point("serve.predict")
                perf.count_dispatch()  # one compiled launch per batch
                xb = jnp.asarray(x)
                if rep.device is not None:
                    xb = jax.device_put(xb, rep.device)
                preds = np.asarray(self._apply(rep.params, xb))
            # dklint: ignore[broad-except] the predict error lands TYPED on every future in the batch
            except Exception as e:
                # typed error to every waiter in the batch — a failed
                # predict must never hang a caller
                with self._cond:
                    self._n_errors += len(reqs)
                    self._outstanding -= len(reqs)
                    self._reg_pending.set(self._outstanding)
                self._reg_errors.inc(len(reqs))
                events.emit("serve_predict_error", replica=rep.index,
                            n=len(reqs), error=type(e).__name__)
                for r in reqs:
                    r.future.set_exception(e)
            else:
                dt = time.perf_counter() - t0
                rep.batches += 1
                with self._cond:
                    self._n_batches += 1
                    self._n_completed += len(reqs)
                    self._outstanding -= len(reqs)
                    self._reg_pending.set(self._outstanding)
                self._reg_completed.inc(len(reqs))
                # exemplar: the replica thread has no open span, so
                # the batch's first request trace is passed explicitly
                # — a scrape's bad predict percentile then links to a
                # retained trace containing this very hop
                ex = ((reqs[0].ctx.trace_id, reqs[0].ctx.span_id)
                      if reqs and reqs[0].ctx is not None else None)
                self._m_predict.observe(dt, exemplar=ex)
                self._reg_predict.observe(dt, exemplar=ex)
                events.emit("serve_predict", replica=rep.index,
                            n=len(reqs), rung=len(x), duration_s=dt)
                if events.enabled():
                    # the in-flight window, stamped into every
                    # request's trace from the REPLICA thread — the
                    # second half of the cross-thread handoff
                    tw1 = time.time()
                    for r in reqs:
                        spans.span_at("serve.exec", r.ctx, tw0, tw1,
                                      replica=rep.index, rung=len(x))
                for r, p in zip(reqs, preds[:len(reqs)]):
                    r.future.set_result(p)
            finally:
                with self._cond:
                    self._inflight -= 1
                    self._cond.notify_all()

    # -- hot reload -----------------------------------------------------
    def set_params(self, state, step=None):
        """Atomically swap every replica's parameters between batches.

        ``state`` is either a bare params pytree or a training-state
        dict holding one under ``"params"`` (what ``Checkpointer``
        snapshots).  In-flight batches finish on the params they
        started with; the next batch a replica picks up sees the new
        ones — zero dropped requests, no lock on the predict path."""
        params = (state["params"]
                  if isinstance(state, dict) and "params" in state
                  else state)
        for rep in self._replicas:
            rep.put_params(params)
        self._host_params = params
        self.reload_count += 1
        metrics.counter("serve.reloads").inc()
        events.emit("serve_reload", step=step,
                    replicas=len(self._replicas))

    # -- elastic replica set --------------------------------------------
    def resize(self, n):
        """Grow or shrink the replica set in place — the autoscaler's
        actuation seam.  Grow: new replicas share the construction
        device list round-robin and start on the CURRENT params.
        Shrink: a retired replica's sentinel is posted under the same
        lock the batcher dispatches under, so it lands strictly AFTER
        any batch already routed there — the retiree delivers its whole
        backlog, then exits (nothing admitted is ever dropped).  -> the
        new replica count.  Raises :class:`Overloaded` on a draining or
        stopped engine (the replica set is frozen once shutdown began).
        """
        n = int(n)
        if n < 1:
            raise ValueError(f"resize({n}): must keep >= 1 replica")
        started = []
        with self._cond:
            if self._stopped or self._draining:
                raise Overloaded(
                    "stopped" if self._stopped else "draining")
            cur = len(self._replicas)
            if n < cur:
                retired = self._replicas[n:]
                del self._replicas[n:]
                self._rr = 0
                for rep in retired:
                    rep.inbox.put(None)
            elif n > cur:
                devs = self._devices
                for _ in range(n - cur):
                    idx = self._next_replica_index
                    self._next_replica_index += 1
                    rep = _Replica(
                        idx, devs[idx % len(devs)] if devs else None,
                        self._host_params)
                    self._replicas.append(rep)
                    t = threading.Thread(
                        target=self._replica_loop, args=(rep,),
                        daemon=True, name=f"dk-serve-replica-{idx}")
                    # the full thread list (retirees included) so
                    # _shutdown_threads joins every thread ever started;
                    # a retiree's thread exits on its sentinel and joins
                    # instantly
                    self._replica_threads.append(t)
                    started.append(t)
        for t in started:  # start outside the lock; inboxes buffer
            t.start()
        return n

    # -- lifecycle ------------------------------------------------------
    def drain(self, timeout_s=None):
        """Graceful shutdown: stop admission (typed rejection), flush
        everything pending immediately, deliver every in-flight batch,
        then stop the workers.  -> dict of delivery counts.  Raises
        ``TimeoutError`` if the backlog outlives ``timeout_s`` (the
        workers keep delivering regardless)."""
        t0 = time.perf_counter()
        with self._cond:
            self._draining = True
            backlog = len(self._pending) + self._inflight
            self._cond.notify_all()
        events.emit("serve_drain_begin", backlog=backlog)
        deadline = (None if timeout_s is None
                    else time.monotonic() + timeout_s)
        with self._cond:
            while self._outstanding:
                remaining = (None if deadline is None
                             else deadline - time.monotonic())
                if remaining is not None and remaining <= 0:
                    raise TimeoutError(
                        f"drain: {self._outstanding} admitted requests "
                        f"unresolved after {timeout_s}s "
                        f"({len(self._pending)} queued, "
                        f"{self._inflight} batches in flight)")
                self._cond.wait(remaining)
        # unconditional + idempotent: a PREVIOUS drain that timed out
        # left _draining set but the workers alive — this call (backlog
        # now clear) must still be able to stop them
        self._shutdown_threads()
        out = {"delivered": self._n_completed,
               "errored": self._n_errors,
               "rejected": self._n_rejected,
               "duration_s": time.perf_counter() - t0}
        events.emit("serve_drain", **out)
        return out

    def _shutdown_threads(self):
        with self._cond:
            first = not self._stopped
            self._stopped = True
            self._cond.notify_all()
        if not first:  # idempotent: a second caller waits, not re-stops
            self._drained.wait(timeout=10)
            return
        # the BATCHER joins FIRST: it may be between popping a batch and
        # dispatching it to a replica inbox — a sentinel enqueued before
        # that dispatch would park the batch behind it forever (replica
        # loops break on the sentinel), orphaning its futures
        if self._batcher_thread is not threading.current_thread():
            self._batcher_thread.join(timeout=10)
        for rep in self._replicas:
            rep.inbox.put(None)
        for t in self._replica_threads:
            if t is not threading.current_thread():
                t.join(timeout=10)
        self._drained.set()

    def close(self, drain=True, timeout_s=None):
        """Stop the engine.  ``drain=True`` (default) delivers the
        backlog first; ``drain=False`` fails pending futures with a
        typed :class:`Overloaded` (still never a silent drop)."""
        if self._stopped:
            return
        if drain:
            self.drain(timeout_s=timeout_s)
            return
        with self._cond:
            self._draining = True
            pending, self._pending = list(self._pending), \
                collections.deque()
            self._outstanding -= len(pending)
            self._reg_pending.set(self._outstanding)
            self._n_rejected += len(pending)
            self._cond.notify_all()
        self._reg_rejected.inc(len(pending))
        for r in pending:
            r.future.set_exception(Overloaded("stopped"))
        self._shutdown_threads()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    @property
    def draining(self):
        return self._draining

    @property
    def running(self):
        return not self._stopped

    def stats(self):
        """JSON-ready engine counters — the ``/metricsz`` payload core."""
        with self._cond:
            pending, inflight = len(self._pending), self._inflight
            outstanding = self._outstanding
            enq, done = self._n_enqueued, self._n_completed
            rej, err, nb = self._n_rejected, self._n_errors, \
                self._n_batches
            shapes = sorted(self._shapes)  # mutated under this lock too
        return {
            "replicas": len(self._replicas),
            "batch_ladder": list(self.batch_ladder),
            "feature_shape": (list(self.feature_shape)
                              if self.feature_shape else None),
            "pending": pending,
            "outstanding": outstanding,
            "inflight_batches": inflight,
            "enqueued": enq,
            "completed": done,
            "rejected": rej,
            "errors": err,
            "batches": nb,
            "reloads": self.reload_count,
            "batches_by_replica": [r.batches for r in self._replicas],
            "shapes_dispatched": [s[0] for s in shapes],
            # the no-retrace bound: distinct batch shapes ever dispatched
            # can never exceed the ladder size (executables on top of
            # this are shapes x replica devices — also fixed)
            "retrace_count": len(shapes),
            "retrace_bound": len(self.batch_ladder),
            "draining": self._draining,
            "fill_ratio": self._m_fill.summary(),
            "predict_s": self._m_predict.summary(),
            "queue_wait_s": self._m_wait.summary(),
        }
