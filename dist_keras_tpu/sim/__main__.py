"""CLI: ``python -m dist_keras_tpu.sim --scenario ps_churn``.

Runs one scenario (or ``--scenario all``) and prints a single JSON
document as the LAST stdout line — the contract ``tools/bench.py``'s
``sim_swarm`` row and ``tools/gates.py --sim-only`` both parse.  Exit
code 0 iff every scenario's invariants held.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

from dist_keras_tpu.sim.runner import run_scenario
from dist_keras_tpu.sim.scenarios import SCENARIOS, ScenarioFailed
from dist_keras_tpu.sim.world import SimTimeLimitExceeded
from dist_keras_tpu.utils import knobs


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="python -m dist_keras_tpu.sim",
        description="deterministic cluster simulator")
    ap.add_argument("--scenario", default="ps_churn",
                    choices=sorted(SCENARIOS) + ["all"],
                    help="scenario script to run (default: ps_churn)")
    ap.add_argument("--seed", type=int, default=None,
                    help="scheduler seed (default: DK_SIM_SEED)")
    ap.add_argument("--hosts", type=int, default=None,
                    help="simulated host/writer count (default: "
                         "DK_SIM_HOSTS for ps_churn, per-scenario "
                         "defaults otherwise)")
    ap.add_argument("--time-limit-s", type=float, default=None,
                    help="simulated-time horizon before a would-be "
                         "hang dies typed (default: "
                         "DK_SIM_TIME_LIMIT_S)")
    args = ap.parse_args(argv)

    names = (sorted(SCENARIOS) if args.scenario == "all"
             else [args.scenario])
    hosts = args.hosts
    if hosts is None and args.scenario == "ps_churn":
        hosts = knobs.get("DK_SIM_HOSTS")
    out = {"scenarios": [], "passed": True}
    rc = 0
    for name in names:
        t0 = time.perf_counter()  # wall clock: measured OUTSIDE the sim
        try:
            result = run_scenario(name, seed=args.seed, hosts=hosts,
                                  time_limit_s=args.time_limit_s)
            result["wall_s"] = round(time.perf_counter() - t0, 3)
        except (ScenarioFailed, SimTimeLimitExceeded) as e:
            result = {"scenario": name, "error": type(e).__name__,
                      "detail": str(e)[:500],
                      "wall_s": round(time.perf_counter() - t0, 3)}
            out["passed"] = False
            rc = 1
        out["scenarios"].append(result)
        print(f"[sim] {name}: "
              + ("FAILED " + result.get("error", "")
                 if "error" in result else
                 f"ok (sim {result['sim_elapsed_s']:.1f}s, "
                 f"wall {result['wall_s']:.1f}s, "
                 f"digest {result['digest'][:12]})"),
              file=sys.stderr)
    print(json.dumps(out))
    return rc


if __name__ == "__main__":
    sys.exit(main())
