"""SimWorld — the deterministic discrete-event clock behind the seam.

The FoundationDB/Jepsen lesson, sized for this repo: the expensive part
of distributed-systems confidence is not the assertions, it is the
*scheduler* — who runs when, which sleeps interleave, when the
partition heals.  :class:`SimWorld` replaces the process's clocks
through the :mod:`~dist_keras_tpu.resilience.world` seam and makes the
scheduler a seeded PRNG: every ``sleep`` advances simulated time
instantly, every timer fires in deterministic order, and the whole
run's observable history lands in a trace whose SHA-256 digest must be
bit-identical across replays of the same seed.

What determinism costs (and why it is cheap here):

- **Single-threaded by construction.**  The sim never spawns threads;
  concurrency is modeled as the scenario's seeded interleaving of
  per-host actions.  Real threads in real mode still hit
  :class:`~dist_keras_tpu.resilience.world.RealWorld` — the global
  world slot only changes inside a scenario.
- **No wall-clock reads, ever.**  The sim epoch is a fixed constant
  (:data:`SIM_EPOCH`), so heartbeat stamps, lease expiries and chaos
  horizons are identical numbers run over run.  ``time`` and
  ``monotonic`` move in lockstep — staleness judgments compare stamps
  to the same clock that wrote them.
- **A hard time limit instead of a hang.**  A scenario that would wait
  forever (a deadlock, an unhealed partition) trips
  :class:`SimTimeLimitExceeded` the moment simulated time crosses the
  horizon — the "never a hang" acceptance is structural, not hoped-for.
"""

from __future__ import annotations

import hashlib
import heapq
import itertools
import random

from dist_keras_tpu.resilience.world import World

# A fixed, recognizably-fake epoch (2001-09-09T01:46:40Z).  Large so
# mtime stamps written with sim time look like plausible file times to
# code that subtracts them, constant so replays are bit-identical.
SIM_EPOCH = 1_000_000_000.0


class SimTimeLimitExceeded(RuntimeError):
    """Simulated time crossed the scenario's horizon — the typed form
    of "this would have hung"."""

    def __init__(self, limit_s, now):
        self.limit_s = float(limit_s)
        self.now = float(now)
        super().__init__(
            f"simulated time {now - SIM_EPOCH:.3f}s crossed the "
            f"scenario horizon {limit_s:.3f}s — a real cluster would "
            "still be waiting (deadlock or unhealed fault)")


class SimWorld(World):
    """Deterministic simulated clock + seeded scheduler PRNG + trace.

    ``sleep`` advances :meth:`time`/:meth:`monotonic` instantly, firing
    any timers scheduled inside the skipped span in (time, insertion)
    order.  ``rng`` is THE scenario randomness — scenarios draw every
    choice (which host runs, who dies, when the partition heals) from
    it so one seed pins the entire interleaving.

    ``record(kind, **fields)`` appends to the trace; :meth:`digest`
    hashes it.  Only deterministic values may be recorded — the digest
    equality test across replays is the enforcement.
    """

    def __init__(self, seed=0, time_limit_s=None, start=SIM_EPOCH):
        self.seed = int(seed)
        self.rng = random.Random(self.seed)
        self._start = float(start)
        self._now = float(start)
        self.time_limit_s = (None if time_limit_s is None
                             else float(time_limit_s))
        self._timers = []            # heap of (at, seq, fn)
        self._seq = itertools.count()
        self.trace = []
        self.sleeps = 0              # how many sleeps were absorbed

    # -- the World interface -------------------------------------------
    def time(self):
        return self._now

    def monotonic(self):
        return self._now

    def sleep(self, seconds):
        self.sleeps += 1
        self.advance(seconds)

    # -- simulated-time control ----------------------------------------
    @property
    def elapsed(self):
        """Simulated seconds since the scenario began."""
        return self._now - self._start

    def _check_limit(self):
        if (self.time_limit_s is not None
                and self.elapsed > self.time_limit_s):
            raise SimTimeLimitExceeded(self.time_limit_s, self._now)

    def advance(self, seconds):
        """Jump the clock forward, firing due timers in order.  Timer
        callbacks run AT their scheduled instant (``monotonic()``
        inside one reads the timer's time, not the jump target)."""
        target = self._now + max(0.0, float(seconds))
        while self._timers and self._timers[0][0] <= target:
            at, _, fn = heapq.heappop(self._timers)
            self._now = max(self._now, at)
            self._check_limit()
            fn()
        self._now = target
        self._check_limit()

    def call_later(self, delay_s, fn):
        """Schedule ``fn()`` at now + delay_s (fires inside a future
        :meth:`advance`/:meth:`sleep` that crosses it)."""
        return self.call_at(self._now + max(0.0, float(delay_s)), fn)

    def call_at(self, at, fn):
        heapq.heappush(self._timers, (float(at), next(self._seq), fn))

    # -- the replay trace ----------------------------------------------
    def record(self, __kind, **fields):
        """Append one trace entry stamped with the sim clock.  Fields
        are sorted so dict construction order can never leak into the
        digest.  (The positional name is mangled so ``kind=`` stays
        usable as a field key.)"""
        self.trace.append((round(self.elapsed, 9), str(__kind),
                           tuple(sorted(fields.items()))))

    def digest(self):
        """SHA-256 over the full trace — the bit-identity witness."""
        h = hashlib.sha256()
        for entry in self.trace:
            h.update(repr(entry).encode("utf-8"))
        return h.hexdigest()
