"""Seeded chaos scenarios over the simulated cluster.

Each scenario is a function ``(world, **knobs) -> result dict`` run
under an installed :class:`~dist_keras_tpu.sim.world.SimWorld` — real
runtime components (the in-process PS swarm, ``supervise``,
``launch.Job``'s relaunch waves, the remote checkpoint store) driven by
the world's seeded PRNG, with every observable action appended to the
world's trace.  Two runs with the same seed must produce bit-identical
trace digests; that equality is the replay contract the test suite and
the CI gate enforce.

The scenarios:

- ``ps_churn`` — the flagship: a thousand-worker PS swarm on the
  quadratic model, with >10% of hosts killed (leases reaped) and
  rejoined, plus one partition-then-heal window.  Converges past the
  0.80 accuracy floor; every fault is typed or absorbed.
- ``partition_heal`` — a focused partition window over a smaller
  swarm: retries absorb what the heal reaches, the rest die typed
  (``PSUnavailable``), nobody hangs.
- ``preemption_storm`` — coordinated preemptions: each host runs
  under ``supervise`` with a seeded number of :class:`Preempted`
  strikes; budgets and backoffs tick on the sim clock; over-budget
  hosts die typed (``CrashLoop``).
- ``relaunch_waves`` — ``launch.Job.supervise_run`` against simulated
  hosts (the ``runner`` seam + sim-time heartbeat stamps): a transient
  host death triggers a whole-pod wave, a repeat offender is dropped
  by an elastic resize, and an all-rc-0 pod ends supervision.
- ``gc_race`` — many writers mirroring differential checkpoints into
  one in-memory store interleaved with ``prune_remote``: after every
  prune, every surviving ``COMPLETE`` step is fully fetchable.
- ``router_failover`` — the serving router's ``BackendPool`` under a
  load spike: a backend killed mid-spike is evicted within the stale
  window (connect-failure + heartbeat evidence), re-admitted after
  healing, zero silent drops and zero placements on an evicted host.
- ``decode_replica_churn`` — sequence-level decode survivability:
  modeled replicas with real paged-KV allocators under two kill/heal
  cycles; in-flight sequences on a killed replica re-admit onto
  survivors via teacher-forced replay — zero lost sequences, every
  stream bit-identical to the undisturbed oracle, every cache
  balanced at the end.
- ``slo_burn`` — the SLO plane's multi-window burn-rate math on sim
  time: a seeded mid-run error window must page inside the fault,
  escalate to the fast class while errors flow, and clear exactly
  once as the trailing hour dilutes.

Scenario outcomes are *asserted* here (a violated invariant raises
:class:`ScenarioFailed`), so a scenario that returns IS its own green
verdict — the CLI and the gate only relay it.
"""

from __future__ import annotations

import hashlib
import itertools
import json
import os
import shutil

import numpy as np

from dist_keras_tpu.observability import metrics as _metrics
from dist_keras_tpu.ps.center import StaleCommit
from dist_keras_tpu.ps.client import PSUnavailable
from dist_keras_tpu.ps.inproc import InProcPSClient, InProcPSServer
from dist_keras_tpu.resilience import preemption
from dist_keras_tpu.resilience import store as _store
from dist_keras_tpu.resilience.supervisor import CrashLoop, supervise


class ScenarioFailed(AssertionError):
    """A scenario invariant did not hold — the sim's typed red verdict."""


def _require(cond, what):
    if not cond:
        raise ScenarioFailed(what)


# ---------------------------------------------------------------------
# the PS swarm engine (ps_churn / partition_heal share it)
# ---------------------------------------------------------------------

def _ps_swarm(world, hosts, steps_per_host, *, kill_frac=0.0,
              partition_at=None, partition_s=2.0, tick_s=0.01,
              dim=8, lr=1.0, staleness_cap=None):
    """Run ``hosts`` asynchronous workers against one in-process
    center variable on the quadratic model ``f(w)=0.5||w-w*||^2``
    (whose exact gradient step makes convergence a pure function of
    the DynSGD staleness algebra, not of data).  The world's PRNG
    owns the interleaving: each turn one runnable host advances one
    phase (join -> commit -> pull -> commit -> ...), so staleness
    emerges from the schedule exactly as it does from real racing
    workers.  Kills/reaps/rejoins and the partition window fire at
    scripted sim times.  -> result dict (asserted converged)."""
    rng = world.rng
    nrng = np.random.default_rng(world.seed)
    w_star = nrng.standard_normal(dim).astype(np.float32)
    c0 = np.zeros(dim, np.float32)
    d0 = float(np.linalg.norm(c0 - w_star))
    # the chaos script scales with the run's nominal span so the same
    # scenario shape works at 50 hosts (tests) and 1000 (the gate)
    est_span = hosts * (2 * steps_per_host + 1) * tick_s
    lease_s = max(5.0, 0.25 * est_span)
    server = InProcPSServer(
        {"w": c0.copy()}, window=1, lease_s=lease_s,
        staleness_cap=(50 * hosts if staleness_cap is None
                       else staleness_cap))
    part = {"on": False}
    swarm = []
    for h in range(hosts):
        client = InProcPSClient(
            server, attempts=4, backoff=0.05, jitter=0.1,
            partitioned=lambda: part["on"],
            seed=world.seed * 1_000_003 + h)
        swarm.append({"h": h, "client": client, "wid": None,
                      "version": None, "center": None, "steps": 0,
                      "alive": True, "phase": "join", "faults": 0})

    kill_n = int(round(hosts * kill_frac))
    killed = []         # chosen AT the kill instant, from joined hosts
    killed_wids = set()
    t_kill = 0.08 * est_span
    script = []
    if kill_n:
        script += [(t_kill, "kill"),
                   (t_kill + lease_s + 2.0, "reap"),
                   (t_kill + lease_s + 3.0, "rejoin")]
    if partition_at is not None:
        script += [(partition_at, "part_on"),
                   (partition_at + partition_s, "part_off")]
    script.sort()
    si = 0
    typed_faults = 0
    reaped = []

    active = list(swarm)
    # run until the hosts are done AND the chaos script is spent: a
    # small swarm can finish its steps before the reap/rejoin instants,
    # and skipping those silently would un-test the very churn the
    # scenario exists to exercise (the idle advance below jumps straight
    # to the next scripted instant; at gate scale the loop never idles)
    while active or si < len(script):
        while si < len(script) and script[si][0] <= world.elapsed:
            _, ev = script[si]
            si += 1
            if ev == "kill":
                # victims are drawn from hosts that have JOINED — a
                # never-joined host holds no lease, so killing it
                # proves nothing about reaping (and at small scales a
                # big fraction may not have had a first turn yet)
                joined = [hv["h"] for hv in swarm
                          if hv["wid"] is not None]
                _require(len(joined) >= kill_n,
                         f"only {len(joined)} hosts joined by the "
                         f"kill instant — cannot kill {kill_n}")
                killed = sorted(rng.sample(joined, kill_n))
                killed_wids = {swarm[h]["wid"] for h in killed}
                for h in killed:
                    swarm[h]["alive"] = False
                active = [hv for hv in active if hv["alive"]]
                world.record("kill", hosts=tuple(killed))
            elif ev == "reap":
                reaped = server.reap()
                world.record("reap", lapsed=len(reaped))
            elif ev == "rejoin":
                for h in killed:
                    swarm[h]["alive"] = True
                    swarm[h]["phase"] = "join"
                active = [hv for hv in swarm
                          if hv["alive"]
                          and hv["steps"] < steps_per_host]
                world.record("rejoin", hosts=tuple(killed))
            elif ev == "part_on":
                part["on"] = True
                world.record("partition", on=True)
            else:
                part["on"] = False
                world.record("partition", on=False)
        if not active:
            if si < len(script):  # idle until the next scripted event
                world.advance(max(tick_s,
                                  script[si][0] - world.elapsed))
                continue
            break
        hv = active[rng.randrange(len(active))]
        try:
            if hv["phase"] == "join":
                r = hv["client"].join(wid=hv["wid"], rank=hv["h"])
                hv["wid"] = r["wid"]
                hv["version"], hv["center"] = r["version"], r["center"]
                hv["phase"] = "commit"
                world.record("join", host=hv["h"],
                             version=r["version"],
                             rejoined=bool(r["rejoined"]))
            elif hv["phase"] == "pull":
                r = hv["client"].pull(wid=hv["wid"])
                hv["version"], hv["center"] = r["version"], r["center"]
                hv["phase"] = "commit"
            else:  # commit: the exact quadratic gradient step
                delta = {"w": (lr * (w_star - hv["center"]["w"]))
                         .astype(np.float32)}
                r = hv["client"].commit(hv["wid"], hv["version"],
                                        delta, rank=hv["h"])
                hv["version"], hv["center"] = r["version"], r["center"]
                hv["steps"] += 1
                hv["phase"] = "pull"
                _metrics.counter("sim.host_steps").inc()
                world.record("commit", host=hv["h"],
                             version=r["version"],
                             staleness=int(r["staleness"]),
                             rejoined=bool(r["rejoined"]))
                if hv["steps"] >= steps_per_host:
                    active.remove(hv)
        except StaleCommit as e:
            # typed: the worker's recovery is a fresh pull
            hv["faults"] += 1
            typed_faults += 1
            _metrics.counter("sim.faults").inc()
            world.record("fault", host=hv["h"], kind="StaleCommit",
                         staleness=int(e.staleness))
            hv["phase"] = "pull"
        except PSUnavailable:
            # typed after the retry budget (the absorbed occurrences
            # never surface here — that is the point of the policy)
            hv["faults"] += 1
            typed_faults += 1
            _metrics.counter("sim.faults").inc()
            world.record("fault", host=hv["h"], kind="PSUnavailable")
            hv["phase"] = "pull" if hv["wid"] is not None else "join"
        world.advance(tick_s)

    _require(not part["on"], "partition never healed")
    if killed:
        _require(killed_wids <= {w for w, _ in reaped},
                 "killed hosts' leases were never reaped")
    clock, center = server.center.state()
    accuracy = 1.0 - float(np.linalg.norm(center["w"] - w_star)) / d0
    stats = server.center.stats()
    result = {
        "hosts": hosts,
        "steps_per_host": steps_per_host,
        "commits": clock,
        "accuracy": round(accuracy, 6),
        "typed_faults": typed_faults,
        "killed": len(killed),
        "reaped": len(reaped),
        "lapses": stats["lapsed_total"],
        "sleeps": world.sleeps,
    }
    _require(accuracy >= 0.80,
             f"center accuracy {accuracy:.3f} below the 0.80 floor")
    return result


def ps_churn(world, hosts=None, workdir=None):
    """1000-worker swarm with >=12% of hosts killed/rejoined and one
    partition healed mid-run."""
    hosts = 1000 if hosts is None else int(hosts)
    steps = 3
    est_span = hosts * (2 * steps + 1) * 0.01
    result = _ps_swarm(world, hosts, steps, kill_frac=0.12,
                       partition_at=0.7 * est_span, partition_s=2.0)
    _require(result["killed"] >= max(1, int(0.10 * hosts)),
             "churn scenario must kill >=10% of hosts")
    _require(result["reaped"] >= result["killed"],
             "killed hosts' leases were never reaped")
    return result


def partition_heal(world, hosts=None, workdir=None):
    """Partition the whole swarm mid-run; retries absorb what the heal
    reaches, the rest surface typed — and the run still converges."""
    hosts = 64 if hosts is None else int(hosts)
    steps = 4
    est_span = hosts * (2 * steps + 1) * 0.01
    return _ps_swarm(world, hosts, steps, kill_frac=0.0,
                     partition_at=0.5 * est_span, partition_s=1.5)


# ---------------------------------------------------------------------
# preemption storm (supervise on the sim clock)
# ---------------------------------------------------------------------

def preemption_storm(world, hosts=None, workdir=None):
    """Every host trains under ``supervise``; a seeded number of
    preemption strikes hits each one.  Hosts within the restart budget
    complete; hosts past it die typed (``CrashLoop``).  All budget
    windows and backoff sleeps tick on the sim clock."""
    hosts = 40 if hosts is None else int(hosts)
    rng = world.rng
    max_restarts = 3
    completed = crash_loops = restarts = 0
    for h in range(hosts):
        strikes = rng.choice([0, 0, 1, 1, 2, 3, 5])
        state = {"left": strikes}

        def body(attempt, resume_step, state=state, h=h):
            world.advance(0.05)  # one sim "training chunk"
            if state["left"] > 0:
                state["left"] -= 1
                raise preemption.Preempted(15)
            return h

        t0 = world.elapsed
        try:
            supervise(body, max_restarts=max_restarts,
                      budget_window_s=3600.0, backoff=0.2,
                      multiplier=2.0)
            completed += 1
            restarts += strikes
            world.record("supervised", host=h, strikes=strikes,
                         outcome="completed",
                         sim_s=round(world.elapsed - t0, 9))
        except CrashLoop:
            crash_loops += 1
            world.record("supervised", host=h, strikes=strikes,
                         outcome="crash_loop")
        finally:
            preemption.clear()
    _require(completed + crash_loops == hosts,
             "every host must end completed or typed")
    _require(crash_loops == 0 or restarts > 0,
             "storm produced no restarts at all")
    expected_loops = sum(
        1 for e in world.trace
        if e[1] == "supervised"
        and dict(e[2]).get("strikes", 0) > max_restarts)
    _require(crash_loops == expected_loops,
             f"crash loops {crash_loops} != over-budget hosts "
             f"{expected_loops}")
    return {"hosts": hosts, "completed": completed,
            "crash_loops": crash_loops, "restarts": restarts,
            "sleeps": world.sleeps}


# ---------------------------------------------------------------------
# rolling relaunch waves (launch.Job's runner seam)
# ---------------------------------------------------------------------

def relaunch_waves(world, hosts=None, workdir=None):
    """``Job.supervise_run`` against simulated hosts: the ``runner``
    seam replaces ssh/rsync, heartbeat files are stamped with SIM time
    (``os.utime``), and chaos timers kill hosts under the supervisor's
    feet.  A transient death triggers a whole-pod wave; a permanent
    one is dropped by an elastic resize; all-rc-0 ends the run."""
    import re as _re

    from dist_keras_tpu.launch.job import Job

    hosts = 6 if hosts is None else max(4, int(hosts))
    own = workdir is None
    if own:
        import tempfile

        workdir = tempfile.mkdtemp(prefix="dk-sim-waves-")
    try:
        coord = os.path.join(workdir, "coord")
        jobdir = os.path.join(workdir, "job")
        os.makedirs(coord, exist_ok=True)
        os.makedirs(jobdir, exist_ok=True)
        names = [f"sim{r}" for r in range(hosts)]
        alive = {}        # host name -> (session, rank)
        perma_dead = set()

        def _hb_root(session):
            root = coord if not session else os.path.join(
                coord, str(session))
            return os.path.join(root, "hb")

        def _stamp(session, rank):
            hb = _hb_root(session)
            os.makedirs(hb, exist_ok=True)
            path = os.path.join(hb, f"rank_{rank}")
            with open(path, "w") as f:
                f.write(repr(world.time()))
            t = world.time()
            os.utime(path, (t, t))

        def runner(cmd):
            if cmd[0] == "rsync":
                return 0
            host, shell = cmd[1], cmd[2]
            if "nohup" in shell:
                sess_m = _re.search(r"DK_COORD_SESSION=(\d+)", shell)
                rank = int(_re.search(r"DK_COORD_RANK=(\d+)",
                                      shell).group(1))
                session = int(sess_m.group(1)) if sess_m else 0
                world.record("launch", host=host, rank=rank,
                             session=session)
                if host in perma_dead:
                    # launches, instantly dies dark: no beat, no rc —
                    # exactly the repeat-offender evidence shape
                    return 0
                alive[host] = (session, rank)
                _stamp(session, rank)
                return 0
            if "kill -s TERM" in shell:
                alive.pop(host, None)
                return 0
            return 0

        def beat():
            for host, (session, rank) in sorted(alive.items()):
                _stamp(session, rank)
            world.call_later(1.0, beat)

        world.call_later(1.0, beat)

        job = Job("sim-secret", "simwaves", jobdir, hosts=names,
                  coord_dir=coord, runner=runner,
                  trace_id="0" * 32,
                  supervise={"max_restarts": 4,
                             "budget_window_s": 100000.0,
                             "interval_s": 2.0, "grace_s": 4.0,
                             "elastic": True, "min_world": 2})

        transient, permanent = names[2], names[hosts - 2]

        def kill_transient():
            alive.pop(transient, None)
            world.record("host_dark", host=transient, kind="transient")

        def kill_permanent():
            perma_dead.add(permanent)
            alive.pop(permanent, None)
            world.record("host_dark", host=permanent, kind="permanent")

        world.call_later(6.0, kill_transient)
        world.call_later(20.0, kill_permanent)

        done = {"wrote_rc": False}

        def maybe_finish():
            # once the pod is stable at hosts-1 survivors (the elastic
            # resize landed), record rc 0 for every live rank: the
            # supervisor's positive completed evidence
            if (not done["wrote_rc"]
                    and len(alive) == len(job.hosts)
                    and permanent not in job.hosts
                    and len(job.hosts) == hosts - 1):
                for host, (session, rank) in sorted(alive.items()):
                    root = coord if not session else os.path.join(
                        coord, str(session))
                    os.makedirs(os.path.join(root, "rc"),
                                exist_ok=True)
                    with open(os.path.join(root, "rc",
                                           f"rank_{rank}"), "w") as f:
                        f.write("0")
                done["wrote_rc"] = True
                world.record("run_complete_rc", ranks=len(alive))
            if not done["wrote_rc"]:
                world.call_later(2.0, maybe_finish)

        world.call_later(10.0, maybe_finish)

        rc = job.send()
        _require(rc == 0, f"initial pod launch failed rc={rc}")
        waves = job.supervise_run(out=None, stale_after_s=3.0)
        for ranks, session in waves:
            world.record("wave", session=session,
                         dead=tuple(sorted(ranks)))
        _require(len(waves) >= 2,
                 f"expected >=2 relaunch waves, got {len(waves)}")
        _require(len(job.hosts) == hosts - 1,
                 "elastic resize never dropped the permanent host")
        _require(permanent not in job.hosts,
                 "the wrong host was dropped")
        return {"hosts": hosts, "waves": len(waves),
                "final_world": job.num_processes,
                "dropped": [permanent], "sleeps": world.sleeps}
    finally:
        if own:
            shutil.rmtree(workdir, ignore_errors=True)


# ---------------------------------------------------------------------
# differential-checkpoint GC races
# ---------------------------------------------------------------------

def gc_race(world, hosts=None, workdir=None):
    """Writers mirror differential steps (shared CAS chunk pool) into
    one in-memory store, interleaved with ``prune_remote`` and seeded
    transient store failures.  After every prune, every surviving
    ``COMPLETE`` step must be fully fetchable — marker, files and
    every referenced chunk present."""
    writers = 100 if hosts is None else int(hosts)
    steps = max(3 * writers, 60)
    keep = 5
    rng = world.rng
    own = workdir is None
    if own:
        import tempfile

        workdir = tempfile.mkdtemp(prefix="dk-sim-gc-")
    try:
        local = os.path.join(workdir, "local")
        cas_dir = os.path.join(local, "chunks")
        os.makedirs(cas_dir, exist_ok=True)
        # the shared CAS pool: a handful of chunks referenced by many
        # steps, so dedup skips + prunes genuinely contend
        pool = []
        for i in range(12):
            data = f"chunk-payload-{i}".encode() * 64
            sha = hashlib.sha256(data).hexdigest()
            with open(os.path.join(cas_dir, sha), "wb") as f:
                f.write(data)
            pool.append(sha)

        flaky = {"pending": 0, "tripped": 0}

        def gate(op, key):
            if flaky["pending"] > 0:
                flaky["pending"] -= 1
                flaky["tripped"] += 1
                return True
            return False

        store = _store.MemoryStore(fail=gate)

        def make_step(step, writer):
            path = os.path.join(local, f"step_{step:08d}")
            os.makedirs(path, exist_ok=True)
            refs = rng.sample(pool, 2)
            with open(os.path.join(path, "payload.bin"), "wb") as f:
                f.write(f"payload-{step}-{writer}".encode())
            with open(os.path.join(path, "chunks.json"), "w") as f:
                json.dump({"leaves": [
                    {"files": [f"chunks/{sha}" for sha in refs]}]}, f)
            return path

        def check_fetchable(tag):
            for step in _store.remote_steps(store):
                key = _store.step_key(step)
                marker = json.loads(store.get_bytes(
                    key + "/" + _store.COMPLETE_NAME).decode())
                for rel in marker["files"]:
                    _require(store.exists(key + "/" + rel),
                             f"{tag}: step {step} lost file {rel}")
                for sha in marker["chunks"]:
                    _require(
                        store.exists(_store.CHUNK_PREFIX + sha),
                        f"{tag}: step {step} lost chunk {sha[:12]}")

        pushed = pruned_total = 0
        next_step = 1
        while next_step <= steps:
            if rng.random() < 0.12 and pushed > keep:
                st = _store.prune_remote(store, keep)
                pruned_total += len(st["pruned_steps"])
                world.record("prune",
                             steps=tuple(st["pruned_steps"]),
                             swept=st["swept_chunks"])
                check_fetchable("post-prune")
                world.record("gc_check",
                             surviving=len(_store.remote_steps(store)))
            else:
                if rng.random() < 0.08:
                    # one transient refusal; every push op runs under
                    # the ckpt.push retry surface, so it is absorbed
                    # (prune's list calls are NOT retried — flaking
                    # those would test nothing this repo promises)
                    flaky["pending"] = 1
                writer = rng.randrange(writers)
                path = make_step(next_step, writer)
                st = _store.push_step(store, local, next_step, path)
                world.record("push", step=next_step, writer=writer,
                             skipped=bool(st["skipped"]))
                shutil.rmtree(path, ignore_errors=True)
                pushed += 1
                next_step += 1
            world.advance(0.01)
        final = _store.prune_remote(store, keep)
        pruned_total += len(final["pruned_steps"])
        check_fetchable("final")
        surviving = _store.remote_steps(store)
        _require(len(surviving) == keep,
                 f"retention horizon violated: {len(surviving)} "
                 f"steps survive, keep={keep}")
        # the newest survivor must round-trip through the real heal
        # path (chunk re-hash included)
        heal_dir = os.path.join(workdir, "heal")
        os.makedirs(heal_dir, exist_ok=True)
        stage = _store.fetch_step(store, heal_dir, surviving[-1])
        _require(os.path.isfile(os.path.join(stage, "payload.bin")),
                 "healed step is missing its payload")
        return {"writers": writers, "steps": steps,
                "pruned": pruned_total, "surviving": len(surviving),
                "flaky_ops": flaky["tripped"], "keep": keep}
    finally:
        if own:
            shutil.rmtree(workdir, ignore_errors=True)


# ---------------------------------------------------------------------
# serving-router failover under a load spike
# ---------------------------------------------------------------------

def router_failover(world, hosts=None, workdir=None):
    """The serving router's :class:`BackendPool` policy core driven on
    SIM time against modeled backends: a seeded load spike, one
    backend killed mid-spike (connect failures + heartbeat gone dark),
    evicted within the stale window, healed later and re-admitted
    after its hysteresis streak.  Every request is either placed on a
    live backend and eventually served, or typed-rejected — zero
    silent drops, zero placements on an evicted backend."""
    from dist_keras_tpu.serving.router import BackendPool

    hosts = 8 if hosts is None else max(3, int(hosts))
    rng = world.rng
    own = workdir is None
    if own:
        import tempfile

        workdir = tempfile.mkdtemp(prefix="dk-sim-router-")
    try:
        coord = os.path.join(workdir, "coord")
        hb = os.path.join(coord, "hb")
        os.makedirs(hb, exist_ok=True)
        addrs = [f"sim{r}:9000" for r in range(hosts)]
        probe_s, stale_s = 0.5, 2.0
        pool = BackendPool(addrs, fail_threshold=3, stale_s=stale_s,
                           readmit_checks=2, coord_dir=coord,
                           world_size=hosts)
        backends = {a: {"up": True, "depth": 0, "rank": r}
                    for r, a in enumerate(addrs)}
        serve_per_tick = 3   # per-backend service rate (reqs / tick)

        def _stamp(rank):
            path = os.path.join(hb, f"rank_{rank}")
            with open(path, "w") as f:
                f.write(repr(world.time()))
            t = world.time()
            os.utime(path, (t, t))

        for r in range(hosts):
            _stamp(r)

        def beat():  # sim-time heartbeats for every live backend
            for b in backends.values():
                if b["up"]:
                    _stamp(b["rank"])
            world.call_later(0.5, beat)

        world.call_later(0.5, beat)

        victim = addrs[rng.randrange(hosts)]
        t_kill, t_heal, t_end = 4.0, 12.0, 20.0
        tick = 0.1
        placed = completed = rejected = 0
        picked_dead_after_evict = 0
        kill_at = evict_after = readmit_at = None
        next_probe = 0.0

        while world.elapsed < t_end:
            now = world.elapsed
            if kill_at is None and now >= t_kill:
                backends[victim]["up"] = False
                kill_at = now
                world.record("kill", backend=victim)
            if (kill_at is not None and now >= t_heal
                    and not backends[victim]["up"]):
                backends[victim]["up"] = True
                world.record("heal", backend=victim)
            if now >= next_probe:  # the router's health-probe round
                for a, b in backends.items():
                    if b["up"]:
                        pool.note_probe(a, True, depth=b["depth"])
                    else:
                        pool.note_probe(a, False)
                pool.sweep()
                next_probe = now + probe_s
                snap = {s["addr"]: s for s in pool.snapshot()}
                if (evict_after is None and kill_at is not None
                        and not snap[victim]["live"]):
                    evict_after = now - kill_at
                    world.record(
                        "evicted", backend=victim,
                        reason=snap[victim]["evicted_reason"],
                        after_s=round(evict_after, 9))
                if (evict_after is not None and readmit_at is None
                        and now >= t_heal and snap[victim]["live"]):
                    readmit_at = now
                    world.record("readmitted", backend=victim,
                                 at_s=round(now, 9))
            # offered load: a spike window covering the kill instant
            spike = 2.0 <= now <= 9.0
            for _ in range(rng.randrange(8, 12) if spike
                           else rng.randrange(2, 5)):
                excluded = set()
                for _attempt in range(2):  # router: 1 sibling retry
                    a = pool.pick(exclude=excluded)
                    if a is None:
                        rejected += 1  # typed 503: no live backend
                        break
                    if evict_after is not None and a == victim \
                            and not backends[a]["up"]:
                        picked_dead_after_evict += 1
                    if backends[a]["up"]:
                        backends[a]["depth"] += 1
                        pool.note_forward(a, True)
                        placed += 1
                        break
                    # connect failure: evidence + sibling retry —
                    # exactly RouterServer.forward's policy
                    pool.note_forward(a, False)
                    excluded.add(a)
                else:
                    rejected += 1  # both attempts burned: typed 503
            for b in backends.values():  # backends serve their queues
                if b["up"] and b["depth"]:
                    served = min(b["depth"], serve_per_tick)
                    b["depth"] -= served
                    completed += served
            world.advance(tick)

        # drain: every placed request must complete (no silent loss)
        for _ in range(200):
            residual = sum(b["depth"] for b in backends.values())
            if not residual:
                break
            for b in backends.values():
                if b["up"] and b["depth"]:
                    served = min(b["depth"], serve_per_tick)
                    b["depth"] -= served
                    completed += served
            world.advance(tick)

        _require(evict_after is not None,
                 "the killed backend was never evicted")
        _require(evict_after <= stale_s + 2 * probe_s + 1e-9,
                 f"eviction took {evict_after:.2f}s — outside the "
                 f"stale window {stale_s}s + probe slack")
        _require(readmit_at is not None,
                 "the healed backend was never re-admitted")
        _require(picked_dead_after_evict == 0,
                 f"{picked_dead_after_evict} requests were routed to "
                 "an evicted backend")
        _require(completed == placed,
                 f"dropped requests: placed {placed} != completed "
                 f"{completed}")
        _require(rejected < placed,
                 f"rejected {rejected} >= placed {placed} — the pool "
                 "shed more than it served")
        _require(pool.evictions >= 1 and pool.readmissions >= 1,
                 "pool counters missed the evict/readmit cycle")
        return {"hosts": hosts, "victim": victim,
                "evict_after_s": round(evict_after, 6),
                "readmit_at_s": round(readmit_at, 6),
                "placed": placed, "completed": completed,
                "rejected": rejected,
                "evictions": pool.evictions,
                "readmissions": pool.readmissions,
                "sleeps": world.sleeps}
    finally:
        if own:
            shutil.rmtree(workdir, ignore_errors=True)


def router_decode_spike(world, hosts=None, workdir=None):
    """Router failover under a spike of LONG-RUNNING decode sequences
    (the ROADMAP-flagged scenario): the :class:`BackendPool` policy
    core on sim time over modeled decode backends, each owning a real
    :class:`~dist_keras_tpu.serving.kv_cache.PagedKVCache` and a fixed
    slot set.  Sequences hold pages for their whole multi-tick
    lifetime, so the spike exhausts KV and the router's
    sibling-on-503 policy spreads ``kv_exhausted`` rejections across
    hosts; one backend dies mid-spike with sequences in flight.
    Invariants: eviction inside the stale window, re-admission after
    heal, zero placements on an evicted backend, every admitted
    sequence either completes or is attributed to the host death
    (nothing silently dropped), and every surviving backend's page
    accounting balances to zero at the end."""
    from dist_keras_tpu.serving.kv_cache import (
        PagedKVCache,
        PagesExhausted,
    )
    from dist_keras_tpu.serving.router import BackendPool

    hosts = 6 if hosts is None else max(3, int(hosts))
    rng = world.rng
    own = workdir is None
    if own:
        import tempfile

        workdir = tempfile.mkdtemp(prefix="dk-sim-decode-")
    try:
        coord = os.path.join(workdir, "coord")
        hb = os.path.join(coord, "hb")
        os.makedirs(hb, exist_ok=True)
        addrs = [f"sim{r}:9000" for r in range(hosts)]
        probe_s, stale_s = 0.5, 2.0
        pool = BackendPool(addrs, fail_threshold=3, stale_s=stale_s,
                           readmit_checks=2, coord_dir=coord,
                           world_size=hosts)
        page_size, num_pages, slots = 4, 24, 6

        def fresh_backend(rank):
            return {"up": True, "rank": rank,
                    "cache": PagedKVCache(num_pages, page_size),
                    "active": {}}  # seq id -> remaining decode ticks

        backends = {a: fresh_backend(r) for r, a in enumerate(addrs)}
        seq_ids = itertools.count()

        def _stamp(rank):
            path = os.path.join(hb, f"rank_{rank}")
            with open(path, "w") as f:
                f.write(repr(world.time()))
            t = world.time()
            os.utime(path, (t, t))

        for r in range(hosts):
            _stamp(r)

        def beat():
            for b in backends.values():
                if b["up"]:
                    _stamp(b["rank"])
            world.call_later(0.5, beat)

        world.call_later(0.5, beat)

        victim = addrs[rng.randrange(hosts)]
        t_kill, t_heal, t_end = 4.0, 12.0, 20.0
        tick = 0.1
        placed = completed = rejected = 0
        kv_rejections = lost_on_kill = 0
        picked_dead_after_evict = 0
        kill_at = evict_after = readmit_at = None
        next_probe = 0.0

        def admit(b):
            """One decode admission against a modeled backend — the
            DecodeEngine door: slots then worst-case page reservation,
            typed refusal otherwise (the router sees a 503 and moves
            to a sibling, exactly ``forward``'s policy)."""
            if len(b["active"]) >= slots:
                raise PagesExhausted(0, 0, num_pages)
            plen = rng.randrange(2, 9)
            max_new = rng.randrange(10, 31)
            sid = next(seq_ids)
            b["cache"].alloc(sid, plen + max_new)  # may raise
            b["active"][sid] = max_new
            return sid

        while world.elapsed < t_end:
            now = world.elapsed
            if kill_at is None and now >= t_kill:
                b = backends[victim]
                b["up"] = False
                # the host died with sequences in flight: they are
                # LOST TO THE HOST (attributed, not silent) and its
                # restart comes back with a fresh pool
                lost_on_kill = len(b["active"])
                b["active"] = {}
                b["cache"] = PagedKVCache(num_pages, page_size)
                kill_at = now
                world.record("kill", backend=victim,
                             lost=lost_on_kill)
            if (kill_at is not None and now >= t_heal
                    and not backends[victim]["up"]):
                backends[victim]["up"] = True
                world.record("heal", backend=victim)
            if now >= next_probe:
                for a, b in backends.items():
                    if b["up"]:
                        pool.note_probe(a, True,
                                        depth=len(b["active"]))
                    else:
                        pool.note_probe(a, False)
                pool.sweep()
                next_probe = now + probe_s
                snap = {s["addr"]: s for s in pool.snapshot()}
                if (evict_after is None and kill_at is not None
                        and not snap[victim]["live"]):
                    evict_after = now - kill_at
                    world.record(
                        "evicted", backend=victim,
                        reason=snap[victim]["evicted_reason"],
                        after_s=round(evict_after, 9))
                if (evict_after is not None and readmit_at is None
                        and now >= t_heal and snap[victim]["live"]):
                    readmit_at = now
                    world.record("readmitted", backend=victim,
                                 at_s=round(now, 9))
            # offered load: long-running generations, spiking over the
            # kill instant — each holds pages for its whole lifetime
            spike = 2.0 <= now <= 9.0
            for _ in range(rng.randrange(3, 6) if spike
                           else rng.randrange(0, 2)):
                excluded = set()
                for _attempt in range(2):  # router: 1 sibling retry
                    a = pool.pick(exclude=excluded)
                    if a is None:
                        rejected += 1
                        break
                    if evict_after is not None and a == victim \
                            and not backends[a]["up"]:
                        picked_dead_after_evict += 1
                    b = backends[a]
                    if b["up"]:
                        try:
                            admit(b)
                        except PagesExhausted:
                            # backend answered a typed 503
                            # kv_exhausted: reachable, but this
                            # REQUEST moves to a sibling
                            kv_rejections += 1
                            pool.note_forward(a, True)
                            excluded.add(a)
                            continue
                        pool.note_forward(a, True)
                        placed += 1
                        break
                    pool.note_forward(a, False)
                    excluded.add(a)
                else:
                    rejected += 1
            # continuous batching: every active sequence on a live
            # backend decodes one token per tick; completions free
            # their pages the same tick
            for b in backends.values():
                if not b["up"]:
                    continue
                done = []
                for sid in b["active"]:
                    b["active"][sid] -= 1
                    if b["active"][sid] <= 0:
                        done.append(sid)
                for sid in done:
                    del b["active"][sid]
                    b["cache"].free(sid)
                    completed += 1
            world.advance(tick)

        # drain: every still-active sequence decodes to completion
        for _ in range(400):
            if not any(b["active"] for b in backends.values()
                       if b["up"]):
                break
            for b in backends.values():
                if not b["up"]:
                    continue
                done = []
                for sid in b["active"]:
                    b["active"][sid] -= 1
                    if b["active"][sid] <= 0:
                        done.append(sid)
                for sid in done:
                    del b["active"][sid]
                    b["cache"].free(sid)
                    completed += 1
            world.advance(tick)

        _require(evict_after is not None,
                 "the killed backend was never evicted")
        _require(evict_after <= stale_s + 2 * probe_s + 1e-9,
                 f"eviction took {evict_after:.2f}s — outside the "
                 f"stale window {stale_s}s + probe slack")
        _require(readmit_at is not None,
                 "the healed backend was never re-admitted")
        _require(picked_dead_after_evict == 0,
                 f"{picked_dead_after_evict} requests were routed to "
                 "an evicted backend")
        _require(completed + lost_on_kill == placed,
                 f"silently dropped sequences: completed {completed} "
                 f"+ lost {lost_on_kill} != placed {placed}")
        _require(kv_rejections > 0,
                 "the spike never exhausted a KV pool — the scenario "
                 "is not exercising paged admission")
        for a, b in backends.items():
            b["cache"].assert_balanced()
            _require(b["cache"].used_pages() == 0,
                     f"{a} leaked {b['cache'].used_pages()} KV pages")
        return {"hosts": hosts, "victim": victim,
                "evict_after_s": round(evict_after, 6),
                "readmit_at_s": round(readmit_at, 6),
                "placed": placed, "completed": completed,
                "lost_on_kill": lost_on_kill,
                "rejected": rejected,
                "kv_rejections": kv_rejections,
                "evictions": pool.evictions,
                "readmissions": pool.readmissions,
                "sleeps": world.sleeps}
    finally:
        if own:
            shutil.rmtree(workdir, ignore_errors=True)


def decode_replica_churn(world, hosts=None, workdir=None):
    """Sequence-level decode survivability under replica churn, on sim
    time: one modeled :class:`DecodeEngine` with several replicas —
    each owning a real :class:`~dist_keras_tpu.serving.kv_cache
    .PagedKVCache` and a fixed slot set — decoding deterministic token
    streams while two kill/heal cycles churn the replica set.  A
    killed replica's in-flight sequences are NOT lost: their pages
    free on the dead cache and each sequence re-admits onto a
    survivor, teacher-forced-replaying its held prefix (no emission
    during catch-up) before resuming its stream exactly where it
    stopped.  Invariants: every placed sequence completes (zero lost
    — the whole point of recovery), every completed stream is
    bit-identical to the undisturbed oracle stream, at least one
    recovery actually happened in each churn cycle, orphans waiting
    for survivor capacity all drain, and every cache balances to zero
    pages at the end.  Pure seeded math over real allocators, so two
    runs with the same seed produce bit-identical stream digests —
    that equality is the replay row the CI gate enforces."""
    from dist_keras_tpu.serving.kv_cache import PagedKVCache

    hosts = 3 if hosts is None else max(2, int(hosts))
    rng = world.rng
    page_size, num_pages, slots = 4, 24, 6
    replay_rate = 8          # teacher-forced catch-up positions/tick
    tick = 0.1
    churn = [(3.0, 8.0), (10.0, 15.0)]   # (kill, heal) cycles
    t_end = 18.0

    def tok(sid, i):
        """The deterministic 'model': next token is a pure function
        of (sequence, position) — the sim's stand-in for greedy
        argmax, so replay correctness is exactly stream equality."""
        return (sid * 31 + i * 7 + 3) % 97

    def fresh_replica(idx):
        return {"idx": idx, "up": True,
                "cache": PagedKVCache(num_pages, page_size),
                "active": {}}   # sid -> seq state dict

    replicas = [fresh_replica(i) for i in range(hosts)]
    seq_ids = itertools.count()
    pending = []             # orphans waiting for survivor capacity
    streams = {}             # sid -> completed token list
    placed = completed = rejected = recoveries = 0
    catchup_ticks = 0        # the recovery latency tax, in ticks
    cycle_recoveries = []

    def place(seq):
        """Admit onto the most-free live replica — the engine's
        worst-case page reservation at the door; None if no survivor
        has room (the orphan waits, it is never dropped)."""
        live = [r for r in replicas if r["up"]
                and len(r["active"]) < slots]
        live.sort(key=lambda r: r["cache"].used_pages())
        for r in live:
            need = r["cache"].pages_for(seq["plen"] + seq["max_new"])
            if num_pages - r["cache"].used_pages() >= need:
                r["cache"].alloc(seq["sid"],
                                 seq["plen"] + seq["max_new"])
                r["active"][seq["sid"]] = seq
                return r
        return None

    ki = 0
    while world.elapsed < t_end:
        now = world.elapsed
        if ki < len(churn) and now >= churn[ki][0] \
                and replicas[ki % hosts]["up"]:
            # kill: quarantine the replica, free its pages, re-admit
            # every in-flight sequence onto survivors (teacher-forced
            # replay of the whole held prefix)
            victim = replicas[ki % hosts]
            victim["up"] = False
            orphans = list(victim["active"].values())
            victim["active"] = {}
            victim["cache"] = PagedKVCache(num_pages, page_size)
            n_rec = 0
            for seq in orphans:
                seq["catchup"] = seq["plen"] + len(seq["emitted"])
                if place(seq) is None:
                    pending.append(seq)
                n_rec += 1
            recoveries += n_rec
            cycle_recoveries.append(n_rec)
            world.record("decode_kill", replica=victim["idx"],
                         orphans=n_rec)
        if ki < len(churn) and now >= churn[ki][1] \
                and not replicas[ki % hosts]["up"]:
            replicas[ki % hosts]["up"] = True
            world.record("decode_heal", replica=ki % hosts)
            ki += 1
        # orphans first (requeue priority), then fresh offered load
        still = []
        for seq in pending:
            if place(seq) is None:
                still.append(seq)
        pending[:] = still
        for _ in range(rng.randrange(0, 3)):
            sid = next(seq_ids)
            seq = {"sid": sid, "plen": rng.randrange(2, 9),
                   "max_new": rng.randrange(5, 21),
                   "emitted": [], "catchup": 0}
            if place(seq) is None:
                rejected += 1     # typed Overloaded at the door
            else:
                placed += 1
        # continuous batching: replaying sequences burn catch-up
        # positions (emitting nothing), caught-up ones emit one token
        for r in replicas:
            if not r["up"]:
                continue
            done = []
            for sid, seq in r["active"].items():
                if seq["catchup"] > 0:
                    seq["catchup"] -= min(seq["catchup"], replay_rate)
                    catchup_ticks += 1
                    continue
                seq["emitted"].append(tok(sid, len(seq["emitted"])))
                if len(seq["emitted"]) >= seq["max_new"]:
                    done.append(sid)
            for sid in done:
                seq = r["active"].pop(sid)
                r["cache"].free(sid)
                streams[sid] = seq["emitted"]
                completed += 1
        world.advance(tick)

    # drain: no new load; pending orphans re-place as slots free
    for _ in range(600):
        if not pending and not any(r["active"] for r in replicas
                                   if r["up"]):
            break
        still = []
        for seq in pending:
            if place(seq) is None:
                still.append(seq)
        pending[:] = still
        for r in replicas:
            if not r["up"]:
                continue
            done = []
            for sid, seq in r["active"].items():
                if seq["catchup"] > 0:
                    seq["catchup"] -= min(seq["catchup"], replay_rate)
                    catchup_ticks += 1
                    continue
                seq["emitted"].append(tok(sid, len(seq["emitted"])))
                if len(seq["emitted"]) >= seq["max_new"]:
                    done.append(sid)
            for sid in done:
                seq = r["active"].pop(sid)
                r["cache"].free(sid)
                streams[sid] = seq["emitted"]
                completed += 1
        world.advance(tick)

    _require(not pending, f"{len(pending)} orphans never re-placed")
    _require(completed == placed,
             f"lost sequences: completed {completed} != placed "
             f"{placed} — recovery dropped work")
    _require(recoveries > 0 and len(cycle_recoveries) == len(churn),
             "no churn cycle actually recovered sequences")
    _require(all(n > 0 for n in cycle_recoveries),
             f"a kill caught zero in-flight sequences "
             f"{cycle_recoveries} — the scenario is not exercising "
             f"recovery")
    for sid, emitted in streams.items():
        oracle = [tok(sid, i) for i in range(len(emitted))]
        _require(emitted == oracle,
                 f"seq {sid} stream diverged from the oracle after "
                 f"recovery")
    for r in replicas:
        r["cache"].assert_balanced()
        _require(r["cache"].used_pages() == 0,
                 f"replica {r['idx']} leaked "
                 f"{r['cache'].used_pages()} KV pages")
    digest = hashlib.sha256(json.dumps(
        sorted(streams.items()), separators=(",", ":")
    ).encode()).hexdigest()
    world.record("decode_digest", sha256=digest[:16])
    return {"hosts": hosts, "placed": placed, "completed": completed,
            "rejected": rejected, "recoveries": recoveries,
            "cycle_recoveries": cycle_recoveries,
            "catchup_ticks": catchup_ticks,
            "stream_digest": digest, "sleeps": world.sleeps}


def slo_burn(world, hosts=None, workdir=None):
    """The SLO plane's multi-window burn-rate math driven on SIM time:
    seeded modeled serving traffic with a mid-run error window.  The
    page must fire INSIDE the fault window and escalate to the FAST
    class (5 m AND 1 h both >= 14.4x) while the errors still flow,
    hold through the slow page's sustained-burn condition after they
    stop, and clear exactly once — when the growing covered span
    dilutes the hour-class burn below 6x.  (The burst is kept short —
    90 s at 20% — so the whole arc fits inside the simulator's default
    3600 s horizon.)  Transition-only: one fire, one clear.  Pure
    ring-time math over a private registry, so two runs with the same
    seed produce bit-identical digests."""
    from dist_keras_tpu.observability import slo

    hosts = 8 if hosts is None else max(1, int(hosts))
    rng = world.rng
    tick = 10.0
    t_fault0, t_fault1, t_end = 600.0, 690.0, 3400.0
    err_frac = 0.2

    counts = {"good": 0, "total": 0}
    reg = slo.Registry()
    reg.register(slo.Objective(
        "serve_availability", 0.999,
        lambda: (counts["good"], counts["total"]),
        description="sim: modeled serving traffic"))
    rule = slo.SLOBurnRate(registry=reg)

    fires = clears = 0
    fired_at = cleared_at = fast_at = None
    fire_page = fire_objective = None
    was_firing = False
    while world.elapsed < t_end:
        now = world.elapsed
        in_fault = t_fault0 <= now < t_fault1
        n = rng.randrange(4 * hosts, 6 * hosts + 1)
        bad = (sum(1 for _ in range(n) if rng.random() < err_frac)
               if in_fault else 0)
        counts["total"] += n
        counts["good"] += n - bad
        firing, fields = rule.evaluate(now)
        if firing and not was_firing:
            fires += 1
            fired_at = now
            fire_page = fields["page"]
            fire_objective = fields["objective"]
            world.record("slo_fire", t_s=round(now, 6),
                         objective=fields["objective"],
                         page=fields["page"],
                         burn_5m=fields["burn_5m"],
                         burn_1h=fields["burn_1h"])
        elif was_firing and not firing:
            clears += 1
            cleared_at = now
            world.record("slo_clear", t_s=round(now, 6))
        if firing and fast_at is None and fields["page"] == "fast":
            fast_at = now  # the slow page's cold-start head start ends
            world.record("slo_fast", t_s=round(now, 6))
        was_firing = firing
        world.advance(tick)

    _require(fires == 1,
             f"expected exactly one fire transition, got {fires}")
    _require(clears == 1,
             f"expected exactly one clear transition, got {clears}")
    _require(t_fault0 <= fired_at <= t_fault1,
             f"page fired at +{fired_at:.0f}s — outside the fault "
             f"window [{t_fault0:.0f}, {t_fault1:.0f}]s")
    _require(fire_objective == "serve_availability",
             f"alert named {fire_objective!r}")
    # cold start: the partial 1h/6h windows degrade to the covered
    # span, so the SLOW page may trip first — but a hard burn must
    # escalate to the fast page while the fault is still live
    _require(fast_at is not None and fast_at <= t_fault1,
             f"the fast page never tripped inside the fault window "
             f"(fast_at={fast_at})")
    _require(cleared_at > t_fault1,
             f"cleared at +{cleared_at:.0f}s, inside the fault")
    # the slow page holds until the covered span dilutes the burst
    # (~18s of bad traffic) below 6x the 0.1% budget: t ~ 3000s
    _require(cleared_at <= t_fault1 + 3600.0 + tick,
             f"clear took until +{cleared_at:.0f}s — more than one "
             f"1h window past the fault end")
    _require(not reg.breaching(),
             f"still breaching at the end: {reg.breaching()}")
    return {"hosts": hosts,
            "fired_at_s": round(fired_at, 6),
            "fast_at_s": round(fast_at, 6),
            "cleared_at_s": round(cleared_at, 6),
            "page": fire_page, "objective": fire_objective,
            "requests": counts["total"],
            "errors": counts["total"] - counts["good"],
            "sleeps": world.sleeps}


SCENARIOS = {
    "ps_churn": ps_churn,
    "partition_heal": partition_heal,
    "preemption_storm": preemption_storm,
    "relaunch_waves": relaunch_waves,
    "gc_race": gc_race,
    "router_failover": router_failover,
    "router_decode_spike": router_decode_spike,
    "decode_replica_churn": decode_replica_churn,
    "slo_burn": slo_burn,
}
