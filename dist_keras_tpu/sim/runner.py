"""Scenario runner — install the sim world, run the script, verdict.

Separated from ``__init__`` so the CLI, the gate and tests share one
entry point without importing the argparse layer.
"""

from __future__ import annotations

from dist_keras_tpu.observability import events
from dist_keras_tpu.resilience import world as _world
from dist_keras_tpu.sim.scenarios import SCENARIOS
from dist_keras_tpu.sim.world import SimWorld
from dist_keras_tpu.utils import knobs


def run_scenario(name, seed=None, hosts=None, time_limit_s=None,
                 workdir=None):
    """Run one named scenario under a fresh :class:`SimWorld`;
    -> result dict (scenario, seed, digest, trace_len, sim_elapsed_s
    + the scenario's own fields).  Raises
    :class:`~dist_keras_tpu.sim.scenarios.ScenarioFailed` on a
    violated invariant and
    :class:`~dist_keras_tpu.sim.world.SimTimeLimitExceeded` on a
    would-be hang — never returns a half-verdict.

    Defaults resolve the ``DK_SIM_*`` knobs, so the launcher-exported
    configuration governs here like everywhere else.
    """
    try:
        fn = SCENARIOS[name]
    except KeyError:
        raise ValueError(
            f"unknown scenario {name!r}; valid: "
            + ", ".join(sorted(SCENARIOS)))
    seed = int(knobs.get("DK_SIM_SEED") if seed is None else seed)
    if time_limit_s is None:
        time_limit_s = knobs.get("DK_SIM_TIME_LIMIT_S")
    world = SimWorld(seed=seed, time_limit_s=time_limit_s)
    events.emit("sim_scenario_begin", scenario=name, seed=seed,
                hosts=hosts)
    with _world.use(world):
        result = fn(world, hosts=hosts, workdir=workdir)
    result = dict(result)
    result.update({
        "scenario": name,
        "seed": seed,
        "sim_elapsed_s": round(world.elapsed, 6),
        "trace_len": len(world.trace),
        "digest": world.digest(),
    })
    events.emit("sim_scenario_end", scenario=name, seed=seed,
                digest=result["digest"],
                sim_elapsed_s=result["sim_elapsed_s"],
                trace_len=result["trace_len"])
    return result
