"""Deterministic cluster simulator (round 20).

A thousand-host chaos scenario costs a thousand hosts — unless the
runtime's environment seams (clock, sleep, transport, process
spawn/kill, disk) are injectable.  Round 19's components already take
``clock=``/``sleep=`` in places; this package closes the loop: a
:class:`~dist_keras_tpu.sim.world.SimWorld` installs itself behind the
:mod:`~dist_keras_tpu.resilience.world` seam and the REAL components —
retry policies, supervisors, the PS center variable, ``launch.Job``'s
relaunch waves, the remote checkpoint store — run at the speed of
arithmetic under a seeded scheduler, with every run replayable
bit-for-bit from its seed.

Entry points:

- ``python -m dist_keras_tpu.sim --scenario ps_churn --hosts 1000``
  runs one scenario and prints a JSON verdict as its last stdout line.
- :func:`run_scenario` is the library surface the CLI, the CI gate
  (``tools/gates.py --sim-only``) and the benchmark's ``sim_swarm``
  row all share.

Scenario scripts live in :mod:`~dist_keras_tpu.sim.scenarios`; the
simulated clock/scheduler in :mod:`~dist_keras_tpu.sim.world`.
"""

from dist_keras_tpu.sim.runner import run_scenario
from dist_keras_tpu.sim.scenarios import SCENARIOS, ScenarioFailed
from dist_keras_tpu.sim.world import (SIM_EPOCH, SimTimeLimitExceeded,
                                      SimWorld)

__all__ = [
    "SIM_EPOCH", "SimWorld", "SimTimeLimitExceeded",
    "SCENARIOS", "ScenarioFailed", "run_scenario",
]
